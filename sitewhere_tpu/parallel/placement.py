"""Elastic tenant placement: versioned ownership + epoch-fenced handoff.

Ownership before this module was the static partitioner
``owner_rank(token, n_ranks)`` (parallel/cluster.py) — Kafka partition
semantics, where the only topology change is the OFFLINE path (drain
every rank, ``migrate_cluster_snapshots``, restart). A production fleet
adds and drains hosts under live traffic (ROADMAP item 3; SURVEY §5.4's
consumer-group rebalancing). This module is that capability, built from
pieces the repo already trusts: WAL replay for catch-up (PR 6), the
forward queue's spill/redelivery discipline for in-flight re-routing
(PR 6/9), and the conservation ledger to prove nothing was lost (PR 13).

The model
---------

* The cluster's PROVISIONED rank set (``ClusterConfig.peers``) is fixed
  — addresses are known up front, exactly like a stateful set's
  ordinals. Elasticity is which provisioned ranks are ACTIVE (own
  slots), and that is the placement map's job. Event-id tagging
  (``local * n_ranks + rank``) therefore never changes shape.
* Tokens hash into a FIXED slot space: ``slot = owner_rank(token,
  n_slots)`` with ``n_slots = n_ranks * slots_per_rank`` chosen at
  cluster genesis (Redis-Cluster-style hash slots). The INITIAL map
  assigns ``slot -> slot % n_ranks``, which — because ``n_ranks``
  divides ``n_slots`` — is byte-identical to the legacy
  ``owner_rank(token, n_ranks)`` partitioner: adopting the placement
  plane re-routes nothing.
* A :class:`PlacementMap` is immutable and EPOCH-numbered. Every
  ownership read (facade routing, forward partitioning, owner-side
  guards, scheduler fire-over, replica-ring derivation) resolves
  through the rank's installed map, so all surfaces agree on one epoch
  at any instant (pinned by tests/test_placement.py). A rank never
  adopts a lower epoch.

The handoff protocol (one move = one source rank, >= 1 slots, one
target rank; coordinated from any rank)
---------------------------------------

1. **catch-up** — the target first builds a CONTENT FILTER from its
   own WAL (``handoff_prepare``: the multiset of moving-slot records it
   already holds — so a range returning to a former owner, or a retried
   move whose earlier attempt partially applied, never re-ingests what
   is already there). The source then replays its WAL records whose
   token hashes into a moving slot straight into the target's LIVE
   engine (``Placement.handoffApply``: decode + WAL + dedup happen at
   the target, in its own interner space — the route-then-decode rule).
   Shipments carry position-deterministic forward ids, so a
   killed-and-retried pass is suppressed by the target's SpillRegistry,
   never re-applied.
   Repeated passes ship only the delta (the cursor is "matching records
   shipped so far"; WAL order is append-only and stable). A PRUNED
   source WAL is refused loudly BEFORE anything ships — pruned history
   lives in snapshots/archives, which is the offline
   (``cluster_reshard``) path's job.
2. **fence** — the source, under its engine lock, fences the moving
   slots: ingest for them now fails with a typed ``code=473`` redirect
   (never applied, never lost — the sender's ForwardQueue spills and
   re-routes; the facade's own payloads briefly wait on the fence).
   The WAL tail since the catch-up cursor then ships, and the target
   VERIFIES the applied watermark (every shipped forward id recorded)
   before the fence round returns.
3. **commit** — the coordinator installs ``map.with_moves(...)`` (epoch
   + 1) locally and broadcasts it (tolerant: a down rank adopts later
   from any redirect, which carries the replier's map). The commit
   install at the SOURCE is itself the completion: it drops the fences
   for the moved-away slots and closes the move, so a lost
   ``handoffFinish`` leaves nothing dangling. A coordinator dead BEFORE
   commit is covered by the fence deadline (an expired fence aborts the
   move — the map never changed, the source still owns, nothing was
   acked and lost), and the fence round itself re-verifies + re-arms
   its fences after the tail ship so an expiry mid-ship can never
   commit.

Crash matrix (chaos-gated in tests/test_placement.py and the bench
placement leg): source killed -> coordinator aborts, map unchanged,
target's partial copy is invisible (reads filter to owned slots);
target killed -> catch-up RPC fails, abort, source still sole owner;
coordinator killed pre-commit -> the fence deadline unfences the
source; coordinator killed post-partial-broadcast -> stale ranks
converge via redirect-with-map (the higher epoch always wins).

Known limits (documented, deliberate): a move re-ingests WAL history at
the target, so moved events get new rank-scoped ids and a fresh
``received_ms`` (event-time columns are payload-carried and survive
exactly — the offline reshard has the same contract); the source keeps
its dead rows (filtered from every read) until the operator compacts;
assignments created through the admin path are not WAL-carried and do
not migrate (the offline path, or re-creation, covers them); the
residual duplicate window of PR 6 (owner applied + recorded, reply
lost, redelivery lands post-move at the target) is closed by the
engine-level alternate-id dedup exactly as before.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib
import threading
import time

logger = logging.getLogger(__name__)

# ownership redirect: the typed "not mine" reject (HTTP has no exact
# analog; 473 sits in the 4xx "caller must re-route" family). The error
# frame's data payload carries the replier's placement map so a stale
# sender converges in one hop.
REDIRECT_CODE = 473

DEFAULT_SLOTS_PER_RANK = 8


def _slot_of(token: str, n_slots: int) -> int:
    from sitewhere_tpu.parallel.cluster import owner_rank

    return owner_rank(token, n_slots)


def slot_for_token(token: str, n_shards: int,
                   slots_per_rank: int = DEFAULT_SLOTS_PER_RANK) -> int:
    """The placement SLOT a token hashes into in an SPMD store's slot
    space (``n_slots = n_shards * slots_per_rank``) — the identity the
    shard heat plane (ISSUE 18) attributes routed rows to, and the unit
    ``decide_balance`` moves."""
    return _slot_of(token, n_shards * slots_per_rank)


def shard_for_token(token: str, n_shards: int,
                    slots_per_rank: int = DEFAULT_SLOTS_PER_RANK) -> int:
    """THE slot -> shard map of the SPMD store (ISSUE 16): tokens hash
    into the same fixed slot space as cluster placement (``n_slots =
    n_shards * slots_per_rank``) and shards take the genesis assignment
    ``slot % n_shards``. Because ``n_shards`` divides ``n_slots`` this
    is byte-identical to the legacy ``owner_rank(token, n_shards)``
    partitioner — a token lands on the same index whether "index" means
    a cluster rank or an SPMD mesh shard, so placement tooling and the
    conservation ledger carry over unmodified."""
    return slot_for_token(token, n_shards, slots_per_rank) % n_shards


@dataclasses.dataclass(frozen=True)
class PlacementMap:
    """Immutable, epoch-numbered slot->rank directory. ``n_slots`` is
    fixed at cluster genesis; elasticity is re-assigning slots, never
    re-hashing tokens."""

    epoch: int
    n_slots: int
    assignment: tuple[int, ...]

    @staticmethod
    def initial(n_ranks: int,
                slots_per_rank: int = DEFAULT_SLOTS_PER_RANK,
                active_ranks: "list[int] | None" = None) -> "PlacementMap":
        """The genesis map. With every provisioned rank active the
        assignment is ``slot -> slot % n_ranks`` — byte-identical to the
        legacy ``owner_rank(token, n_ranks)`` partitioner (``n_ranks``
        divides ``n_slots``). With ``active_ranks`` a strict subset
        (ranks provisioned for a later join), slots round-robin over the
        active set only."""
        n_slots = n_ranks * max(1, int(slots_per_rank))
        if active_ranks is None:
            assign = tuple(s % n_ranks for s in range(n_slots))
        else:
            act = sorted(set(int(r) for r in active_ranks))
            if not act or any(r < 0 or r >= n_ranks for r in act):
                raise ValueError(
                    f"active_ranks {active_ranks} outside provisioned "
                    f"range [0, {n_ranks})")
            assign = tuple(act[s % len(act)] for s in range(n_slots))
        return PlacementMap(epoch=1, n_slots=n_slots, assignment=assign)

    def slot_of(self, token: str) -> int:
        return _slot_of(token, self.n_slots)

    def owner_of_slot(self, slot: int) -> int:
        return self.assignment[slot]

    def owner(self, token: str) -> int:
        return self.assignment[self.slot_of(token)]

    def active_ranks(self) -> list[int]:
        return sorted(set(self.assignment))

    def slots_of(self, rank: int) -> list[int]:
        return [s for s, r in enumerate(self.assignment) if r == rank]

    def with_moves(self, moves: dict[int, int]) -> "PlacementMap":
        """The next epoch with ``{slot: new_rank}`` applied."""
        assign = list(self.assignment)
        for slot, rank in moves.items():
            if not (0 <= int(slot) < self.n_slots):
                raise ValueError(f"slot {slot} outside [0, {self.n_slots})")
            assign[int(slot)] = int(rank)
        return PlacementMap(epoch=self.epoch + 1, n_slots=self.n_slots,
                            assignment=tuple(assign))

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "nSlots": self.n_slots,
                "assignment": list(self.assignment)}

    @staticmethod
    def from_dict(d: dict) -> "PlacementMap":
        assign = tuple(int(r) for r in d["assignment"])
        n_slots = int(d["nSlots"])
        if len(assign) != n_slots:
            raise ValueError(
                f"placement assignment length {len(assign)} != nSlots "
                f"{n_slots}")
        return PlacementMap(epoch=int(d["epoch"]), n_slots=n_slots,
                            assignment=assign)


@dataclasses.dataclass
class _Move:
    """Source-side state of one in-flight handoff."""

    move_id: str
    slots: tuple[int, ...]
    target: int
    state: str = "catchup"          # catchup | fenced | done | aborted
    shipped_records: int = 0        # WAL-record cursor (matching records)
    shipped_batches: int = 0
    shipped_payloads: int = 0
    fids: list = dataclasses.field(default_factory=list)
    started_mono: float = dataclasses.field(default_factory=time.monotonic)
    fence_deadline: float | None = None


class PlacementManager:
    """One per rank: the installed map, the rank's fences, the
    source-side handoff machinery, and the counters every surface
    (metrics, conservation, debug bundle) reads. Attached to both the
    ClusterEngine facade and its local engine (the forward_queue
    pattern), so cluster RPC handlers — which bind to the engine —
    reach it."""

    def __init__(self, cluster, pmap: PlacementMap,
                 directory: "str | pathlib.Path | None" = None,
                 fence_timeout_s: float = 20.0,
                 move_timeout_s: float = 120.0):
        self.cluster = cluster
        self.dir = pathlib.Path(directory) if directory else None
        self.fence_timeout_s = float(fence_timeout_s)
        self.move_timeout_s = float(move_timeout_s)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._map = pmap
        self._cache_map_views(pmap)
        # target-side content filters of in-flight handoffs, keyed by
        # move id: a Counter of (kind, tenant, payload-digest) this rank
        # ALREADY holds for the moving slots (built by handoff_prepare
        # from its OWN WAL). The apply path consumes it so a replay
        # never re-ingests what a former ownership era (or an aborted
        # earlier attempt) already applied — the no-dual-apply half of
        # the protocol for RETURNING ranges.
        self._prepared: dict[str, dict] = {}
        # lock-free fast-path flag the facade reads per ingest batch:
        # True only while >= 1 slot is fenced here (rare, short)
        self.has_fences = False
        # in-flight ingest gate: every owner-side ingest (facade local
        # sub-batch, cluster RPC ingest handlers) holds it from its
        # fence/guard check through its engine apply. The fence step
        # registers fences FIRST, then waits for the gate to drain, so
        # a batch that checked pre-fence has finished its WAL append
        # before the tail extents are captured — without this, a racing
        # batch could slip an acked record past the shipped tail and
        # lose it to the commit (the dual-window this protocol exists
        # to close).
        self._inflight = 0
        # slot -> (target rank, move_id, deadline): writes for fenced
        # slots redirect (code 473, no map attached — "retry shortly")
        self._fences: dict[int, tuple[int, str, float]] = {}
        self._moves: dict[str, _Move] = {}
        # True once ANY epoch > genesis was seen here: the read-side
        # owned-slot filter arms only then, so the no-move fleet pays
        # nothing on the query path
        self.ever_moved = False
        # the bench's overhead estimator toggles enforcement per frame;
        # production never flips this
        self.enforce = True
        self.counters = {"moves_started": 0, "moves_completed": 0,
                         "moves_aborted": 0, "fenced_write_redirects": 0,
                         "stale_sender_redirects": 0,
                         "maps_installed": 0, "maps_refused": 0,
                         "handoff_shipped_batches": 0,
                         "handoff_shipped_payloads": 0}
        if self.dir is not None:
            self.dir.mkdir(parents=True, exist_ok=True)
            loaded = self._load()
            if loaded is not None and loaded.epoch > self._map.epoch:
                self._map = loaded
                self._cache_map_views(loaded)
                self.ever_moved = loaded.epoch > 1

    # ------------------------------------------------------------ map
    def _cache_map_views(self, pmap: PlacementMap) -> None:
        """Per-install caches for the per-batch hot paths: a numpy
        assignment table (the guard's vectorized ownership check) and
        the plain routing list (the partitioner's no-fence fast path).
        Replaced WHOLESALE with the map, so lock-free readers see a
        consistent view."""
        import numpy as np

        self._assign_np = np.asarray(pmap.assignment, dtype=np.int64)
        self._routing_nofence = list(pmap.assignment)

    def map(self) -> PlacementMap:
        # _map is replaced wholesale under the lock; a bare read is a
        # consistent snapshot (the per-batch hot paths ride this)
        return self._map

    @property
    def epoch(self) -> int:
        return self.map().epoch

    def owner(self, token: str) -> int:
        return self.map().owner(token)

    def slot_of(self, token: str) -> int:
        return self.map().slot_of(token)

    def data_ranks(self) -> list[int]:
        """The ranks a data fan-out (queries, flush, sweeps) must cover:
        every slot-owning rank plus this one. A drained rank leaves this
        set the instant the commit epoch lands, so its departure never
        fails a query."""
        m = self.map()
        return sorted(set(m.assignment) | {self.cluster.rank})

    def slot_routing(self) -> list[int]:
        """slot -> rank for INGEST routing: the installed map with this
        rank's fences substituted by their targets, so the facade's own
        payloads for a fencing slot head toward the new owner's durable
        spill queue instead of the fenced engine. Lock-free cached list
        on the (overwhelmingly common) no-fence path."""
        if not self.has_fences:
            return self._routing_nofence
        with self._lock:
            self._expire_fences_locked()
            routing = list(self._map.assignment)
            for slot, (target, _mid, _dl) in self._fences.items():
                routing[slot] = target
            return routing

    def _persist_locked(self) -> None:
        if self.dir is None:
            return
        tmp = self.dir / "placement.json.tmp"
        tmp.write_text(json.dumps(self._map.to_dict()))
        tmp.rename(self.dir / "placement.json")

    def _load(self) -> "PlacementMap | None":
        try:
            return PlacementMap.from_dict(json.loads(
                (self.dir / "placement.json").read_text()))
        except (OSError, ValueError, KeyError):
            return None

    def install(self, map_dict: dict) -> bool:
        """Adopt a map iff its epoch is strictly higher (same-epoch
        re-install is an idempotent no-op; a LOWER epoch is refused —
        fencing: a partitioned coordinator's stale commit can never
        roll ownership back). Dropping fences for slots this rank no
        longer owns happens here: once the commit epoch lands, the map
        itself routes the slot away."""
        new = PlacementMap.from_dict(map_dict)
        with self._cv:
            if new.epoch < self._map.epoch:
                self.counters["maps_refused"] += 1
                return False
            if new.epoch == self._map.epoch:
                if new.assignment != self._map.assignment:
                    self.counters["maps_refused"] += 1
                    logger.error(
                        "rank %d: refused placement epoch %d with a "
                        "DIFFERENT assignment than the installed one "
                        "(split-brain commit?)", self.cluster.rank,
                        new.epoch)
                    return False
                return True
            if new.n_slots != self._map.n_slots:
                self.counters["maps_refused"] += 1
                raise ValueError(
                    f"placement n_slots {new.n_slots} != configured "
                    f"{self._map.n_slots}: the slot space is fixed at "
                    "cluster genesis")
            self._map = new
            self._cache_map_views(new)
            if new.epoch > 1:
                self.ever_moved = True
            me = self.cluster.rank
            for slot in [s for s in self._fences
                         if new.assignment[s] != me]:
                self._fences.pop(slot, None)
            self.has_fences = bool(self._fences)
            # the commit epoch IS the completion: close any of OUR
            # in-flight moves this map realizes, so a lost
            # handoffFinish cannot leave a phantom "fenced" move
            # (its fences are gone, so no deadline would ever fire)
            for mv in self._moves.values():
                if (mv.state in ("catchup", "fenced") and mv.slots
                        and all(new.assignment[s] == mv.target
                                for s in mv.slots)):
                    mv.state = "done"
                    self.counters["moves_completed"] += 1
                    _placement_instruments()["moves"].inc(
                        state="completed")
            self.counters["maps_installed"] += 1
            self._persist_locked()
            self._cv.notify_all()
            logger.info("rank %d: placement epoch %d installed "
                        "(active ranks %s)", me, new.epoch,
                        new.active_ranks())
            return True

    def sync_from_peers(self) -> int:
        """Pull the highest placement epoch any reachable peer holds
        (join/boot convergence; redirects keep the steady state
        converged). Returns the epoch in force afterwards."""
        c = self.cluster
        for r in range(c.n_ranks):
            if r == c.rank:
                continue
            try:
                d = c._peer(r).call("Placement.get")
            except (ConnectionError, TimeoutError):
                continue
            if d and int(d.get("epoch", 0)) > self.map().epoch:
                self.install(d)
        return self.map().epoch

    # --------------------------------------------------------- fences
    def _expire_fences_locked(self) -> None:
        now = time.monotonic()
        for slot in [s for s, (_t, mid, dl) in self._fences.items()
                     if dl < now]:
            _t, mid, _dl = self._fences.pop(slot)
            self.has_fences = bool(self._fences)
            mv = self._moves.get(mid)
            if mv is not None and mv.state == "fenced":
                mv.state = "aborted"
                self.counters["moves_aborted"] += 1
                logger.warning(
                    "rank %d: fence for move %s expired without a "
                    "commit — move aborted, this rank still owns "
                    "slots %s", self.cluster.rank, mid, mv.slots)
        self._cv.notify_all()

    def fenced_slots(self) -> dict[int, int]:
        with self._lock:
            self._expire_fences_locked()
            return {s: t for s, (t, _m, _d) in self._fences.items()}

    def wait_unfenced(self, slots, timeout_s: float = 5.0) -> None:
        """Block until none of ``slots`` is fenced here (or timeout).
        The facade's own ingest path uses this so a fence window costs
        its payloads the fence DURATION, not a spill/redeliver round
        trip."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while True:
                self._expire_fences_locked()
                if not any(s in self._fences for s in slots):
                    return
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self._cv.wait(min(left, 0.05))

    def ingest_gate(self):
        """Context manager every owner-side ingest path holds across
        its fence check AND engine apply (see ``_inflight``). One lock
        inc/dec per batch — negligible next to decode+dispatch."""
        return _IngestGate(self)

    def _drain_ingests(self, timeout_s: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    logger.warning(
                        "rank %d: %d ingest(s) still in flight after "
                        "%.1fs fence drain — proceeding (their records "
                        "land before the extents capture takes the "
                        "engine lock they hold)", self.cluster.rank,
                        self._inflight, timeout_s)
                    return False
                self._cv.wait(min(left, 0.05))
        return True

    # ---------------------------------------------------------- guard
    def redirect_error(self, reason: str, fenced: bool = False):
        """The typed ownership reject. A MOVED redirect attaches this
        rank's map (the sender adopts the higher epoch and re-routes in
        one hop); a FENCED redirect attaches a short retry hint instead
        — the commit is in flight and the sender must neither apply
        here nor guess the target early."""
        from sitewhere_tpu.rpc.protocol import RpcError

        if fenced:
            self.counters["fenced_write_redirects"] += 1
            _placement_instruments()["redirects"].inc(kind="fenced")
            return RpcError(f"placement fence: {reason}", REDIRECT_CODE,
                            retry_after_s=0.05,
                            data={"fenced": True,
                                  "epoch": self.map().epoch})
        self.counters["stale_sender_redirects"] += 1
        _placement_instruments()["redirects"].inc(kind="stale")
        return RpcError(f"placement redirect: {reason}", REDIRECT_CODE,
                        data={"map": self.map().to_dict()})

    def guard_tokens(self, tokens) -> None:
        """Owner-side write guard for token-addressed surfaces
        (process/admin paths): every token must hash into a slot this
        rank owns and is not fencing, else the whole call redirects
        BEFORE anything applies (all-or-nothing, like the QoS shed)."""
        if not self.enforce:
            return
        m = self.map()
        me = self.cluster.rank
        with self._lock:
            self._expire_fences_locked()
            fences = set(self._fences)
        for tok in tokens:
            slot = m.slot_of(tok)
            if slot in fences:
                raise self.redirect_error(
                    f"slot {slot} ({tok!r}) is mid-handoff", fenced=True)
            if m.assignment[slot] != me:
                raise self.redirect_error(
                    f"slot {slot} ({tok!r}) owned by rank "
                    f"{m.assignment[slot]} at epoch {m.epoch}")

    def guard_payloads(self, payloads: list, kind: str) -> None:
        """Owner-side write guard for the batch ingest surfaces: one
        native route pass classifies every payload's slot; any
        not-owned or fenced slot redirects the WHOLE batch pre-ingest
        (the sender re-partitions under the newer map — a partial apply
        here would be exactly the dual-ownership window the protocol
        exists to prevent). Unroutable payloads (slot < 0) pass: the
        engine's dead-letter path owns them wherever they land. Hot
        path: one native route call + one vectorized gather — no lock
        unless a fence is up (the bench gates this <= 3%)."""
        if not self.enforce or not payloads:
            return
        import numpy as np
        from sitewhere_tpu.native.binding import route_payloads

        m = self.map()
        me = self.cluster.rank
        fences = None
        if self.has_fences:
            with self._lock:
                self._expire_fences_locked()
                fences = set(self._fences)
        slots = route_payloads(payloads, m.n_slots,
                               binary=(kind == "binary"))
        if slots is not None and not fences:
            s = slots.astype(np.int64)
            owners = self._assign_np[np.clip(s, 0, m.n_slots - 1)]
            bad = s[(s >= 0) & (owners != me)]
            if not bad.size:
                return
            slot = int(bad[0])
            raise self.redirect_error(
                f"slot {slot} owned by rank {m.assignment[slot]} "
                f"at epoch {m.epoch}")
        slot_list = ([int(x) for x in slots.tolist()]
                     if slots is not None
                     else _payload_slots(payloads, kind, m.n_slots))
        for slot in slot_list:
            if slot < 0:
                continue
            if fences and slot in fences:
                raise self.redirect_error(
                    f"slot {slot} is mid-handoff", fenced=True)
            if m.assignment[slot] != me:
                raise self.redirect_error(
                    f"slot {slot} owned by rank {m.assignment[slot]} "
                    f"at epoch {m.epoch}")

    # ------------------------------------------------- read filtering
    def owns_token(self, token: str) -> bool:
        return self.map().owner(token) == self.cluster.rank

    def filter_rows(self, rows: list, key: str = "deviceToken") -> list:
        """Drop rows whose token's slot this rank no longer owns — the
        read-side half of single ownership: after a move, the source's
        dead copies (and a target's pre-commit partial copy after an
        abort) must not double-count in fan-out merges. Zero-cost until
        the first move ever lands (``ever_moved``)."""
        if not self.ever_moved:
            return rows
        m = self.map()
        me = self.cluster.rank
        return [row for row in rows
                if (tok := row.get(key)) is None
                or m.owner(tok) == me]

    # ----------------------------------------------- source-side moves
    def _move(self, move_id: str) -> _Move:
        with self._lock:
            mv = self._moves.get(move_id)
            if mv is None:
                raise KeyError(f"unknown move {move_id!r}")
            return mv

    def _gc_moves_locked(self) -> None:
        now = time.monotonic()
        for mid in [m for m, mv in self._moves.items()
                    if mv.state == "catchup"
                    and now - mv.started_mono > self.move_timeout_s]:
            self._moves[mid].state = "aborted"
            self.counters["moves_aborted"] += 1
            logger.warning("rank %d: move %s timed out in catch-up — "
                           "aborted", self.cluster.rank, mid)

    def handoff_start(self, move_id: str, slots: list, target: int) -> dict:
        """Source-side move registration (idempotent). Refuses slots
        this rank does not own, a target outside the provisioned set,
        and — loudly, before anything ships — a PRUNED WAL: catch-up IS
        WAL replay, and a pruned log no longer carries the full acked
        history (the offline snapshot path owns that case)."""
        m = self.map()
        me = self.cluster.rank
        slots = tuple(sorted(int(s) for s in slots))
        for s in slots:
            if m.assignment[s] != me:
                raise ValueError(
                    f"slot {s} is owned by rank {m.assignment[s]}, not "
                    f"this rank ({me}) — cannot hand off")
        if not (0 <= int(target) < self.cluster.n_ranks):
            raise ValueError(f"target rank {target} not provisioned")
        if int(target) == me:
            raise ValueError("target rank is the source rank")
        eng = self.cluster.local
        wal = getattr(eng, "wal", None)
        if wal is not None:
            segs = sorted(pathlib.Path(wal.dir).glob("segment-*.log"))
            if segs and int(segs[0].stem.split("-")[1]) != 0:
                raise ValueError(
                    f"rank {me} WAL was pruned (oldest segment "
                    f"{segs[0].name}): online handoff replays the WAL "
                    "and would silently drop the pruned span — use the "
                    "offline cluster_reshard path")
        with self._lock:
            self._gc_moves_locked()
            mv = self._moves.get(move_id)
            if mv is None:
                mv = self._moves[move_id] = _Move(move_id, slots,
                                                 int(target))
                self.counters["moves_started"] += 1
                _placement_instruments()["moves"].inc(state="started")
            if mv.state == "aborted":
                raise ValueError(f"move {move_id} already aborted")
        return {"moveId": move_id, "slots": list(slots),
                "target": int(target), "state": mv.state}

    def _wal_extents(self) -> dict:
        """Durable byte extents of the source WAL, captured under the
        engine lock (the ReplicaFeed resync discipline: nothing beyond
        the durable watermark, no torn user-space tail)."""
        eng = self.cluster.local
        wal = getattr(eng, "wal", None)
        with eng.lock:
            if wal is None:
                return {}
            if wal.group_commit:
                wal.wait_durable(getattr(eng, "_wal_last_seq", 0))
                return wal.durable_view()
            wal.flush()
            return {p.name: p.stat().st_size
                    for p in sorted(wal.dir.glob("segment-*.log"))}

    def _ship_delta(self, mv: _Move, chunk: int = 256) -> int:
        """Ship every not-yet-shipped WAL record whose token hashes into
        a moving slot (the cursor is a count over MATCHING records —
        WAL order is append-only and stable, so skip-then-ship is
        exact). Returns records shipped this pass. Deterministic fids
        (`<move>-<idx>`) make retries idempotent at the target."""
        from sitewhere_tpu.parallel.replication import _read_wal_records

        eng = self.cluster.local
        wal = getattr(eng, "wal", None)
        if wal is None:
            return 0
        extents = self._wal_extents()
        n_slots = self.map().n_slots
        moving = set(mv.slots)
        wal_dir = pathlib.Path(wal.dir)
        seen = shipped = 0
        batch: list[bytes] = []
        batch_key: "tuple[str, str] | None" = None
        batch_start = 0   # cursor position of the batch's first record

        def flush_batch():
            """Ship one batch with a POSITION-deterministic fid: a
            retried pass (target briefly down, response lost) re-ships
            the SAME records under the SAME fid, so the target's
            registry suppresses the duplicate instead of re-applying.
            The cursor advances only on a confirmed apply — a mid-pass
            failure resumes exactly where durability stopped."""
            nonlocal batch, shipped
            if not batch:
                return
            kind, tenant = batch_key
            fid = f"{mv.move_id}-r{batch_start:09d}"
            self.cluster._peer(mv.target).call(
                "Placement.handoffApply", moveId=mv.move_id, fid=fid,
                encoding=kind, tenant=tenant,
                lens=[len(p) for p in batch],
                _attachment=b"".join(batch))
            if fid not in mv.fids:
                mv.fids.append(fid)
            mv.shipped_batches += 1
            mv.shipped_payloads += len(batch)
            mv.shipped_records = batch_start + len(batch)
            self.counters["handoff_shipped_batches"] += 1
            self.counters["handoff_shipped_payloads"] += len(batch)
            shipped += len(batch)
            batch = []

        # chunked native routing over a record window keeps the hash in
        # C for the common (large-history) case
        window: list[tuple[str, str, bytes]] = []

        def drain_window():
            nonlocal window, seen, batch_key, batch_start
            if not window:
                return
            slots = _payload_slots([p for _k, _t, p in window],
                                   "mixed", n_slots,
                                   kinds=[k for k, _t, _p in window])
            for (kind, tenant, payload), slot in zip(window, slots):
                if slot < 0 or slot not in moving:
                    continue
                seen += 1
                if seen <= mv.shipped_records:
                    continue   # shipped by an earlier pass
                key = (kind, tenant)
                if batch and (key != batch_key or len(batch) >= chunk):
                    flush_batch()
                if not batch:
                    batch_start = seen - 1
                batch_key = key
                batch.append(payload)
            window = []

        for rec in _read_wal_records(wal_dir, extents):
            window.append(rec)
            if len(window) >= 512:
                drain_window()
        drain_window()
        flush_batch()
        return shipped

    def handoff_catchup(self, move_id: str, chunk: int = 256) -> dict:
        """One catch-up pass; the coordinator repeats until the delta
        reaches zero, then fences. Safe to re-run after any failure."""
        mv = self._move(move_id)
        if mv.state not in ("catchup", "fenced"):
            raise ValueError(f"move {move_id} is {mv.state}")
        shipped = self._ship_delta(mv, chunk=chunk)
        return {"moveId": move_id, "shipped": shipped,
                "shippedRecords": mv.shipped_records,
                "shippedBatches": mv.shipped_batches}

    def handoff_fence(self, move_id: str) -> dict:
        """Fence the moving slots (writes for them now redirect — never
        applied here again), ship the WAL tail that raced the last
        catch-up pass, and verify the target's applied watermark (every
        shipped fid recorded there). After this returns, the target
        holds the full acked history of the moving slots and the
        coordinator may commit the epoch."""
        mv = self._move(move_id)
        if mv.state == "aborted":
            raise ValueError(f"move {move_id} already aborted")
        deadline = time.monotonic() + self.fence_timeout_s
        with self._lock:
            for s in mv.slots:
                self._fences[s] = (mv.target, move_id, deadline)
            self.has_fences = True
            mv.state = "fenced"
            mv.fence_deadline = deadline
        # drain the in-flight ingest gate: every batch that passed its
        # fence check BEFORE the registration above finishes its engine
        # apply (and WAL append) before the tail extents are captured —
        # new batches see the fence and route to the target's queue
        self._drain_ingests()
        tail = self._ship_delta(mv)
        reply = self.cluster._peer(mv.target).call(
            "Placement.handoffVerify", moveId=move_id, fids=mv.fids)
        if not reply.get("applied"):
            raise RuntimeError(
                f"move {move_id}: target rank {mv.target} is missing "
                f"shipped batches {reply.get('missing')} — refusing to "
                "commit")
        # the tail ship + verify may have outlasted the fence deadline
        # (huge WAL, slow target): an EXPIRED fence means writes may
        # have resumed here, so committing would lose them — refuse,
        # loudly, and make the coordinator abort. Otherwise RE-ARM the
        # deadline so the coordinator has a full window to commit
        # (commit is a handful of millisecond-scale RPCs; a coordinator
        # that cannot install within fence_timeout_s is as good as
        # dead, and the expiry abort keeps the source authoritative).
        with self._lock:
            live = all(self._fences.get(s, (None, None, 0.0))[1]
                       == move_id for s in mv.slots)
            if not live or mv.state != "fenced":
                raise RuntimeError(
                    f"move {move_id}: fence expired during the tail "
                    "ship — writes may have resumed at the source; "
                    "refusing to commit (retry the move)")
            redeadline = time.monotonic() + self.fence_timeout_s
            for s in mv.slots:
                self._fences[s] = (mv.target, move_id, redeadline)
            mv.fence_deadline = redeadline
        return {"moveId": move_id, "tail": tail,
                "shippedBatches": mv.shipped_batches,
                "shippedPayloads": mv.shipped_payloads,
                "applied": True}

    def handoff_finish(self, move_id: str) -> dict:
        """Commit acknowledgement from the coordinator: drop the fences
        (the installed map now routes the slots away) and close the
        move."""
        mv = self._move(move_id)
        with self._cv:
            for s in mv.slots:
                self._fences.pop(s, None)
            self.has_fences = bool(self._fences)
            if mv.state in ("catchup", "fenced"):
                # normally already "done" via the commit install; a
                # move the fence deadline ABORTED stays aborted — the
                # counters must never double-book one move
                mv.state = "done"
                self.counters["moves_completed"] += 1
                _placement_instruments()["moves"].inc(state="completed")
            self._cv.notify_all()
        return {"moveId": move_id, "state": mv.state}

    def handoff_abort(self, move_id: str) -> dict:
        """Coordinator-side abort (target unreachable, operator cancel):
        unfence, keep ownership, count it. The target's partial copy is
        invisible to reads (owned-slot filter) and gets overwritten by
        any later successful move's replay (fid-deduped)."""
        try:
            mv = self._move(move_id)
        except KeyError:
            return {"moveId": move_id, "state": "unknown"}
        with self._cv:
            for s in mv.slots:
                f = self._fences.get(s)
                if f is not None and f[1] == move_id:
                    self._fences.pop(s)
            self.has_fences = bool(self._fences)
            if mv.state not in ("done", "aborted"):
                mv.state = "aborted"
                self.counters["moves_aborted"] += 1
                _placement_instruments()["moves"].inc(state="aborted")
            self._cv.notify_all()
        return {"moveId": move_id, "state": mv.state}

    # ------------------------------------------------- target helpers
    def handoff_prepare(self, move_id: str, slots: list) -> dict:
        """TARGET-side content filter, built BEFORE any catch-up batch
        arrives: scan this rank's OWN WAL for records whose token hashes
        into the moving slots and remember their content multiset
        ((kind, tenant, payload digest) -> count). The apply path
        consumes it, so the incoming replay re-ingests ONLY what this
        rank does not already hold — the no-dual-apply guarantee for a
        range RETURNING to a former owner, and for a retried move whose
        earlier attempt partially applied under different forward ids.
        Exact multiset semantics: a legitimately duplicated payload
        (same bytes sent twice across eras) is dropped once per copy
        already held."""
        import hashlib

        eng = self.cluster.local
        wal = getattr(eng, "wal", None)
        counter: dict = {}
        total = 0
        if wal is not None:
            from sitewhere_tpu.parallel.replication import (
                _read_wal_records)

            extents = self._wal_extents()
            moving = set(int(s) for s in slots)
            n_slots = self.map().n_slots
            window: list = []

            def drain():
                nonlocal window, total
                if not window:
                    return
                slist = _payload_slots(
                    [p for _k, _t, p in window], "mixed", n_slots,
                    kinds=[k for k, _t, _p in window])
                for (kind, tenant, payload), slot in zip(window, slist):
                    if slot in moving:
                        key = (kind, tenant,
                               hashlib.blake2b(payload,
                                               digest_size=16).digest())
                        counter[key] = counter.get(key, 0) + 1
                        total += 1
                window = []

            for rec in _read_wal_records(pathlib.Path(wal.dir), extents):
                window.append(rec)
                if len(window) >= 512:
                    drain()
            drain()
        with self._lock:
            now = time.monotonic()
            for mid in [m for m, (ts, _c) in self._prepared.items()
                        if now - ts > self.move_timeout_s]:
                self._prepared.pop(mid)
            self._prepared[move_id] = (now, counter)
        return {"moveId": move_id, "alreadyHeld": total}

    def consume_prepared(self, move_id: str, kind: str, tenant: str,
                         plist: list) -> list:
        """Filter one incoming handoff batch against the prepared
        content multiset (decrementing matches). Without a prepared
        entry (manager absent, prepare skipped by an old coordinator)
        the batch passes through unchanged."""
        import hashlib

        with self._lock:
            ent = self._prepared.get(move_id)
            if ent is None:
                return plist
            counter = ent[1]
            out = []
            for p in plist:
                key = (kind, tenant,
                       hashlib.blake2b(p, digest_size=16).digest())
                n = counter.get(key, 0)
                if n > 0:
                    counter[key] = n - 1
                else:
                    out.append(p)
            return out

    def handoff_verify(self, move_id: str, fids: list) -> dict:
        """Target-side applied-watermark check: every fid the source
        shipped must be recorded in this rank's spill registry (the
        handoffApply handler records AFTER ingest, so a recorded fid is
        an applied batch)."""
        reg = getattr(self.cluster.local, "spill_registry", None)
        if reg is None:
            # no registry attached: the synchronous apply RPCs were the
            # confirmation; nothing further to check
            return {"moveId": move_id, "applied": True, "missing": []}
        missing = [f for f in fids if not reg.seen(f)]
        return {"moveId": move_id, "applied": not missing,
                "missing": missing}

    # -------------------------------------------------------- surfaces
    def ledger_stage(self) -> dict:
        """The conservation ledger's placement stage: one lock-consistent
        read of the move accounting (started == completed + aborted +
        in-flight is the new equation) plus the epoch/fence posture."""
        with self._lock:
            self._gc_moves_locked()
            self._expire_fences_locked()
            in_flight = sum(1 for mv in self._moves.values()
                            if mv.state in ("catchup", "fenced"))
            return {
                "epoch": self._map.epoch,
                "moves_started": self.counters["moves_started"],
                "moves_completed": self.counters["moves_completed"],
                "moves_aborted": self.counters["moves_aborted"],
                "moves_in_flight": in_flight,
                "fenced_slots": len(self._fences),
                "fenced_write_redirects":
                    self.counters["fenced_write_redirects"],
                "stale_sender_redirects":
                    self.counters["stale_sender_redirects"],
            }

    def payload(self) -> dict:
        """THE document behind ``GET /api/instance/placement``, the
        ``Instance.placement`` RPC, and the debug bundle's placement
        section: the installed map, per-range handoff state, and the
        counters."""
        with self._lock:
            self._gc_moves_locked()
            self._expire_fences_locked()
            moves = [{
                "moveId": mv.move_id, "slots": list(mv.slots),
                "target": mv.target, "state": mv.state,
                "shippedBatches": mv.shipped_batches,
                "shippedPayloads": mv.shipped_payloads,
            } for mv in self._moves.values()]
            return {
                "rank": self.cluster.rank,
                "map": self._map.to_dict(),
                "activeRanks": self._map.active_ranks(),
                "slots": {str(r): self._map.slots_of(r)
                          for r in self._map.active_ranks()},
                "fences": {str(s): {"target": t, "moveId": mid}
                           for s, (t, mid, _dl) in self._fences.items()},
                "moves": moves,
                "counters": dict(self.counters),
            }

    def metrics(self) -> dict:
        with self._lock:
            return {"placement_epoch": self._map.epoch,
                    "placement_fenced_slots": len(self._fences),
                    **{f"placement_{k}": v
                       for k, v in self.counters.items()}}


def _payload_slots(payloads: list, kind: str, n_slots: int,
                   kinds: "list[str] | None" = None) -> list[int]:
    """Slot per payload (-1 = unroutable). ONE native route call for a
    homogeneous batch; the byte-exact Python port otherwise. Routing by
    ``n_slots`` instead of ``n_ranks`` is the only difference from the
    legacy partitioner — same hash, same envelope scan."""
    from sitewhere_tpu.native.binding import route_payloads

    if kinds is None:
        ranks = route_payloads(payloads, n_slots,
                               binary=(kind == "binary"))
        if ranks is not None:
            return [int(r) for r in ranks.tolist()]
        kinds = [kind] * len(payloads)
    from sitewhere_tpu.native.route_fallback import (route_binary_payload,
                                                     route_json_payload)

    return [(route_binary_payload if k == "binary" else route_json_payload)
            (p, n_slots) for k, p in zip(kinds, payloads)]


# --------------------------------------------------------------------------
# coordination: move / join / drain (run from any rank)
# --------------------------------------------------------------------------

def _placement_call(cluster, rank: int, method: str, **params):
    """Dispatch a Placement.* step: direct manager call when the step
    targets THIS rank (the coordinator is often also the source), RPC
    otherwise."""
    if rank == cluster.rank:
        pm = cluster.placement
        local = {
            "Placement.handoffStart": lambda moveId, slots, target:
                pm.handoff_start(moveId, slots, target),
            "Placement.handoffPrepare": lambda moveId, slots:
                pm.handoff_prepare(moveId, slots),
            "Placement.handoffCatchup": lambda moveId:
                pm.handoff_catchup(moveId),
            "Placement.handoffFence": lambda moveId:
                pm.handoff_fence(moveId),
            "Placement.handoffFinish": lambda moveId:
                pm.handoff_finish(moveId),
            "Placement.handoffAbort": lambda moveId:
                pm.handoff_abort(moveId),
            "Placement.install": lambda map:
                {"installed": pm.install(map), "epoch": pm.epoch},
            "Placement.get": lambda: pm.map().to_dict(),
        }
        return local[method](**params)
    return cluster._peer(rank).call(method, **params)


def move_slots(cluster, slots, target: int,
               max_catchup_rounds: int = 32) -> dict:
    """THE handoff orchestration: move ``slots`` to ``target`` with zero
    acked loss and no dual-ownership window. Slots may span several
    current owners; each (source, target) pair runs the full
    catch-up -> fence -> verify -> commit -> finish sequence. Any
    failure before commit aborts that source's move (ownership
    unchanged); the commit itself is a single map install + tolerant
    broadcast, after which redirects converge every straggler."""
    pm = cluster.placement
    stats = {"moves": [], "epoch_before": pm.epoch}
    by_src: dict[int, list[int]] = {}
    m = pm.map()
    for s in sorted(set(int(x) for x in slots)):
        src = m.owner_of_slot(s)
        if src != int(target):
            by_src.setdefault(src, []).append(s)
    for src, sl in sorted(by_src.items()):
        move_id = f"mv{cluster.rank}-{time.time_ns()}"
        rec = {"moveId": move_id, "source": src, "target": int(target),
               "slots": sl}
        try:
            _placement_call(cluster, src, "Placement.handoffStart",
                            moveId=move_id, slots=sl, target=int(target))
            _placement_call(cluster, int(target),
                            "Placement.handoffPrepare",
                            moveId=move_id, slots=sl)
            for _ in range(max_catchup_rounds):
                r = _placement_call(cluster, src,
                                    "Placement.handoffCatchup",
                                    moveId=move_id)
                if r["shipped"] == 0:
                    break
            f = _placement_call(cluster, src, "Placement.handoffFence",
                                moveId=move_id)
            rec.update(shippedBatches=f["shippedBatches"],
                       shippedPayloads=f["shippedPayloads"])
        except Exception as e:
            rec.update(state="aborted", error=repr(e))
            stats["moves"].append(rec)
            try:
                _placement_call(cluster, src, "Placement.handoffAbort",
                                moveId=move_id)
            except Exception:
                pass   # source unreachable: its fence deadline unfences
            logger.warning("placement move %s (rank %d -> %d) aborted: "
                           "%r", move_id, src, target, e)
            continue
        # commit: epoch+1 installed locally first (the coordinator is a
        # data rank; its routing flips atomically with the install),
        # then broadcast tolerant — stragglers converge via redirects
        new_map = pm.map().with_moves({s: int(target) for s in sl})
        pm.install(new_map.to_dict())
        for r in range(cluster.n_ranks):
            if r == cluster.rank:
                continue
            try:
                _placement_call(cluster, r, "Placement.install",
                                map=new_map.to_dict())
            except Exception:
                pass
        try:
            _placement_call(cluster, src, "Placement.handoffFinish",
                            moveId=move_id)
        except Exception:
            pass   # fence deadline covers a lost finish
        rec.update(state="done", epoch=new_map.epoch)
        stats["moves"].append(rec)
        logger.info("placement move %s: slots %s rank %d -> %d at "
                    "epoch %d", move_id, sl, src, target, new_map.epoch)
    stats["epoch_after"] = pm.epoch
    return stats


def join_rank(cluster, rank: int, share: "int | None" = None) -> dict:
    """Bring a provisioned-but-inactive rank into the active set by
    moving it an even share of slots (round-robin from the most-loaded
    current owners). The rank's process must already be serving its
    cluster RPC; it bootstraps by receiving handoff replay — the
    follower-then-owner sequence of the protocol docstring."""
    pm = cluster.placement
    m = pm.map()
    active = m.active_ranks()
    if rank in active:
        return {"joined": False, "reason": "already active",
                "epoch": m.epoch}
    if share is None:
        share = max(1, m.n_slots // (len(active) + 1))
    by_owner = sorted(((len(m.slots_of(r)), r) for r in active),
                      reverse=True)
    picked: list[int] = []
    donors = [r for _n, r in by_owner]
    di = 0
    while len(picked) < share and donors:
        r = donors[di % len(donors)]
        avail = [s for s in pm.map().slots_of(r) if s not in picked]
        if not avail:
            donors.remove(r)
            continue
        picked.append(avail[len(picked) % len(avail)])
        di += 1
    res = move_slots(cluster, picked, rank)
    res["joined"] = any(mv.get("state") == "done"
                        for mv in res["moves"])
    return res


def drain_rank(cluster, rank: int) -> dict:
    """Hand off EVERY slot ``rank`` owns (round-robin over the remaining
    active ranks), after which the rank owns nothing, leaves the data
    fan-out set, and can be stopped with zero acked loss."""
    pm = cluster.placement
    m = pm.map()
    targets = [r for r in m.active_ranks() if r != rank]
    if not targets:
        raise ValueError(f"rank {rank} is the only active rank — "
                         "nothing can absorb its slots")
    slots = m.slots_of(rank)
    results = []
    for i, t in enumerate(targets):
        chunk = slots[i::len(targets)]
        if chunk:
            results.append(move_slots(cluster, chunk, t))
    drained = not pm.map().slots_of(rank)
    return {"rank": rank, "drained": drained, "epoch": pm.epoch,
            "results": results}


# --------------------------------------------------------------------------
# the load-balancing half: hot-tenant detection -> proposed moves
# --------------------------------------------------------------------------

def decide_balance(tenant_p99_ms: dict, tenant_rank: dict,
                   tenant_slots: dict, pmap: PlacementMap,
                   p99_target_ms: float,
                   max_moves: int = 1,
                   slot_heat: dict | None = None) -> list[tuple[int, int]]:
    """PURE balancing policy (unit-testable like autotune.decide): given
    each tenant's worst e2e p99, its dominant owner rank, and the slots
    its devices hash into, propose up to ``max_moves`` (slot, target)
    moves that peel the hottest tenant's busiest slot off its rank onto
    the active rank with the fewest slots. No proposal when nothing
    breaches the target, when the hot tenant's rank is already the
    lightest, or when the hot slot is the rank's only slot (moving it
    would just relocate the problem).

    ``slot_heat`` (ISSUE 18) is an optional ``{slot: events/s}`` map —
    the SPMD shard heat plane's slot EWMA — used to pick the ACTUAL
    busiest of the tenant's slots instead of the first. ``None`` keeps
    the decision byte-identical to the pre-heat policy (pure-function
    pin in tests/test_shardobs.py)."""
    breaches = sorted(((p, t) for t, p in tenant_p99_ms.items()
                       if p is not None and p > p99_target_ms),
                      reverse=True)
    if not breaches:
        return []
    active = pmap.active_ranks()
    load = {r: len(pmap.slots_of(r)) for r in active}
    moves: list[tuple[int, int]] = []
    for _p99, tenant in breaches:
        if len(moves) >= max_moves:
            break
        src = tenant_rank.get(tenant)
        slots = [s for s in tenant_slots.get(tenant, ())
                 if pmap.owner_of_slot(s) == src]
        if src is None or not slots or load.get(src, 0) <= 1:
            continue
        target = min((r for r in active if r != src),
                     key=lambda r: load[r], default=None)
        if target is None or load[target] >= load[src]:
            continue
        if slot_heat:
            # hottest of the tenant's slots by measured events/s; ties
            # (and unmeasured slots, heat 0.0) break toward the lowest
            # slot id, which is slots[0] when nothing is measured
            slot = max(slots, key=lambda s: (slot_heat.get(s, 0.0), -s))
        else:
            slot = slots[0]
        moves.append((slot, target))
        load[src] -= 1
        load[target] += 1
    return moves


def propose_moves(cluster, p99_target_ms: float = 250.0,
                  max_moves: int = 1,
                  heat: dict | None = None) -> list[tuple[int, int]]:
    """Gather the live inputs for :func:`decide_balance` from the SLO
    plane (the per-tenant ``swtpu_ingest_e2e_seconds`` histograms, PR
    7/9) and this rank's device registry, and return proposed
    ``(slot, target)`` moves. Advisory: the operator (or an autonomous
    loop) applies them through :func:`move_slots` — placement changes
    always ride the fenced protocol, never a side door.

    ``heat`` (ISSUE 18) is an optional ``{slot: events/s}`` map — feed
    it the SPMD heat plane's top-K slot document (``spmd_heat_payload``
    "slots"/"topK", or a tracker's ``top_slots()``) so the hot tenant's
    ACTUAL busiest slot moves. ``None`` (the default) is byte-identical
    to the PR-15 policy."""
    from sitewhere_tpu.utils.metrics import REGISTRY, slo_metrics

    hist = slo_metrics(REGISTRY)["ingest_e2e"]
    pm = cluster.placement
    m = pm.map()
    tenant_p99: dict = {}
    tenant_slots: dict = {}
    tenant_rank_votes: dict = {}
    for info in cluster.local.devices.values():
        ten = getattr(info, "tenant", None) or "default"
        slot = m.slot_of(info.token)
        tenant_slots.setdefault(ten, set()).add(slot)
        votes = tenant_rank_votes.setdefault(ten, {})
        r = m.owner_of_slot(slot)
        votes[r] = votes.get(r, 0) + 1
    for ten in tenant_slots:
        q = hist.quantile_where(0.99, tenant=ten)
        tenant_p99[ten] = None if q is None else q * 1e3
    tenant_rank = {t: max(v, key=v.get)
                   for t, v in tenant_rank_votes.items() if v}
    return decide_balance(tenant_p99, tenant_rank,
                          {t: sorted(s) for t, s in tenant_slots.items()},
                          m, p99_target_ms, max_moves=max_moves,
                          slot_heat=heat)


# --------------------------------------------------------------------------
# RPC surface + instruments
# --------------------------------------------------------------------------

def register_placement_rpc(srv, engine) -> None:
    """The placement plane on the rank's cluster RPC server. Handlers
    bind to the ENGINE (register_cluster_rpc discipline) and reach the
    manager via ``engine.placement``. The handoff data movers are ASYNC
    (off-loop via to_thread): a catch-up pass reads the whole WAL and
    makes outbound peer calls — running it synchronously would block
    this rank's RPC loop exactly as deployment rule 1
    (parallel/cluster.py) forbids."""
    import asyncio

    def _pm() -> PlacementManager:
        pm = getattr(engine, "placement", None)
        if pm is None:
            raise ValueError("no placement manager on this rank")
        return pm

    def get():
        return _pm().map().to_dict()

    def install(map: dict):
        pm = _pm()
        return {"installed": pm.install(map), "epoch": pm.epoch}

    def status():
        return _pm().payload()

    async def handoff_start(moveId: str, slots: list, target: int):
        return await asyncio.to_thread(_pm().handoff_start, moveId,
                                       slots, target)

    async def handoff_prepare(moveId: str, slots: list):
        return await asyncio.to_thread(_pm().handoff_prepare, moveId,
                                       slots)

    async def handoff_catchup(moveId: str):
        return await asyncio.to_thread(_pm().handoff_catchup, moveId)

    async def handoff_fence(moveId: str):
        return await asyncio.to_thread(_pm().handoff_fence, moveId)

    def handoff_finish(moveId: str):
        return _pm().handoff_finish(moveId)

    def handoff_abort(moveId: str):
        return _pm().handoff_abort(moveId)

    def handoff_verify(moveId: str, fids: list):
        return _pm().handoff_verify(moveId, fids)

    async def handoff_apply(moveId: str, fid: str, encoding: str,
                            tenant: str, lens: list,
                            _attachment: bytes = None,
                            payloads: list = None):
        """Target-side replay ingest: fid-deduped via the spill
        registry, NO placement guard (the slots are not ours YET — that
        is the point) and NO QoS admission (these events were admitted
        at their original edge and are already acked/durable; replay
        must re-apply unconditionally, the WAL-replay rule). Off-loop:
        a full ingest (decode + WAL + dispatch) must not block the RPC
        loop."""
        from sitewhere_tpu.parallel.cluster import _wire_payloads

        def _run():
            reg = getattr(engine, "spill_registry", None)
            if reg is not None and reg.seen(fid):
                return {"duplicate_forward": 1}
            plist = _wire_payloads(payloads, lens, _attachment)
            pm = getattr(engine, "placement", None)
            held = 0
            if pm is not None:
                kept = pm.consume_prepared(moveId, encoding, tenant,
                                           plist)
                held = len(plist) - len(kept)
                plist = kept
            summary = {}
            if plist:
                if encoding == "binary":
                    summary = engine.ingest_binary_batch(plist, tenant)
                else:
                    summary = engine.ingest_json_batch(plist, tenant)
            if held:
                summary["handoff_already_held"] = held
            if reg is not None:
                reg.record(fid)
            return summary

        return await asyncio.to_thread(_run)

    for name, fn in {
        "Placement.get": get,
        "Placement.install": install,
        "Placement.status": status,
        "Placement.handoffStart": handoff_start,
        "Placement.handoffPrepare": handoff_prepare,
        "Placement.handoffCatchup": handoff_catchup,
        "Placement.handoffFence": handoff_fence,
        "Placement.handoffFinish": handoff_finish,
        "Placement.handoffAbort": handoff_abort,
        "Placement.handoffVerify": handoff_verify,
        "Placement.handoffApply": handoff_apply,
    }.items():
        srv.register(name, fn)


# resolved once: the redirect counter sits on the owner-side guard path
_INSTRUMENTS: dict | None = None


def _placement_instruments() -> dict:
    global _INSTRUMENTS
    if _INSTRUMENTS is None:
        from sitewhere_tpu.utils.metrics import placement_metrics

        _INSTRUMENTS = placement_metrics()
    return _INSTRUMENTS


class _IngestGate:
    """See :meth:`PlacementManager.ingest_gate`."""

    __slots__ = ("_pm",)

    def __init__(self, pm: PlacementManager):
        self._pm = pm

    def __enter__(self):
        with self._pm._lock:
            self._pm._inflight += 1
        return self

    def __exit__(self, *exc):
        with self._pm._cv:
            self._pm._inflight -= 1
            self._pm._cv.notify_all()
        return False
