"""Multi-process product runtime: DistributedEngine replicas + router.

The reference deploys every service as horizontally scaled replicas over
partitioned Kafka consumer groups — each replica runs the FULL product
behavior for its partitions
(service-outbound-connectors/.../kafka/KafkaOutboundConnectorHost.java:43-257),
and gRPC routers dispatch calls into the right engine from any node
(service-device-state/.../grpc/DeviceStateRouter.java:62-72). This module
is that deployment model for the TPU build:

  * every rank runs a complete ``DistributedEngine`` — string tokens, WAL,
    archive, feeds — over ITS chips, for the devices it OWNS;
  * ownership is a stable hash of the device-token STRING
    (``owner_rank``), not interner order — so every rank routes the same
    token to the same owner without any shared state. This is the
    token-keyed Kafka partitioner (EventSourcesManager.java:183) applied
    at the process level;
  * ingest accepted at any rank forwards the raw payload bytes of
    remote-owned events to their owner over the authenticated control
    RPC (rpc/protocol.py) — decode, WAL, dedup, and registration all
    happen exactly once, AT the owner, in its own dictionary space.
    Interner federation therefore needs no cross-rank translation
    tables: the owning rank's interners are authoritative by
    construction (route-then-decode, like a Kafka producer sending raw
    bytes to the partition's broker);
  * reads from any rank route (device/state lookups → owner) or fan out
    and merge (event queries, state search, metrics) — the
    ``DeviceStateRouter`` pattern — so REST served from ANY rank returns
    identical results;
  * event ids are cluster-global: ``local_id * n_ranks + rank`` —
    bijective, so by-id lookups route without coordination.

Within a rank, scaling stays TPU-native (ShardedEngine's shard_map step +
ICI collectives); ACROSS ranks the data plane is this replica model over
DCN, mirroring Kafka's role at the pod boundary (SURVEY.md §2.9).

Deployment rules:

1. Serve the cluster RPC on its OWN event loop (thread), separate from
   any loop whose handlers call the ClusterEngine facade (e.g. the REST
   gateway). Facade calls block synchronously on peer RPC; if the
   blocked loop is also the only one answering incoming cluster RPC,
   two ranks fanning out at each other deadlock. ``register_cluster_rpc``
   handlers bind to the local engine only, so a dedicated RPC loop can
   always answer (cluster_demo.py wires it this way).
2. Scope: this layer clusters the ENGINE surface — devices, events,
   state, feeds, metrics. Instance-level management entities (device
   types, areas/customers, assets, schedules, users/tenants) live in
   each rank's EntityStores, mirroring how the reference keeps them in
   per-service databases shared by replicas: in a multi-rank deployment,
   apply management mutations through the instance control-plane RPC
   (rpc/server.py build_instance_rpc — every family is exposed) against
   each rank, the way the reference's per-service gRPC is reachable
   from every node. Tenant LANES need no broadcast: forwarded ingest
   interns the tenant at the owner, and fan-out queries resolve tenant
   names rank-locally.
3. Rank count is part of the topology (ownership = token-hash %
   n_ranks, exactly Kafka's partition semantics): change it like a
   topology change — drain, stand up the new rank set, and migrate with
   ``reshard_cluster`` (replay every old rank's WAL through the new
   partitioner: each event re-routes exactly once to its new owner and
   re-logs in that owner's WAL) — not by adding ranks to a live cluster.
"""

from __future__ import annotations

import asyncio
import base64
import concurrent.futures
import dataclasses
import json
import logging
import threading
import time
from pathlib import Path as pathlib_Path
from typing import Any

from sitewhere_tpu.core.events import EpochBase
from sitewhere_tpu.engine import AssignmentInfo, DeviceInfo
from sitewhere_tpu.search.index import event_order_key
from sitewhere_tpu.parallel.distributed import (DistributedConfig,
                                                DistributedEngine)

logger = logging.getLogger(__name__)

_FNV_OFFSET = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3
_MASK64 = (1 << 64) - 1


# resolved once, like replication's instruments: the forward-hop
# histogram sits on the per-sub-batch forward path
_CLUSTER_INSTRUMENTS: dict | None = None


def _cluster_instruments() -> dict:
    global _CLUSTER_INSTRUMENTS
    if _CLUSTER_INSTRUMENTS is None:
        from sitewhere_tpu.utils.metrics import cluster_metrics_instruments

        _CLUSTER_INSTRUMENTS = cluster_metrics_instruments()
    return _CLUSTER_INSTRUMENTS


def owner_rank(token: str, n_ranks: int) -> int:
    """Owning rank of a device token: FNV-1a over the token STRING —
    stable across processes, restarts, and interner orders (the process-
    level Kafka partitioner)."""
    h = _FNV_OFFSET
    for b in token.encode():
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h % n_ranks


@dataclasses.dataclass
class ClusterConfig:
    """One rank's view of the cluster. ``n_ranks``/``peers`` are the
    PROVISIONED rank set (addresses known up front, stateful-set style);
    which ranks are ACTIVE — own tenant slots — is the placement map's
    job (ISSUE 15): ``initial_ranks`` narrows the genesis map to a
    subset so provisioned-but-inactive ranks can JOIN later through the
    epoch-fenced handoff, and :func:`placement.drain_rank` retires an
    active rank under live traffic. ``slots_per_rank`` fixes the slot
    space at genesis (``n_slots = n_ranks * slots_per_rank``); the
    default map is byte-identical to the legacy ``owner_rank``
    partitioner. ``placement_dir`` persists the installed map (defaults
    to ``<wal_dir>/placement`` when the engine journals)."""

    rank: int
    n_ranks: int
    peers: list[str]                  # RPC "host:port" per rank
    secret: str                       # shared JWT secret (cross-rank auth)
    epoch_base_unix_s: float          # ONE epoch base for the whole
                                      # cluster so merged timestamps agree
    engine: DistributedConfig = dataclasses.field(
        default_factory=DistributedConfig)
    connect_timeout_s: float = 30.0
    slots_per_rank: int = 8
    initial_ranks: "list[int] | None" = None
    placement_dir: "str | None" = None


class _SyncPeer:
    """Synchronous facade over one RpcClient: a background event loop owns
    the connection; ``call()`` blocks the calling thread only (the engine
    surface is synchronous, like the reference's blocking gRPC stubs)."""

    def __init__(self, addr: str, token_factory, timeout_s: float = 30.0,
                 src_rank: int = -1, dst_rank: int = -1):
        host, _, port = addr.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        # (src, dst) identify this link for the chaos fault seam
        # (utils/faults.py) — a no-op attribute read unless a plan is
        # installed
        self.src_rank, self.dst_rank = src_rank, dst_rank
        # a FACTORY, not a token: JwtService.validate enforces exp, so a
        # token minted once at engine construction would turn every
        # reconnect after its 24h expiry into a permanent 401 — mint
        # fresh per connection attempt instead
        self.token_factory = token_factory
        self.timeout_s = timeout_s
        self.grace_s = 30.0     # server-side processing allowance on top
                                # of the connect timeout
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        daemon=True)
        self._thread.start()
        self._client = None
        self._lock = threading.Lock()

    def _run(self, coro):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        try:
            return fut.result(self.timeout_s + self.grace_s)
        except (TimeoutError, concurrent.futures.TimeoutError) as e:
            # the coroutine is still running on the background loop —
            # cancel it so the shared client isn't left with a pending
            # future silently consuming the next response off the wire
            fut.cancel()
            if isinstance(e, TimeoutError):
                raise
            # Python < 3.11: the futures TimeoutError is NOT the builtin
            # one — normalize so every downstream handler catches it
            raise TimeoutError(*e.args) from None

    def _connect(self):
        from sitewhere_tpu.rpc.client import RpcClient

        deadline = time.monotonic() + self.timeout_s
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                return self._run(RpcClient(
                    host=self.host, port=self.port,
                    auth_token=self.token_factory()).connect())
            except (ConnectionError, OSError, TimeoutError) as e:
                # TimeoutError: half-open peer accepted TCP but never
                # answered the handshake — retry like any connect failure
                last = e
                time.sleep(0.1)
        raise ConnectionError(
            f"peer {self.host}:{self.port} unreachable: {last}")

    def _reconnect(self, stale) -> "Any":
        """Drop ``stale`` (connection state indeterminate after an error
        or timeout) and return a fresh client. If the fresh connect
        fails, the slot is left empty so the next caller retries from
        scratch instead of reusing a closed client."""
        with self._lock:
            if self._client is stale:
                try:
                    self._run(stale.close())
                except Exception:
                    pass
                self._client = None
            if self._client is None:
                self._client = self._connect()
            return self._client

    def _timed_out(self, client, method: str) -> "Any":
        """A timed-out call is INDETERMINATE: the peer may still be
        executing it, so auto-retrying would double-execute
        non-idempotent RPCs (invokeCommand, registerDevice). Reconnect
        so the NEXT caller gets a clean connection (the cancelled future
        must not eat a later response), then surface the timeout —
        idempotent callers retry themselves."""
        try:
            self._reconnect(client)
        except ConnectionError:
            pass   # slot left empty; the next call() reconnects
        raise TimeoutError(
            f"peer {self.host}:{self.port} timed out on {method} "
            f"after {self.timeout_s + self.grace_s:.1f}s (result "
            "indeterminate — not auto-retried)") from None

    def call(self, method: str, **params: Any) -> Any:
        from sitewhere_tpu.utils import faults

        faults.check(self.src_rank, self.dst_rank, method)
        # capture the CALLING thread's traceparent here: the coroutine
        # runs on the background loop, whose context never sees it —
        # this one line threads trace context through every cluster and
        # entity-sync peer call without touching their call sites
        if "_tp" not in params:
            from sitewhere_tpu.utils.tracing import current_traceparent

            tp = current_traceparent()
            if tp is not None:
                params["_tp"] = tp
        with self._lock:
            if self._client is None:
                self._client = self._connect()
            client = self._client
        try:
            return self._run(client.call(method, **params))
        except ConnectionError:
            # one retry over a fresh connection: the peer may have
            # restarted (crash recovery) — the reference's gRPC channels
            # reconnect the same way
            client = self._reconnect(client)
            try:
                return self._run(client.call(method, **params))
            except TimeoutError:
                # the RETRY timing out needs the same indeterminate
                # handling (an except clause does not catch exceptions
                # raised by its sibling)
                self._timed_out(client, method)
        except TimeoutError:
            self._timed_out(client, method)

    def close(self) -> None:
        with self._lock:
            if self._client is not None:
                try:
                    self._run(self._client.close())
                except Exception:
                    pass
                self._client = None
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


def _unb64(payloads: list[str]) -> list[bytes]:
    return [base64.b64decode(p) for p in payloads]


def _split_blob(blob: bytes, lens: list) -> list[bytes]:
    """Inverse of the sender's b"".join: one attachment blob back into
    payload list form. Rejects non-integer/negative lengths BEFORE the
    sum check (a float that sums right would silently misalign every
    boundary after int() truncation)."""
    import operator

    lens = [operator.index(n) for n in lens]   # raises on floats/strings
    if any(n < 0 for n in lens) or sum(lens) != len(blob):
        raise ValueError(
            f"attachment length {len(blob)} does not match lens")
    out, off = [], 0
    for n in lens:
        out.append(bytes(blob[off:off + n]))
        off += n
    return out


def _wire_payloads(payloads=None, lens=None, _attachment=None) -> list[bytes]:
    """Payload list from either wire form: raw attachment blob + lens
    (the hot path — no base64, no json escaping) or the b64 list (spill
    records, older senders). An attachment WITHOUT lens is malformed and
    must fail loudly — silently ingesting zero events would report
    success to a sender that shipped data."""
    if _attachment is not None:
        if lens is None:
            raise ValueError("attachment requires lens")
        return _split_blob(_attachment, lens)
    return _unb64(payloads or [])


def _merge_counts(dicts: list[dict]) -> dict:
    out: dict = {}
    for d in dicts:
        for k, v in d.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = out.get(k, 0) + v
            elif isinstance(v, list):
                out.setdefault(k, []).extend(v)
            else:
                out.setdefault(k, v)
    return out


class _MergedDevices:
    """Read-only merged view of every rank's device mirror
    (``.values()`` / ``len`` fan out to every rank). There is NO by-id
    ``get``: device ids are rank-scoped, so the same integer names a
    DIFFERENT device on every rank — a dict-shaped ``get`` would answer
    from whichever rank it ran on and silently alias. By-id lookups are
    either local by construction (feed/connector/analytics records of
    THIS rank — use ``get_local`` / ``local_device_info``) or routed by
    token (``ClusterEngine.get_device``)."""

    def __init__(self, cluster: "ClusterEngine"):
        self._c = cluster

    def values(self):
        out = _owned_device_infos(self._c.local)
        for r in self._c._data_ranks():
            if r == self._c.rank:
                continue
            out.extend(DeviceInfo(**d) for d in
                       self._c._peer(r).call("Cluster.listDeviceInfos"))
        return out

    def get(self, key, default=None):
        raise TypeError(
            "device ids are rank-local: the same integer names a "
            "different device on every rank, so a cluster-wide by-id "
            "get() cannot exist. Use devices.get_local(id) for records "
            "produced by THIS rank (feeds/connectors/analytics), or "
            "engine.get_device(token) for a routed lookup.")

    def get_local(self, key, default=None):
        """This rank's mirror only — correct for local ids (this rank's
        feed records, analytics tables, dead letters)."""
        return self._c.local.devices.get(key, default)

    def __len__(self) -> int:
        n = len(_owned_device_infos(self._c.local))
        for r in self._c._data_ranks():
            if r != self._c.rank:
                n += self._c._peer(r).call("Cluster.deviceCount")
        return n


class _ClusterFeed:
    """Wraps a rank-local feed consumer, translating event ids to the
    cluster-global id space so records can be re-fetched via
    ``ClusterEngine.get_event`` from ANY rank."""

    def __init__(self, feed, rank: int, n_ranks: int):
        self._feed = feed
        self._rank = rank
        self._n = n_ranks

    def poll(self, *a, **kw):
        return [dataclasses.replace(
            rec, event_id=rec.event_id * self._n + self._rank)
            for rec in self._feed.poll(*a, **kw)]

    def commit(self, events) -> None:
        # commit() decodes (arena, position) from the event id — it must
        # see the LOCAL id, or every commit over-advances ~n_ranks x and
        # silently skips events the consumer never delivered
        self._feed.commit([
            dataclasses.replace(ev, event_id=ev.event_id // self._n)
            for ev in events])

    def __getattr__(self, name):
        return getattr(self._feed, name)


class ClusterEngine:
    """The any-rank product surface: a local DistributedEngine for owned
    devices plus RPC routing/fan-out to peers. Everything not overridden
    here (config, interners, staging, WAL, archive, feeds) delegates to
    the local engine — handlers registered by ``register_cluster_rpc``
    always bind to ``.local``, so routed calls never recurse."""

    def __init__(self, config: ClusterConfig,
                 local: DistributedEngine | None = None):
        self.cluster_config = config
        self.rank = config.rank
        self.n_ranks = config.n_ranks
        if local is not None:
            # a pre-built engine (recover_distributed) carries the epoch
            # base its snapshot/WAL were written under; silently replacing
            # it with a drifted configured base would shift every stored
            # relative timestamp — refuse instead
            base = getattr(local.epoch, "base_unix_s", None)
            if base is not None and abs(base - config.epoch_base_unix_s) > 1e-3:
                raise ValueError(
                    f"recovered engine epoch base {base} != configured "
                    f"cluster base {config.epoch_base_unix_s}: the cluster "
                    "must keep the base its history was written under")
            self.local = local
        else:
            self.local = DistributedEngine(config.engine)
        self.local.epoch = EpochBase(config.epoch_base_unix_s)
        self.epoch = self.local.epoch
        # the rank stamps every flight record (and trace-id generation),
        # so cross-rank trace views attribute records correctly
        self.local.flight.rank = config.rank
        # ditto for the span tracer: timeline events carry pid=rank, the
        # stitch key of the multi-rank Perfetto view (ISSUE 10)
        self.local.tracer.rank = config.rank
        self.search_index = None          # see attach_search_index
        self.command_service = None       # see attach_command_service
        self.forward_queue = None         # see attach_forwarding
        self.replica_feed = None          # see attach_replication
        self.replica_applier = None       # see attach_replication
        self.replication_factor = 1
        # peer health (up/suspect/down + probe backoff) fed by every
        # transport outcome — the failover read path and fire-over
        # detection both key on it
        from sitewhere_tpu.parallel.replication import PeerHealth

        self.health = PeerHealth()
        # versioned tenant placement (ISSUE 15): every ownership read on
        # this rank — facade routing, owner-side guards, fire-over,
        # replica-ring derivation — resolves through THIS manager's
        # installed map, so all surfaces agree on one epoch. Attached to
        # the local engine too (the forward_queue pattern) so cluster
        # RPC handlers reach it.
        from sitewhere_tpu.parallel.placement import (PlacementManager,
                                                      PlacementMap)

        pdir = config.placement_dir
        if pdir is None and config.engine.wal_dir:
            pdir = str(pathlib_Path(config.engine.wal_dir) / "placement")
        self.placement = PlacementManager(
            self, PlacementMap.initial(config.n_ranks,
                                       config.slots_per_rank,
                                       config.initial_ranks),
            directory=pdir)
        self.local.placement = self.placement
        self._peers: dict[int, _SyncPeer] = {}
        self._peers_lock = threading.Lock()
        self._fid_seq = 0
        # assignment-token -> owning rank. Ownership is IMMUTABLE (the
        # assignment lives where its device's shards are, and device
        # ownership is a pure token hash), so entries never go stale;
        # capped so a scan-heavy workload can't grow it without bound.
        self._assignment_ranks: dict[str, int] = {}
        self._token_factory = lambda: cluster_system_jwt(config.secret)

    # ------------------------------------------------------------- plumbing
    def __getattr__(self, name):
        return getattr(self.local, name)

    def _peer(self, rank: int) -> _SyncPeer:
        # locked: concurrent REST/executor threads racing the lazy create
        # would each spawn (and one would leak) a client loop thread
        with self._peers_lock:
            peer = self._peers.get(rank)
            if peer is None:
                peer = self._peers[rank] = _SyncPeer(
                    self.cluster_config.peers[rank], self._token_factory,
                    self.cluster_config.connect_timeout_s,
                    src_rank=self.rank, dst_rank=rank)
            return peer

    def _peer_call(self, rank: int, method: str, **params):
        """Peer call that feeds the health tracker: transport failures
        (refusal/timeout — result unknown either way) count against the
        rank, successes reset it."""
        try:
            res = self._peer(rank).call(method, **params)
        except (ConnectionError, TimeoutError):
            self.health.record_failure(rank)
            raise
        self.health.record_success(rank)
        return res

    def owner(self, token: str) -> int:
        """Owning rank per the installed PLACEMENT map (ISSUE 15): the
        token hashes into a fixed slot, the epoch-numbered map names
        the slot's rank. The genesis map reproduces the legacy
        ``owner_rank(token, n_ranks)`` byte-for-byte."""
        return self.placement.owner(token)

    def _data_ranks(self) -> list[int]:
        """Ranks a DATA fan-out must cover: every slot-owning rank plus
        this one. A drained rank leaves this set at its commit epoch, so
        its departure (and eventual shutdown) never fails a query; a
        joining rank enters it with its first owned slot. Health/status
        surfaces keep fanning over the full provisioned set — operators
        need to see inactive ranks."""
        return self.placement.data_ranks()

    def _route(self, _token: str, _local_fn, _method: str, **params):
        r = self.owner(_token)
        if r == self.rank:
            return _local_fn()
        return self._peer(r).call(_method, **params)

    def close(self) -> None:
        for peer in self._peers.values():
            peer.close()
        self._peers.clear()

    # --------------------------------------------------------------- ingest
    def _partition_payloads(self, payloads: list[bytes],
                            kind: str) -> dict[int, list[bytes]]:
        """Owner-rank partition (the Kafka producer partitioner analog).
        Every implementation must route a payload IDENTICALLY — the
        authoritative semantics are the scanner's (it is also how the
        batch decoder reads envelopes). Fast path: ONE native C call
        hashes every token; fallback: the byte-exact Python port in
        native/route_fallback.py. Unroutable payloads (-1) stay local,
        where the engine's dead-letter path owns them."""
        by_rank: dict[int, list[bytes]] = {}
        me = self.rank
        from sitewhere_tpu.native.binding import route_payloads

        # placement-era routing: the native/Python scanners hash the
        # token into the FIXED slot space (same FNV, n_slots instead of
        # n_ranks) and the installed map's slot->rank table resolves the
        # owner — with this rank's fences substituted by their targets,
        # so mid-handoff payloads head for the new owner's durable queue
        slot_rank = self.placement.slot_routing()
        n_slots = len(slot_rank)
        ranks = route_payloads(payloads, n_slots,
                               binary=(kind == "binary"))
        if ranks is not None:
            for p, s in zip(payloads, ranks.tolist()):
                by_rank.setdefault(me if s < 0 else slot_rank[s],
                                   []).append(p)
            return by_rank
        from sitewhere_tpu.native.route_fallback import (route_binary_payload,
                                                         route_json_payload)

        route_one = (route_binary_payload if kind == "binary"
                     else route_json_payload)
        for p in payloads:
            s = route_one(p, n_slots)
            by_rank.setdefault(me if s < 0 else slot_rank[s], []).append(p)
        return by_rank

    def attach_forwarding(self, queue, registry) -> None:
        """Durable cross-rank forwarding (parallel/forward.py): the spill
        QUEUE is this rank's sender-side buffer; the REGISTRY is placed
        on the local engine so the rank's cluster RPC handlers suppress
        redelivered forward ids (register_cluster_rpc binds engines, not
        this facade)."""
        self.forward_queue = queue
        self.local.forward_queue = queue     # rank metrics see the queue
        self.local.spill_registry = registry

    def attach_replication(self, feed, applier) -> None:
        """Event-plane replication (parallel/replication.py): the FEED is
        this rank's leader role (streams WAL-durable batches to its
        followers — placed on the local engine so _wal_append publishes),
        the APPLIER its follower role (standby stores + failover reads).
        Either may be None on asymmetric topologies."""
        self.replica_feed = feed
        self.replica_applier = applier
        self.local.replica_feed = feed
        self.local.replica_applier = applier
        rf = max(getattr(feed, "rf", 1), getattr(applier, "rf", 1))
        self.replication_factor = max(self.replication_factor, rf)

    # ------------------------------------------------- failover read plumbing
    def _try_peer(self, rank: int) -> bool:
        """Spend a real attempt on this rank? Always, until replication
        gives the read path somewhere else to go; with replicas attached
        a DOWN rank is skipped between probe windows so failover reads
        don't pay a connect timeout each."""
        if self.replica_applier is None and self.replica_feed is None:
            return True
        return (not self.health.is_down(rank)
                or self.health.should_probe(rank))

    def _replica_read(self, owner: int, method: str, local_attr: str,
                      **params):
        """Serve a dead owner's partition from its most-caught-up
        follower: the local standby when this rank follows the owner
        (no RPC), else the owner's followers in ring order (ring order
        is also fire-over order, so the first live follower is the one
        already acting for the owner). Returns None when nobody can
        serve."""
        from sitewhere_tpu.parallel.replication import replica_ring

        ring = replica_ring(owner, self.n_ranks, self.replication_factor)
        for f in ring:
            if f == self.rank:
                applier = self.replica_applier
                if applier is None:
                    continue
                res = getattr(applier, local_attr)(owner, **params)
                if res is not None:
                    return res
                continue
            if self.health.is_down(f) and not self.health.should_probe(f):
                continue
            try:
                res = self._peer_call(f, method, leader=owner, **params)
            except (ConnectionError, TimeoutError):
                continue
            if res is not None and not (isinstance(res, dict)
                                        and res.get("unknown")):
                return res
        return None

    def _next_fid(self) -> str:
        """Unique forward id: rank + wall-clock ns + in-process seq —
        unique across restarts without coordination."""
        self._fid_seq += 1
        return f"{self.rank}-{time.time_ns()}-{self._fid_seq}"

    def _adopt_redirect_map(self, e, replier: int) -> None:
        """Converge placement from a ``code=473`` redirect: adopt the
        replier's attached map when its epoch is newer; when OURS is
        newer (the replier missed the commit broadcast), push it so the
        next delivery lands. Either way the higher epoch wins — epochs
        only move forward."""
        data = getattr(e, "data", None) or {}
        peer_map = data.get("map")
        if peer_map is None:
            return
        my_epoch = self.placement.epoch
        if int(peer_map.get("epoch", 0)) > my_epoch:
            self.placement.install(peer_map)
        elif int(peer_map.get("epoch", 0)) < my_epoch:
            try:
                self._peer(replier).call(
                    "Placement.install",
                    map=self.placement.map().to_dict())
            except (ConnectionError, TimeoutError):
                pass

    def _forward_batch(self, r: int, kind: str, plist: list[bytes],
                       tenant: str, _redirected: bool = False) -> dict:
        """One remote sub-batch. With a forward queue attached, delivery
        is durable: tagged for owner-side dedup, spilled on failure
        (returned as {"spilled": n}) instead of raising mid-batch with
        part of the batch already applied locally. Payload bytes ride the
        frame as a RAW attachment blob (protocol.py ATTACH_BIT) — the
        base64-in-JSON form cost ~10x the owner's actual decode."""
        from sitewhere_tpu.rpc.protocol import MAX_FRAME, RpcError

        lens = [len(p) for p in plist]
        if sum(lens) > MAX_FRAME - (1 << 16) and len(plist) > 1:
            # split BEFORE any join so an oversized batch never copies
            # its full byte payload at every recursion level
            mid = len(plist) // 2
            return _merge_counts([
                self._forward_batch(r, kind, plist[:mid], tenant,
                                    _redirected),
                self._forward_batch(r, kind, plist[mid:], tenant,
                                    _redirected)])
        hop = _cluster_instruments()["forward_hop"]
        if self.forward_queue is None:
            from sitewhere_tpu.parallel.placement import REDIRECT_CODE

            method = ("Cluster.ingestJson" if kind == "json"
                      else "Cluster.ingestBinary")
            with self.local.tracer.begin("forward.hop", dst=r,
                                         payloads=len(plist)):
                t0 = time.perf_counter()
                try:
                    res = self._peer(r).call(method, lens=lens,
                                             tenant=tenant,
                                             _attachment=b"".join(plist))
                except RpcError as e:
                    if (getattr(e, "code", None) != REDIRECT_CODE
                            or _redirected):
                        raise
                    # ownership moved under us (no durable queue to
                    # spill into): adopt the replier's map and re-route
                    # the sub-batch once through the normal partitioner
                    self._adopt_redirect_map(e, r)
                    return self._ingest_routed(plist, tenant, kind,
                                               _redirected=True)
                hop.observe(time.perf_counter() - t0, dst=str(r))
            return res
        fid = self._next_fid()
        tracer = self.local.tracer
        if self.forward_queue.circuit_open(r):
            # a known-down peer: spill without paying the connect
            # timeout (or the blob join) per batch; the retry pump
            # closes the circuit
            self.forward_queue.spill(r, kind, tenant, fid,
                                     payloads=plist)
            with tracer.begin("forward.spill", dst=r, fid=fid,
                              reason="circuit_open",
                              payloads=len(plist)):
                pass
            return {"spilled": len(plist)}
        # the with-block (not bare begin/end) closes the span on EVERY
        # exit — an exception type this except-ladder doesn't catch must
        # not leave an open span on the forwarding thread's stack
        with tracer.begin("forward.hop", dst=r,
                          payloads=len(plist)) as hop_sp:
            try:
                t0 = time.perf_counter()
                res = self._peer(r).call(
                    "Cluster.ingestForward", fid=fid, lens=lens,
                    tenant=tenant, encoding=kind,
                    _attachment=b"".join(plist))
                hop.observe(time.perf_counter() - t0, dst=str(r))
                return res
            except (ConnectionError, TimeoutError):
                hop_sp.annotate(error="transport", spilled=True)
                self.forward_queue.trip(r)
                self.forward_queue.spill(r, kind, tenant, fid,
                                         payloads=plist)
                return {"spilled": len(plist)}
            except RpcError as e:
                from sitewhere_tpu.parallel.placement import REDIRECT_CODE

                if getattr(e, "code", None) == REDIRECT_CODE:
                    # ownership redirect (ISSUE 15). MOVED (map
                    # attached): adopt the newer epoch and spill each
                    # payload group toward its CURRENT owner — the
                    # mid-flight re-route. FENCED (commit in flight):
                    # spill back to the same rank with the owner's
                    # short defer; the post-commit redelivery gets the
                    # map and re-routes then.
                    self._adopt_redirect_map(e, r)
                    data = getattr(e, "data", None) or {}
                    if data.get("fenced"):
                        hop_sp.annotate(error="fence_473", spilled=True)
                        self.forward_queue.spill(
                            r, kind, tenant, fid, payloads=plist,
                            defer_s=getattr(e, "retry_after_s", None)
                            or 0.05)
                        return {"spilled": len(plist),
                                "fence_deferred": len(plist)}
                    hop_sp.annotate(error="redirect_473", spilled=True)
                    out = {"redirected": len(plist)}
                    local_ingest = (self.local.ingest_json_batch
                                    if kind == "json"
                                    else self.local.ingest_binary_batch)
                    for r2, pl2 in self._partition_payloads(
                            plist, kind=kind).items():
                        if r2 == self.rank:
                            # a drain moved the slot TO this rank: the
                            # redirected share is ours now — apply it
                            # (under the ingest gate, so a fence racing
                            # in cannot slip this apply past its tail)
                            with self.placement.ingest_gate():
                                out = _merge_counts(
                                    [out, local_ingest(pl2, tenant)])
                        else:
                            self.forward_queue.spill(
                                r2, kind, tenant, self._next_fid(),
                                payloads=pl2)
                            out["spilled"] = (out.get("spilled", 0)
                                              + len(pl2))
                    return out
                if getattr(e, "code", None) == 429:
                    # owner-side load shed (ISSUE 9): the batch is
                    # already accepted at THIS edge, so it spills for
                    # deferred redelivery honoring the OWNER's
                    # Retry-After — an app-level reject by
                    # classification (the retry pump counts it in
                    # retry_app_rejects, never
                    # retry_transport_failures, and never toward the
                    # poison budget). The owner's hint propagates to
                    # the caller as retry_after_s backpressure.
                    ra = getattr(e, "retry_after_s", None)
                    hop_sp.annotate(error="shed_429", spilled=True)
                    self.forward_queue.spill(r, kind, tenant, fid,
                                             payloads=plist,
                                             defer_s=ra)
                    out = {"spilled": len(plist),
                           "shed_deferred": len(plist)}
                    if ra is not None:
                        out["retry_after_s"] = ra
                    return out
                # oversize single payload (unsplittable) or an
                # owner-side application error: spill WITHOUT tripping
                # the circuit (the peer is up) — the retry pump
                # re-attempts and the retry budget dead-letters a
                # poison batch; data is never lost to an exception
                # racing out of a half-applied ingest call
                hop_sp.annotate(error="app_reject", spilled=True)
                self.forward_queue.spill(r, kind, tenant, fid,
                                         payloads=plist)
                return {"spilled": len(plist)}

    def _ingest_routed(self, payloads: list[bytes], tenant: str,
                       kind: str, _redirected: bool = False) -> dict:
        """Shared facade ingest: ONE trace spans the partition, the local
        sub-batch, and every cross-rank forward. The route record lives in
        the local rank's flight recorder; owner-side records join the same
        trace id via the RPC frame's traceparent, so
        `/api/instance/trace/<id>` reconstructs the full journey from any
        rank."""
        from sitewhere_tpu.utils.tracing import (bind_traceparent,
                                                 current_traceparent,
                                                 new_traceparent)

        from sitewhere_tpu.utils.qos import ShedError

        if self.placement.has_fences:
            # a fence window is short (WAL-tail flush + verify): a batch
            # that actually TOUCHES a fenced slot waits the fence out
            # here — costing those payloads the fence DURATION, not a
            # spill/redeliver round trip — while unrelated traffic sails
            # through. On timeout the partitioner's fence-target
            # substitution takes over and the durable queue converges
            # the stragglers.
            from sitewhere_tpu.parallel.placement import _payload_slots

            fenced = set(self.placement.fenced_slots())
            if fenced:
                touched = fenced.intersection(_payload_slots(
                    payloads, kind, self.placement.map().n_slots))
                if touched:
                    self.placement.wait_unfenced(list(touched),
                                                 timeout_s=2.0)
                    if (self.forward_queue is None
                            and set(self.placement.fenced_slots())
                            & touched):
                        # no durable queue to park the frame in: answer
                        # the caller with the typed retryable shed (REST
                        # maps it to 429 + Retry-After) instead of a
                        # doomed redirect loop — the handoff target
                        # cannot accept until the commit epoch lands
                        from sitewhere_tpu.utils.qos import ShedError

                        raise ShedError(
                            f"tenant {tenant!r}: slots {sorted(touched)}"
                            " are mid-handoff and no durable forward "
                            "queue is attached — retry shortly",
                            tenant=tenant, retry_after_s=0.1,
                            reason="handoff_fence")
        tp = current_traceparent() or new_traceparent(self.rank)
        route_rec = self.local.flight.begin(
            "route", tenant=tenant, n_payloads=len(payloads),
            traceparent=tp)
        with bind_traceparent(tp):
            # the ingest gate (placement.py) spans the fence check —
            # the partitioner — and the LOCAL engine apply: a fence
            # registered mid-batch waits for this batch's WAL append
            # before capturing its tail extents. Forwards run OUTSIDE
            # the gate (they apply at their owner, under ITS gate).
            summaries = []
            with self.placement.ingest_gate():
                by_rank = self._partition_payloads(payloads, kind=kind)
                route_rec.mark("commit")   # partition decided
                local_ingest = (self.local.ingest_json_batch
                                if kind == "json"
                                else self.local.ingest_binary_batch)
                qos = getattr(self.local, "qos", None)
                local_plist = by_rank.get(self.rank)
                if qos is not None and local_plist:
                    # the facade IS the edge for its own sub-batch, and
                    # it decides BEFORE any forward leaves this rank: a
                    # local shed refuses the whole call with a typed
                    # ShedError (REST answers 429 + Retry-After) while
                    # nothing has been applied, forwarded, or spilled
                    # yet — the caller retries the full batch. A shed
                    # decided mid-call would instead silently drop the
                    # local payloads next to remote-owned ones the
                    # forward queue durably redelivers.
                    d = qos.admit(tenant, len(local_plist))
                    if not d.admitted:
                        raise ShedError(
                            f"tenant {tenant!r} shed at facade "
                            f"({d.reason}): retry after "
                            f"{d.retry_after_s:.3f}s", tenant=tenant,
                            retry_after_s=d.retry_after_s,
                            reason=d.reason or "shed")
                if local_plist:
                    summaries.append(local_ingest(local_plist, tenant,
                                                  traceparent=tp))
            forwarded = 0
            for r, plist in by_rank.items():
                if r == self.rank:
                    continue
                forwarded += len(plist)
                summaries.append(self._forward_batch(
                    r, kind, plist, tenant, _redirected))
            if forwarded:
                route_rec.add("forwarded", forwarded)
                route_rec.add("forward_ranks",
                              sorted(r for r in by_rank if r != self.rank))
                route_rec.mark("dispatch")   # last forward left this rank
        # retry_after_s is a HINT, not a count: surface the largest one
        # instead of letting the numeric merge sum hints across ranks
        retry_hints = [s.pop("retry_after_s") for s in summaries
                       if isinstance(s, dict) and "retry_after_s" in s]
        merged = _merge_counts(summaries)
        if retry_hints:
            merged["retry_after_s"] = max(retry_hints)
        if route_rec.trace_id is not None:
            route_rec.add_counts(merged)
            merged["trace_id"] = route_rec.trace_id
        return merged

    def ingest_json_batch(self, payloads: list[bytes],
                          tenant: str = "default") -> dict:
        """Partition the batch by owning rank (token-hash, like the Kafka
        producer partitioner) and forward raw remote payloads — WAL,
        decode, and registration happen once, at each owner."""
        return self._ingest_routed(payloads, tenant, kind="json")

    def ingest_binary_batch(self, payloads: list[bytes],
                            tenant: str = "default") -> dict:
        return self._ingest_routed(payloads, tenant, kind="binary")

    def process(self, req, _redirected: bool = False) -> None:
        tok = req.device_token
        if self.placement.has_fences:
            slot = self.placement.slot_of(tok)
            self.placement.wait_unfenced([slot], timeout_s=2.0)
            fences = self.placement.fenced_slots()
            if slot in fences:
                # fence outlived the wait. With a durable queue, park
                # the envelope for the handoff TARGET with a short defer
                # — it owns the slot at the commit epoch and the pump
                # converges via redirects either way. Without one, the
                # target's guard would deterministically refuse until
                # commit, so answer the caller with the typed retryable
                # shed instead of a doomed redirect loop.
                if self.forward_queue is not None:
                    from sitewhere_tpu.ingest.decoders import (
                        envelope_from_request)

                    self.forward_queue.spill(
                        fences[slot], "envelope", req.tenant,
                        self._next_fid(),
                        envelope=envelope_from_request(req),
                        defer_s=0.1)
                    return
                from sitewhere_tpu.utils.qos import ShedError

                raise ShedError(
                    f"device {tok!r}: slot {slot} is mid-handoff and "
                    "no durable forward queue is attached — retry "
                    "shortly", tenant=req.tenant, retry_after_s=0.1,
                    reason="handoff_fence")
            r = self.owner(tok)
        else:
            r = self.owner(tok)
        if r == self.rank:
            with self.placement.ingest_gate():
                return self.local.process(req)
        from sitewhere_tpu.parallel.placement import REDIRECT_CODE
        from sitewhere_tpu.rpc.protocol import RpcError

        from sitewhere_tpu.ingest.decoders import envelope_from_request

        env = envelope_from_request(req)
        if self.forward_queue is None:
            try:
                self._peer(r).call("Cluster.processEnvelope", envelope=env,
                                   tenant=req.tenant)
            except RpcError as e:
                if (getattr(e, "code", None) != REDIRECT_CODE
                        or _redirected):
                    raise
                self._adopt_redirect_map(e, r)
                return self.process(req, _redirected=True)
            return
        fid = self._next_fid()
        if self.forward_queue.circuit_open(r):
            self.forward_queue.spill(r, "envelope", req.tenant, fid,
                                     envelope=env)
            return
        try:
            self._peer(r).call("Cluster.forwardEnvelope", fid=fid,
                               envelope=env, tenant=req.tenant)
        except (ConnectionError, TimeoutError):
            self.forward_queue.trip(r)
            self.forward_queue.spill(r, "envelope", req.tenant, fid,
                                     envelope=env)
        except RpcError as e:
            if getattr(e, "code", None) != REDIRECT_CODE or _redirected:
                raise
            # ownership redirect on the synchronous single-request path:
            # adopt the newer map and re-route once, keeping the
            # all-or-nothing contract (a deterministic refusal at the
            # NEW owner still reaches the caller)
            self._adopt_redirect_map(e, r)
            return self.process(req, _redirected=True)
        # an owner-side application error (RpcError) RAISES here, unlike
        # the batch path's spill: this is the synchronous all-or-nothing
        # single-request contract — a deterministic validation refusal
        # must reach the caller exactly as it does for a locally-owned
        # device, not turn into a false success + a poison spill record
        # that head-of-line blocks the peer's queue until dead-letter

    def _fanout_keyed(self, local_result, method: str,
                      tolerant: bool = False, ranks=None,
                      **params) -> dict:
        """Local result + the same call on every peer, keyed by rank —
        the one idiom behind flush/metrics/sweeps/status; timeout,
        parallelism, and down-peer policy live here once. ``tolerant``
        marks an unreachable peer with a ``PeerDown`` sentinel (checking
        the forward circuit first, so a known-dead peer costs nothing)
        instead of raising — the scrape surfaces must degrade, queries
        must stay loud. ``ranks`` narrows the sweep (data surfaces pass
        ``_data_ranks()`` so a drained rank's departure never fails a
        query; status surfaces keep the full provisioned set)."""
        out = {self.rank: local_result}
        for r in (range(self.n_ranks) if ranks is None else ranks):
            if r == self.rank:
                continue
            if (tolerant and self.forward_queue is not None
                    and self.forward_queue.circuit_open(r)):
                out[r] = PeerDown("forward circuit open")
                continue
            try:
                out[r] = self._peer(r).call(method, **params)
            except (ConnectionError, TimeoutError) as e:
                if not tolerant:
                    raise
                out[r] = PeerDown(str(e))
        return out

    def _fanout(self, local_result, method: str, ranks=None,
                **params) -> list:
        """List form of ``_fanout_keyed`` (local first, then peers)."""
        return list(self._fanout_keyed(local_result, method, ranks=ranks,
                                       **params).values())

    def flush(self) -> dict:
        """Flush every DATA rank — after this, queries anywhere see
        everything accepted anywhere (the test/REST consistency
        point)."""
        out = self._fanout(self.local.flush(), "Cluster.flush",
                           ranks=self._data_ranks())
        return _merge_counts([s for s in out if s])

    # ---------------------------------------------------------------- admin
    def register_device(self, token: str, device_type: str | None = None,
                        tenant: str = "default", area: str | None = None,
                        customer: str | None = None,
                        metadata: dict | None = None):
        r = self.owner(token)
        if r == self.rank:
            return self.local.register_device(token, device_type, tenant,
                                              area, customer, metadata)
        self._peer(r).call("Cluster.registerDevice", token=token,
                           deviceType=device_type, tenant=tenant, area=area,
                           customer=customer, metadata=metadata)

    def update_device(self, token: str, device_type: str | None = None,
                      area: str | None = None, customer: str | None = None,
                      metadata: dict | None = None):
        r = self.owner(token)
        if r == self.rank:
            return self.local.update_device(token, device_type, area,
                                            customer, metadata)
        res = self._peer(r).call(
            "Cluster.updateDevice", token=token, deviceType=device_type,
            area=area, customer=customer, metadata=metadata)
        if res is None:
            raise KeyError(token)

    def delete_device(self, token: str) -> bool:
        return self._route(
            token, lambda: self.local.delete_device(token),
            "Cluster.deleteDevice", token=token)

    # ---------------------------------------------------------------- reads
    def get_device(self, token: str) -> DeviceInfo | None:
        d = self._route(token, lambda: self.local.get_device(token),
                        "Cluster.getDevice", token=token)
        if d is None or isinstance(d, DeviceInfo):
            return d
        return DeviceInfo(**d)

    def list_assignments(self, device_token: str | None = None,
                         **kw) -> list[AssignmentInfo]:
        if device_token is not None:
            res = self._route(
                device_token,
                lambda: self.local.list_assignments(device_token, **kw),
                "Cluster.listAssignments", token=device_token, **kw)
            return [a if isinstance(a, AssignmentInfo) else
                    AssignmentInfo(**a) for a in res]
        parts = self._fanout(self.local.list_assignments(None, **kw),
                             "Cluster.listAssignments",
                             ranks=self._data_ranks(), token=None, **kw)
        return [a if isinstance(a, AssignmentInfo) else AssignmentInfo(**a)
                for part in parts for a in part]

    def get_device_state(self, token: str) -> dict | None:
        """Owner-routed read with failover: when the owner rank is
        unreachable, the most-caught-up follower serves its standby copy
        with an explicit ``stale_ms`` watermark."""
        r = self.owner(token)
        if r == self.rank:
            return self.local.get_device_state(token)
        err: Exception | None = None
        if self._try_peer(r):
            try:
                return self._peer_call(r, "Cluster.getDeviceState",
                                       token=token)
            except (ConnectionError, TimeoutError) as e:
                err = e
        res = self._replica_read(r, "Cluster.replicaDeviceState",
                                 "device_state", token=token)
        if res is None:
            raise err if err is not None else ConnectionError(
                f"rank {r} down and no replica holds its partition")
        if res.get("missing"):
            return None
        return res

    # ----------------------------------------------------- assignments
    # Assignments live at their DEVICE's owner rank (they expand on its
    # shards), but assignment TOKENS don't encode the device — writes
    # route by device token, by-token reads/updates resolve local-first
    # then ask peers (Assignments.java REST surface, any-rank semantics).
    def _as_info(self, a) -> AssignmentInfo | None:
        if a is None or isinstance(a, AssignmentInfo):
            return a
        return AssignmentInfo(**a)

    def create_assignment(self, device_token: str, token: str | None = None,
                          asset: str | None = None, area: str | None = None,
                          customer: str | None = None,
                          metadata: dict | None = None) -> AssignmentInfo:
        info = self._as_info(self._route(
            device_token,
            lambda: self.local.create_assignment(device_token, token,
                                                 asset, area, customer,
                                                 metadata),
            "Cluster.createAssignment", deviceToken=device_token,
            token=token, asset=asset, area=area, customer=customer,
            metadata=metadata))
        self._cache_assignment_rank(info.token, self.owner(device_token))
        return info

    def _cache_assignment_rank(self, token: str, rank: int) -> None:
        if len(self._assignment_ranks) > 65536:
            self._assignment_ranks.clear()   # cap: a cache, not a table
        self._assignment_ranks[token] = rank

    def _assignment_rank(self, token: str) -> "int | None":
        cached = self._assignment_ranks.get(token)
        if cached is not None:
            return cached
        if self.local.get_assignment(token) is not None:
            self._cache_assignment_rank(token, self.rank)
            return self.rank
        for r in self._data_ranks():
            if r != self.rank and self._peer(r).call(
                    "Cluster.getAssignment", token=token) is not None:
                self._cache_assignment_rank(token, r)
                return r
        return None

    def get_assignment(self, token: str) -> AssignmentInfo | None:
        cached = self._assignment_ranks.get(token)
        if cached is not None and cached != self.rank:
            d = self._peer(cached).call("Cluster.getAssignment",
                                        token=token)
            if d is None:
                self._assignment_ranks.pop(token, None)   # deleted
            return self._as_info(d)
        a = self.local.get_assignment(token)
        if a is not None:
            self._cache_assignment_rank(token, self.rank)
            return a
        for r in self._data_ranks():
            if r != self.rank:
                d = self._peer(r).call("Cluster.getAssignment", token=token)
                if d is not None:
                    self._cache_assignment_rank(token, r)
                    return self._as_info(d)
        return None

    def _assignment_op(self, token: str, local_fn, method: str, **params):
        r = self._assignment_rank(token)
        if r is None:
            raise KeyError(f"assignment {token!r} not found")
        if r == self.rank:
            return local_fn()
        return self._peer(r).call(method, token=token, **params)

    def release_assignment(self, token: str) -> AssignmentInfo:
        return self._as_info(self._assignment_op(
            token, lambda: self.local.release_assignment(token),
            "Cluster.releaseAssignment"))

    def mark_assignment_missing(self, token: str) -> AssignmentInfo:
        return self._as_info(self._assignment_op(
            token, lambda: self.local.mark_assignment_missing(token),
            "Cluster.markAssignmentMissing"))

    def update_assignment(self, token: str, asset: str | None = None,
                          area: str | None = None,
                          customer: str | None = None,
                          metadata: dict | None = None) -> AssignmentInfo:
        return self._as_info(self._assignment_op(
            token,
            lambda: self.local.update_assignment(token, asset, area,
                                                 customer, metadata),
            "Cluster.updateAssignment", asset=asset, area=area,
            customer=customer, metadata=metadata))

    def delete_assignment(self, token: str) -> bool:
        r = self._assignment_rank(token)
        if r is None:
            return False
        self._assignment_ranks.pop(token, None)
        if r == self.rank:
            return self.local.delete_assignment(token)
        return self._peer(r).call("Cluster.deleteAssignment", token=token)

    def search_device_states(self, **kw) -> list[dict]:
        out = self.placement.filter_rows(
            list(self.local.search_device_states(**kw)), key="device")
        for r in self._data_ranks():
            if r == self.rank:
                continue
            part, err = None, None
            if self._try_peer(r):
                try:
                    part = self._peer_call(r, "Cluster.searchDeviceStates",
                                           **kw)
                except (ConnectionError, TimeoutError) as e:
                    err = e
            if part is None:
                # a dead rank's slice comes from its follower's standby
                # (rows carry stale_ms); queries stay loud only when
                # NOBODY can serve the partition
                part = self._replica_read(r, "Cluster.replicaSearchStates",
                                          "search_states", **kw)
                if part is None:
                    raise err if err is not None else ConnectionError(
                        f"rank {r} down and no replica holds its "
                        "partition")
            out.extend(part)
        limit = kw.get("limit")
        if limit is not None:
            out = out[:limit]
        return out

    def query_events(self, **kw) -> dict:
        """Fan out to every rank, merge newest-first — the cross-partition
        query the reference's REST tier performs over per-service gRPC.
        String filters (device/tenant/area/customer/alternate_id) resolve
        per rank; raw interner-id filters cannot cross ranks."""
        if kw.get("aux0") is not None or kw.get("aux1") is not None:
            raise ValueError(
                "aux0/aux1 are rank-local interner ids and mean different "
                "strings on other ranks — use command_responses() or "
                "alternate_id instead")
        results = [_placement_filtered_query(self.local, kw)]
        stale_ms = None
        for r in self._data_ranks():
            if r == self.rank:
                continue
            res, err = None, None
            if self._try_peer(r):
                try:
                    res = self._peer_call(r, "Cluster.queryEvents", **kw)
                except (ConnectionError, TimeoutError) as e:
                    err = e
            if res is None:
                # owner unreachable: its partition serves from the most-
                # caught-up follower's standby, and the merged response
                # carries the replica's staleness watermark
                res = self._replica_read(r, "Cluster.replicaQueryEvents",
                                         "query_events", **kw)
                if res is None:
                    raise err if err is not None else ConnectionError(
                        f"rank {r} down and no replica holds its "
                        "partition")
                stale_ms = max(stale_ms or 0.0,
                               float(res.get("stale_ms", 0.0)))
            results.append(res)
        events = [e for res in results for e in res["events"]]
        events.sort(key=event_order_key)
        limit = kw.get("limit", 100)
        out = {"total": sum(res["total"] for res in results),
               "events": events[:limit]}
        if stale_ms is not None:
            # explicit degradation marker: part of this result is a
            # follower's standby view, at most stale_ms behind the acked
            # history of the dead owner
            out["stale_ms"] = stale_ms
        return out

    def get_event(self, event_id: int,
                  tenant: str | None = None) -> dict | None:
        """Cluster-global by-id lookup: ids are ``local * n_ranks + rank``
        so the owning rank is recoverable from the id alone."""
        if event_id < 0:
            return None
        r = event_id % self.n_ranks
        local_id = event_id // self.n_ranks
        if r == self.rank:
            ev = self.local.get_event(local_id, tenant=tenant)
        else:
            ev = self._peer(r).call("Cluster.getEvent", eventId=local_id,
                                    tenant=tenant)
        if ev is not None:
            ev["eventId"] = event_id
        return ev

    def get_trace(self, trace_id: str) -> dict:
        """Cluster-wide trace resolution: a batch forwarded across ranks
        left lifecycle records on EVERY rank it touched, all under one
        trace id — collect them from the local recorder plus every
        reachable peer (tolerant: a down rank degrades the view, it
        must not 500 the trace endpoint)."""
        keyed = self._fanout_keyed(
            self.local.flight.records_of(trace_id), "Cluster.traceGet",
            tolerant=True, traceId=trace_id)
        records: list[dict] = []
        for r, res in keyed.items():
            if isinstance(res, PeerDown) or not res:
                continue
            records.extend(res)
        records.sort(key=lambda d: d.get("startedMs", 0))
        return {"traceId": trace_id, "records": records}

    def recent_traces(self, limit: int = 50) -> list[dict]:
        """This rank's recent batch records (per-rank surface, like the
        reference scraping one replica; cross-rank journeys resolve via
        get_trace)."""
        return self.local.flight.recent(limit)

    def get_trace_timeline(self, trace_id: str) -> dict:
        """One trace id -> ONE stitched multi-rank Chrome-trace timeline
        (ISSUE 10): each rank contributes its local events (flight-record
        lifecycle intervals + live spans, pid = rank) through the same
        tolerant fan-out as get_trace, and the merge renumbers pids/tids
        with process/thread metadata so Perfetto shows one lane group per
        rank. A down rank degrades the view; it must not 500 the
        endpoint."""
        from sitewhere_tpu.utils.tracing import (finish_timeline,
                                                 timeline_events)

        keyed = self._fanout_keyed(
            timeline_events(self.local, trace_id),
            "Cluster.traceTimeline", tolerant=True, traceId=trace_id)
        events: list[dict] = []
        for r, res in keyed.items():
            if isinstance(res, PeerDown) or not res:
                continue
            events.extend(res)
        return finish_timeline(trace_id, events)

    def make_feed_consumer(self, group_id: str, **kw):
        """Rank-local feed (outbound connectors run per-rank over the
        rank's partition, exactly as the reference's connector hosts
        consume per-partition Kafka groups), with event ids translated to
        the cluster-global space."""
        return _ClusterFeed(self.local.make_feed_consumer(group_id, **kw),
                            self.rank, self.n_ranks)

    def presence_sweep(self) -> list[str]:
        """Cluster-wide presence sweep: each rank marks ITS devices
        missing (per-partition, like the reference's per-tenant-engine
        DevicePresenceManager); one trigger reaches every rank so the
        REST admin surface behaves identically from any node. The
        per-rank BACKGROUND loop should sweep its local engine only —
        N ranks each fanning out would sweep N^2 times per interval."""
        return [t for part in self._fanout(
            self.local.presence_sweep(), "Cluster.presenceSweep",
            ranks=self._data_ranks())
            for t in part]

    def presence_sweep_local(self) -> list[str]:
        """This rank's sweep only — what the per-rank background loop
        calls (the N^2-avoidance policy lives HERE, not in the web
        tier)."""
        return self.local.presence_sweep()

    def attach_command_service(self, svc) -> None:
        """Wire this rank's command-delivery service into the cluster
        surface: remotely-routed invocations land in ITS pending set so
        the rank's own delivery pump can deliver them (per-partition
        consumers, reference-style). Placed on the local engine so the
        rank's RPC server can reach it."""
        self.command_service = svc
        self.local.command_service = svc

    def tag_invocation_id(self, local_id: int) -> int:
        """Cluster-global invocation id: ``local * n_ranks + rank`` (the
        event-id scheme) — histories/pending sets/device acks can never
        collide across ranks."""
        return local_id * self.n_ranks + self.rank

    def command_responses(self, invocation_id: str,
                          limit: int = 100) -> list[dict]:
        """Command responses for one invocation, resolved PER RANK: the
        originating-id string interns into each rank's own id space, so
        the integer must never cross rank boundaries."""
        from sitewhere_tpu.commands.service import local_command_responses

        parts = self._fanout(
            local_command_responses(self.local, invocation_id, limit),
            "Cluster.commandResponses", ranks=self._data_ranks(),
            invocationId=invocation_id, limit=limit)
        docs = [d for part in parts for d in part]
        docs.sort(key=event_order_key)
        return docs[:limit]

    def fetch_invocation(self, invocation_id: int):
        """Resolve an invocation this rank never saw at its OWNING rank
        (the rank-tagged id encodes it) — GET /api/invocations/{id}
        answers identically from every rank, not just originator/owner."""
        from sitewhere_tpu.commands.model import CommandInvocation

        r = invocation_id % self.n_ranks
        if r == self.rank:
            return _owned_invocation(self.local, invocation_id)
        d = self._peer(r).call("Cluster.getInvocation",
                               invocationId=invocation_id)
        return CommandInvocation(**d) if d is not None else None

    def route_invocation(self, inv) -> "int | None":
        """Route a command invocation to its device's owning rank.
        Returns the owner-assigned invocation id, or None when the device
        is local (the caller stages it as usual)."""
        r = self.owner(inv.device_token)
        if r == self.rank:
            return None
        res = self._peer(r).call("Cluster.invokeCommand",
                                 invocation=dataclasses.asdict(inv))
        return int(res["invocationId"])

    def _stage_row(self, et, token_id, tenant_id, ts, now, values, mask,
                   aux0, aux1):
        """Direct row staging must never silently persist a remote-owned
        device's event on the wrong rank (the product paths — process(),
        ingest, route_invocation — all route BEFORE staging; this guards
        any other direct caller)."""
        tid = int(token_id)
        tok = (self.local.tokens.token(tid)
               if 0 <= tid < len(self.local.tokens) else None)
        if tok is not None and self.owner(tok) != self.rank:
            raise NotImplementedError(
                f"direct staging for {tok!r} (owned by rank "
                f"{self.owner(tok)}) would persist on the wrong rank — "
                "use the routed surfaces (process/ingest/invoke)")
        return self.local._stage_row(et, token_id, tenant_id, ts, now,
                                     values, mask, aux0, aux1)

    def attach_search_index(self, index) -> None:
        """Wire this rank's embedded event-search index into the cluster
        surface (each rank's connector indexes ITS partition — all-rank
        queries need the fan-out, like every replica feeding one Solr).
        Also placed on the local engine so the rank's cluster RPC server
        (bound to the engine) can serve Cluster.searchEvents."""
        self.search_index = index
        self.local.search_index = index

    def search_events(self, query: str,
                      max_results: int = 100) -> "list[dict] | None":
        """All-rank event search: fan out to every rank's embedded index,
        merge newest-first. Returns None when no index is attached here
        (the caller falls back to its local provider); a PEER without an
        index fails the call loudly — a silent partial merge would read
        as complete."""
        if self.search_index is None:
            return None
        data_ranks = self._data_ranks()
        parts = self._fanout(
            self.search_index.search(query, max_results,
                                     order="eventDate"),
            "Cluster.searchEvents", ranks=data_ranks, query=query,
            maxResults=max_results)
        for r, part in zip([self.rank] + [r for r in data_ranks
                                          if r != self.rank], parts):
            if part is None:
                raise RuntimeError(
                    f"cluster search incomplete: rank {r} has no search "
                    "index attached")
        docs = [d for part in parts for d in part]
        docs.sort(key=event_order_key)
        return docs[:max_results]

    # metric keys that merge as MAX, not sum (ages/watermarks: a summed
    # "oldest" is an age no spill has)
    _MAX_MERGED = ("forward_queue_oldest_ms", "replica_max_stale_ms",
                   "forward_dedup_horizon_age_ms")

    def metrics(self) -> dict:
        """Cluster-merged counters PLUS per-rank attribution: the summed
        view answers "how much", ``by_rank`` answers "which rank is hot"
        (VERDICT r4 item 7 — a sum that loses the hot rank hides every
        imbalance). Rank-local extras (forward queue, entity replication)
        ride each rank's own metrics via ``local_rank_metrics``. A DOWN
        peer degrades to an ``unreachable`` entry instead of failing the
        whole scrape — the operator needs this surface most exactly when
        a rank is missing."""
        keyed = self._fanout_keyed(local_rank_metrics(self.local),
                                   "Cluster.metrics", tolerant=True)
        up = {str(r): m for r, m in keyed.items()
              if not isinstance(m, PeerDown)}
        merged = _merge_counts(list(up.values()))
        for key in self._MAX_MERGED:
            vals = [m[key] for m in up.values() if key in m]
            if vals:
                merged[key] = max(vals)
        merged["by_rank"] = dict(up)
        for r, m in keyed.items():
            if isinstance(m, PeerDown):
                merged["by_rank"][str(r)] = {"unreachable": 1,
                                             "reason": m.reason}
        return merged

    def tenant_metrics(self) -> dict:
        """Cluster-wide per-tenant event counts (each rank counts ITS
        partition; sums merge) — the Prometheus per-tenant series must
        cover the same corpus as the rank=\"all\" counters on the same
        page. Down peers degrade like metrics()."""
        keyed = self._fanout_keyed(self.local.tenant_metrics(),
                                   "Cluster.tenantMetrics", tolerant=True,
                                   ranks=self._data_ranks())
        merged: dict[str, dict[str, int]] = {}
        for res in keyed.values():
            if isinstance(res, PeerDown):
                continue
            for ten, counts in res.items():
                slot = merged.setdefault(ten, {})
                for etype, n in counts.items():
                    slot[etype] = slot.get(etype, 0) + n
        return merged

    def cluster_metrics(self) -> str:
        """ONE federated Prometheus exposition for the whole cluster,
        served from any rank (ISSUE 7): every live rank exports its own
        engine into its registry and ships the text; samples re-export
        under a ``rank`` label with HELP/TYPE deduped across ranks, and
        histogram bucket lines keep their trace-id exemplars. A DOWN
        rank degrades to ``swtpu_cluster_rank_up{rank=...} 0`` instead
        of failing the scrape — the operator needs this surface most
        exactly when a rank is missing."""
        from sitewhere_tpu.utils.metrics import (REGISTRY, _escape_label,
                                                 export_engine_metrics,
                                                 federate_expositions)

        export_engine_metrics(self.local)
        local_text = REGISTRY.expose_text(exemplars=True)
        keyed = self._fanout_keyed(local_text, "Cluster.metricsText",
                                   tolerant=True)
        parts = {r: t for r, t in keyed.items()
                 if not isinstance(t, PeerDown)}
        lines = [federate_expositions(parts).rstrip("\n"),
                 "# HELP swtpu_cluster_rank_up 1 if the rank answered "
                 "the federated scrape",
                 "# TYPE swtpu_cluster_rank_up gauge"]
        for r in sorted(keyed):
            up = 0 if isinstance(keyed[r], PeerDown) else 1
            lines.append(
                f'swtpu_cluster_rank_up{{rank="{_escape_label(r)}"}} {up}')
        _cluster_instruments()["scrapes"].inc()
        return "\n".join(lines) + "\n"

    def conservation(self) -> dict:
        """Cluster-wide conservation audit (ISSUE 14): every live
        rank's ledger + verdict under its rank key, plus a cluster
        roll-up of the violation count. Rank ledgers are NEVER merged
        into one snapshot — each rank's equations balance against its
        own device counters; a DOWN rank degrades to an ``unreachable``
        entry instead of failing the audit surface."""
        from sitewhere_tpu.utils.conservation import conservation_payload

        keyed = self._fanout_keyed(conservation_payload(self),
                                   "Cluster.conservation", tolerant=True,
                                   ranks=self._data_ranks())
        by_rank: dict[str, dict] = {}
        violations = 0
        for r, res in keyed.items():
            if isinstance(res, PeerDown):
                by_rank[str(r)] = {"unreachable": True,
                                   "reason": res.reason}
            else:
                by_rank[str(r)] = res
                violations += len(res.get("violations", ()))
        return {"clustered": self.n_ranks > 1, "rank": self.rank,
                "byRank": by_rank, "violations": violations,
                "balanced": violations == 0}

    def spmd_heat(self) -> dict:
        """Cluster-wide shard heat & skew (ISSUE 18): every live rank's
        heat document under its rank key (rank-labeled federation, the
        conservation() shape). Heat maps never merge — each rank's
        shards are its own mesh; a DOWN rank degrades to an
        ``unreachable`` entry."""
        from sitewhere_tpu.utils.shardobs import spmd_heat_payload

        keyed = self._fanout_keyed(spmd_heat_payload(self),
                                   "Cluster.spmdHeat", tolerant=True,
                                   ranks=self._data_ranks())
        by_rank: dict[str, dict] = {}
        spmd_any = False
        for r, res in keyed.items():
            if isinstance(res, PeerDown):
                by_rank[str(r)] = {"unreachable": True,
                                   "reason": res.reason}
            else:
                by_rank[str(r)] = res
                spmd_any = spmd_any or bool(res.get("spmd"))
        return {"clustered": self.n_ranks > 1, "rank": self.rank,
                "spmd": spmd_any, "byRank": by_rank}

    def cluster_status(self) -> dict:
        """The operator's cluster page: this rank's identity, every
        rank's reachability + device count, and the durability gauges.
        A peer with an OPEN forward circuit reports DOWN without paying
        a connect timeout on the scrape."""
        keyed = self._fanout_keyed(len(_owned_device_infos(self.local)),
                                   "Cluster.deviceCount", tolerant=True)
        ranks: dict[str, dict] = {}
        for r, res in keyed.items():
            if isinstance(res, PeerDown):
                ranks[str(r)] = {"status": "DOWN", "local": False,
                                 "reason": res.reason}
            else:
                ranks[str(r)] = {"status": "UP", "local": r == self.rank,
                                 "devices": res}
        out = {"clustered": self.n_ranks > 1, "rank": self.rank,
               "nRanks": self.n_ranks,
               "peers": list(self.cluster_config.peers), "ranks": ranks,
               "activeRanks": self.placement.map().active_ranks(),
               "placementEpoch": self.placement.epoch,
               "owned_devices": len(_owned_device_infos(self.local))}
        if self.forward_queue is not None:
            out["forwarding"] = self.forward_queue.metrics()
        rep = getattr(self, "entity_replicator", None)
        if rep is not None:
            out["entities"] = rep.metrics()
        # explicit health states (up/suspect/down) + replication posture:
        # the operator's first stop during a partition event. The
        # per-LEADER staleness watermarks ride the health block so a
        # single lagging follower is visible here before a failover
        # read ever hits it (same series as
        # swtpu_replication_stale_ms{leader=...}).
        out["health"] = {"peers": self.health.snapshot()}
        if self.replica_applier is not None:
            out["health"]["replicationStaleMs"] = {
                str(r): ms
                for r, ms in self.replica_applier.stale_by_leader().items()}
        out["replicationFactor"] = self.replication_factor
        if self.replica_feed is not None:
            out["replicaFeed"] = self.replica_feed.metrics()
        if self.replica_applier is not None:
            out["replicaStandbys"] = self.replica_applier.standbys_status()
        return out

    @property
    def devices(self) -> _MergedDevices:
        return _MergedDevices(self)


class ClusterSearchProvider:
    """The cluster-wide face of the embedded event index: same
    ``.search``/``.info`` surface as EventSearchIndex, backed by the
    all-rank fan-out — the instance registers THIS as its "embedded"
    provider so the REST tier stays a pure provider lookup with no
    engine-topology branches."""

    def __init__(self, cluster: ClusterEngine, local_index):
        self._cluster = cluster
        self._local = local_index

    @property
    def provider_id(self) -> str:
        return self._local.provider_id

    @provider_id.setter
    def provider_id(self, value: str) -> None:
        self._local.provider_id = value

    @property
    def info(self):
        """Cluster-wide provider info: ``docs`` sums every rank's corpus
        (the listing must describe what ``search()`` actually searches,
        not the local slice). A peer whose index isn't attached yet
        counts 0, and an UNREACHABLE peer is skipped — the listing is a
        health surface, not a query, so it must not raise (search()
        itself stays loud about incomplete merges)."""
        from sitewhere_tpu.search.index import SearchProviderInfo

        c = self._cluster
        docs = len(self._local.docs)
        for r in range(c.n_ranks):
            if r == c.rank:
                continue
            try:
                docs += c._peer(r).call("Cluster.searchInfo") or 0
            except (ConnectionError, TimeoutError):
                pass
        return SearchProviderInfo(
            provider_id=self._local.provider_id,
            name="Embedded event index (cluster)", docs=docs)

    def search(self, query: str, max_results: int = 100) -> list[dict]:
        docs = self._cluster.search_events(query, max_results)
        if docs is None:   # facade has no index attached: local behavior
            return self._local.search(query, max_results)
        return docs


class PeerDown:
    """Tolerant-fanout sentinel: the peer at this rank was unreachable."""

    def __init__(self, reason: str):
        self.reason = reason


def local_rank_metrics(engine) -> dict:
    """One rank's full metric set: engine counters plus the durability
    components attached to it (forward queue, spill registry, entity
    replicator) — the single source both the facade's local leg and the
    Cluster.metrics RPC handler report, so every rank's entry in
    ``by_rank`` carries the same schema."""
    m = engine.metrics()
    fq = getattr(engine, "forward_queue", None)
    if fq is not None:
        m.update(fq.metrics())
    reg = getattr(engine, "spill_registry", None)
    if reg is not None:
        m.update(reg.metrics())
    rep = getattr(engine, "entity_replicator", None)
    if rep is not None:
        m.update(rep.metrics())
    feed = getattr(engine, "replica_feed", None)
    if feed is not None:
        m.update(feed.metrics())
    applier = getattr(engine, "replica_applier", None)
    if applier is not None:
        m.update(applier.metrics())
    return m


def _placement_filtered_query(engine, kw: dict) -> dict:
    """Event query with the placement read-side filter applied (ISSUE
    15): after a slot moves away, this rank's dead copies must not
    double-count in fan-out merges. A device-token query for a
    not-owned token short-circuits to an empty page (exact); a global
    query filters its page rows and subtracts them from the total
    (best-effort — the device-side total cannot cheaply exclude dead
    rows, so post-move global totals are an upper bound until the
    source compacts). Zero-cost until the first move ever lands."""
    pm = getattr(engine, "placement", None)
    if pm is None or not pm.ever_moved:
        return engine.query_events(**kw)
    tok = kw.get("device_token")
    if tok is not None and not pm.owns_token(tok):
        return {"total": 0, "events": []}
    res = engine.query_events(**kw)
    events = pm.filter_rows(res.get("events", []))
    dropped = len(res.get("events", [])) - len(events)
    if dropped:
        res = dict(res, events=events,
                   total=max(0, int(res.get("total", 0)) - dropped))
    return res


def _owned_device_infos(engine) -> list:
    """This rank's device mirror restricted to tokens it still OWNS
    (the moved-away entries stay in the mirror as dead records until
    compaction; listing them would double-count against the new
    owner's copy)."""
    infos = list(engine.devices.values())
    pm = getattr(engine, "placement", None)
    if pm is None or not pm.ever_moved:
        return infos
    m = pm.map()
    me = pm.cluster.rank
    return [i for i in infos if m.owner(i.token) == me]


def _owned_invocation(engine, invocation_id: int):
    """The owner-side invocation lookup (one copy for the facade's local
    branch and the Cluster.getInvocation RPC handler)."""
    svc = getattr(engine, "command_service", None)
    return svc.history.get(invocation_id) if svc is not None else None


def replay_wal_through(cluster: ClusterEngine, wal_dir,
                       after_cursor: int = -1) -> int:
    """Replay one (foreign, read-only) rank WAL through the cluster
    router: every record re-routes to its owner under the CURRENT
    partitioner and re-logs in that owner's live WAL. This is the
    rank-count elasticity tool — changing n_ranks re-partitions devices
    (ownership is token-hash % n_ranks, Kafka partition semantics), and
    replaying every old rank's WAL into a fresh cluster migrates the
    whole history exactly once per event to its new owner (the consumer-
    group re-partition-by-replay analog; SURVEY §5.4). Returns records
    replayed.

    PRECONDITION: the source WAL must be complete (never pruned) — replay
    IS the history. A log whose oldest segment was pruned after a
    snapshot no longer carries the full stream, and replaying only its
    tail would silently drop the snapshot-covered events; that case is
    refused."""
    import pathlib

    from sitewhere_tpu.utils.checkpoint import replay_records
    from sitewhere_tpu.utils.ingestlog import IngestLog

    segs = sorted(pathlib.Path(wal_dir).glob("segment-*.log"))
    if segs and int(segs[0].stem.split("-")[1]) != 0:
        raise ValueError(
            f"WAL {wal_dir} was pruned (oldest segment is {segs[0].name}): "
            "it no longer carries the full history — reshard_cluster "
            "needs complete WALs (disable pruning on clusters that want "
            "rank-count elasticity by replay)")
    wal = IngestLog(wal_dir, readonly=True)
    try:
        count = replay_records(wal, cluster.ingest_json_batch,
                               cluster.ingest_binary_batch,
                               after_cursor=after_cursor)
    finally:
        wal.close()
    cluster.flush()
    return count


def reshard_cluster(cluster: ClusterEngine, old_wal_dirs) -> int:
    """Migrate an old cluster's full history into ``cluster`` (fresh
    ranks, any new rank count) by replaying every old rank's WAL through
    the new partitioner. Run from ONE rank; forwarding distributes the
    records. Returns total records replayed."""
    return sum(replay_wal_through(cluster, d) for d in old_wal_dirs)


def cluster_system_jwt(secret: str) -> str:
    """System token for cross-rank calls, minted from the shared cluster
    secret (the reference's system-user JWT context)."""
    from sitewhere_tpu.instance.auth import DEFAULT_ROLES, JwtService

    return JwtService(secret=secret.encode(), expiration_s=24 * 3600)\
        .generate("cluster-system", DEFAULT_ROLES["admin"])


def register_cluster_rpc(srv, engine: DistributedEngine) -> None:
    """Register the cross-rank data/admin plane over the LOCAL engine —
    the per-service gRPC surface peers dispatch into
    (DeviceStateRouter.java:62-72). Handlers bind to the concrete engine,
    never the ClusterEngine facade, so routed calls cannot recurse."""

    def _admit(tenant: str, n: int) -> None:
        """Owner-side admission (ISSUE 9): the OWNER of a forwarded
        batch enforces its tenant buckets/saturation valve — shedding at
        the edge rank alone would let forwards bypass the owner's
        discipline. A shed raises a typed ``code=429`` RpcError carrying
        the owner's Retry-After, which the sender's ForwardQueue
        classifies as an APP reject (deferred + retried, never a
        transport failure, never poison-dead-lettered)."""
        qos = getattr(engine, "qos", None)
        if qos is None:
            return
        d = qos.admit(tenant or "default", n)
        if not d.admitted:
            from sitewhere_tpu.rpc.protocol import RpcError

            raise RpcError(
                f"tenant {tenant!r} shed at owner ({d.reason}): retry "
                f"after {d.retry_after_s:.3f}s", 429,
                retry_after_s=d.retry_after_s)

    def _guard_payloads(plist: list, kind: str) -> None:
        """Owner-side placement guard (ISSUE 15): a batch containing
        any slot this rank does not currently own (or is fencing)
        redirects WHOLE with a typed code=473 BEFORE anything applies
        — the no-dual-ownership half of the handoff protocol. Runs
        before admission so a redirected batch burns no tokens."""
        pm = getattr(engine, "placement", None)
        if pm is not None:
            pm.guard_payloads(plist, kind)

    def _guard_tokens(tokens) -> None:
        pm = getattr(engine, "placement", None)
        if pm is not None:
            pm.guard_tokens(tokens)

    import contextlib

    def _gate():
        """The owner-side ingest gate (placement.py): the guard check
        and the engine apply happen under one in-flight token, so a
        fence registered between them waits for this batch's WAL
        append before shipping its tail."""
        pm = getattr(engine, "placement", None)
        return pm.ingest_gate() if pm is not None \
            else contextlib.nullcontext()

    def ingest_json(payloads: list = None, tenant: str = "default",
                    lens: list = None, _attachment: bytes = None):
        plist = _wire_payloads(payloads, lens, _attachment)
        with _gate():
            _guard_payloads(plist, "json")
            _admit(tenant, len(plist))
            return engine.ingest_json_batch(plist, tenant)

    def ingest_binary(payloads: list = None, tenant: str = "default",
                      lens: list = None, _attachment: bytes = None):
        plist = _wire_payloads(payloads, lens, _attachment)
        with _gate():
            _guard_payloads(plist, "binary")
            _admit(tenant, len(plist))
            return engine.ingest_binary_batch(plist, tenant)

    def ingest_forward(fid: str, payloads: list = None,
                       tenant: str = "default", encoding: str = "json",
                       lens: list = None, _attachment: bytes = None):
        """Tagged forward: the id registry suppresses redeliveries (a
        retry after a lost response or a sender/owner restart must not
        double-ingest). Record AFTER ingest: a crash in between costs a
        duplicate (at-least-once), never a loss. A fid OLDER than the
        registry's eviction watermark can no longer be proven un-applied
        — it dead-letters (preserved, counted) instead of re-applying.
        Admission runs AFTER the dedup verdict (a duplicate redelivery
        must not burn tokens) and BEFORE any ingest (a shed is
        all-or-nothing for the sub-batch, so a later redelivery applies
        it exactly once)."""
        reg = getattr(engine, "spill_registry", None)
        if reg is not None:
            verdict = reg.check(fid)
            if verdict == "duplicate":
                return {"duplicate_forward": 1}
            if verdict == "stale":
                plist = _wire_payloads(payloads, lens, _attachment)
                reg.deadletter(fid, {
                    "fid": fid, "tenant": tenant, "encoding": encoding,
                    "payloads": [base64.b64encode(p).decode()
                                 for p in plist]})
                return {"stale_forward": len(plist)}
        plist = _wire_payloads(payloads, lens, _attachment)
        with _gate():
            _guard_payloads(plist, encoding)
            _admit(tenant, len(plist))
            if encoding == "binary":
                summary = engine.ingest_binary_batch(plist, tenant)
            else:
                summary = engine.ingest_json_batch(plist, tenant)
        if reg is not None:
            reg.record(fid)
        return summary

    def process_envelope(envelope: dict, tenant: str = "default"):
        from sitewhere_tpu.ingest.decoders import request_from_envelope

        req = request_from_envelope(envelope)
        req.tenant = tenant
        with _gate():
            _guard_tokens([req.device_token])
            _admit(tenant, 1)
            engine.process(req)
        return {"accepted": True}

    def forward_envelope(fid: str, envelope: dict,
                         tenant: str = "default"):
        reg = getattr(engine, "spill_registry", None)
        if reg is not None:
            verdict = reg.check(fid)
            if verdict == "duplicate":
                return {"duplicate_forward": 1}
            if verdict == "stale":
                reg.deadletter(fid, {"fid": fid, "tenant": tenant,
                                     "envelope": envelope})
                return {"stale_forward": 1}
        res = process_envelope(envelope, tenant)
        if reg is not None:
            reg.record(fid)
        return res

    def register_device(token: str, deviceType: str = None,
                        tenant: str = "default", area: str = None,
                        customer: str = None, metadata: dict = None):
        _guard_tokens([token])
        engine.register_device(token, deviceType, tenant, area, customer,
                               metadata)
        return {"registered": True}

    def update_device(token: str, deviceType: str = None, area: str = None,
                      customer: str = None, metadata: dict = None):
        _guard_tokens([token])
        try:
            engine.update_device(token, deviceType, area, customer, metadata)
        except KeyError:
            return None
        return {"updated": True}

    def delete_device(token: str):
        _guard_tokens([token])
        return engine.delete_device(token)

    def get_device(token: str):
        info = engine.get_device(token)
        return dataclasses.asdict(info) if info is not None else None

    def list_assignments(token: str = None, **kw):
        return [dataclasses.asdict(a)
                for a in engine.list_assignments(token, **kw)]

    def get_device_state(token: str):
        return engine.get_device_state(token)

    def create_assignment(deviceToken: str, token: str = None,
                          asset: str = None, area: str = None,
                          customer: str = None, metadata: dict = None):
        _guard_tokens([deviceToken])
        return dataclasses.asdict(engine.create_assignment(
            deviceToken, token, asset, area, customer, metadata))

    def get_assignment(token: str):
        a = engine.get_assignment(token)
        return dataclasses.asdict(a) if a is not None else None

    def release_assignment(token: str):
        return dataclasses.asdict(engine.release_assignment(token))

    def mark_assignment_missing(token: str):
        return dataclasses.asdict(engine.mark_assignment_missing(token))

    def update_assignment(token: str, asset: str = None, area: str = None,
                          customer: str = None, metadata: dict = None):
        return dataclasses.asdict(engine.update_assignment(
            token, asset, area, customer, metadata))

    def delete_assignment(token: str):
        return engine.delete_assignment(token)

    def search_device_states(**kw):
        rows = engine.search_device_states(**kw)
        pm = getattr(engine, "placement", None)
        if pm is not None:
            rows = pm.filter_rows(rows, key="device")
        return rows

    def query_events(**kw):
        return _placement_filtered_query(engine, kw)

    def get_event(eventId: int, tenant: str = None):
        return engine.get_event(eventId, tenant=tenant)

    def list_device_infos():
        return [dataclasses.asdict(i) for i in _owned_device_infos(engine)]

    def device_count():
        return len(_owned_device_infos(engine))

    def metrics():
        return local_rank_metrics(engine)

    def metrics_text():
        """This rank's registry exposition (exemplars kept — the caller
        is the federated scrape, which re-labels by rank). The export
        runs HERE, against the local engine, so each rank's text
        reflects its own partition."""
        from sitewhere_tpu.utils.metrics import (REGISTRY,
                                                 export_engine_metrics)

        export_engine_metrics(engine)
        return REGISTRY.expose_text(exemplars=True)

    def tenant_metrics():
        return engine.tenant_metrics()

    def presence_sweep():
        return engine.presence_sweep()

    def command_responses(invocationId: str, limit: int = 100):
        from sitewhere_tpu.commands.service import local_command_responses

        return local_command_responses(engine, invocationId, limit)

    def get_invocation(invocationId: int):
        inv = _owned_invocation(engine, invocationId)
        return dataclasses.asdict(inv) if inv is not None else None

    def invoke_command(invocation: dict):
        svc = getattr(engine, "command_service", None)
        if svc is None:
            raise ValueError(
                "no command-delivery service attached on this rank")
        from sitewhere_tpu.commands.model import CommandInvocation

        return {"invocationId": svc.accept_remote(
            CommandInvocation(**invocation))}

    def search_info():
        idx = getattr(engine, "search_index", None)
        return len(idx.docs) if idx is not None else None

    def search_events(query: str, maxResults: int = 100):
        # the rank's embedded index attaches AFTER server construction
        # (instance wiring) — resolve lazily; None (vs []) tells the
        # caller this rank cannot serve search, never "no matches"
        idx = getattr(engine, "search_index", None)
        return (idx.search(query, maxResults, order="eventDate")
                if idx is not None else None)

    def flush():
        return engine.flush()

    def conservation():
        """This rank's conservation ledger + verdict (ISSUE 14) — the
        facade's ``conservation()`` fans these out into one by-rank
        document; rank ledgers never merge into one snapshot (each
        rank's equations balance against its OWN device counters)."""
        from sitewhere_tpu.utils.conservation import conservation_payload

        return conservation_payload(engine)

    def spmd_heat():
        """This rank's shard heat & skew document (ISSUE 18) — the
        facade's ``spmd_heat()`` fans these out into one by-rank
        document; heat maps never merge (each rank's shards are its
        OWN mesh)."""
        from sitewhere_tpu.utils.shardobs import spmd_heat_payload

        return spmd_heat_payload(engine)

    def trace_get(traceId: str):
        return engine.flight.records_of(traceId)

    def trace_recent(limit: int = 50):
        return engine.flight.recent(limit)

    def trace_timeline(traceId: str):
        # rank-LOCAL chrome events (pid = this rank); the calling
        # facade stitches rank lists into one timeline document
        from sitewhere_tpu.utils.tracing import timeline_events

        return timeline_events(engine, traceId)

    for name, fn in {
        "Cluster.ingestJson": ingest_json,
        "Cluster.ingestBinary": ingest_binary,
        "Cluster.ingestForward": ingest_forward,
        "Cluster.processEnvelope": process_envelope,
        "Cluster.forwardEnvelope": forward_envelope,
        "Cluster.registerDevice": register_device,
        "Cluster.updateDevice": update_device,
        "Cluster.deleteDevice": delete_device,
        "Cluster.getDevice": get_device,
        "Cluster.listAssignments": list_assignments,
        "Cluster.createAssignment": create_assignment,
        "Cluster.getAssignment": get_assignment,
        "Cluster.releaseAssignment": release_assignment,
        "Cluster.markAssignmentMissing": mark_assignment_missing,
        "Cluster.updateAssignment": update_assignment,
        "Cluster.deleteAssignment": delete_assignment,
        "Cluster.getDeviceState": get_device_state,
        "Cluster.searchDeviceStates": search_device_states,
        "Cluster.queryEvents": query_events,
        "Cluster.getEvent": get_event,
        "Cluster.listDeviceInfos": list_device_infos,
        "Cluster.deviceCount": device_count,
        "Cluster.metrics": metrics,
        "Cluster.metricsText": metrics_text,
        "Cluster.tenantMetrics": tenant_metrics,
        "Cluster.presenceSweep": presence_sweep,
        "Cluster.invokeCommand": invoke_command,
        "Cluster.getInvocation": get_invocation,
        "Cluster.commandResponses": command_responses,
        "Cluster.searchEvents": search_events,
        "Cluster.searchInfo": search_info,
        "Cluster.traceGet": trace_get,
        "Cluster.traceRecent": trace_recent,
        "Cluster.traceTimeline": trace_timeline,
        "Cluster.conservation": conservation,
        "Cluster.spmdHeat": spmd_heat,
        "Cluster.flush": flush,
    }.items():
        srv.register(name, fn)


def build_cluster_rpc(engine: DistributedEngine, secret: str):
    """The rank's RPC server: cluster data plane, authenticated with the
    shared cluster secret (unauthenticated peers are rejected exactly like
    the instance RPC)."""
    from sitewhere_tpu.instance.auth import JwtService
    from sitewhere_tpu.rpc.server import RpcServer

    jwt = JwtService(secret=secret.encode(), expiration_s=24 * 3600)
    srv = RpcServer(authenticator=jwt.validate)
    register_cluster_rpc(srv, engine)
    from sitewhere_tpu.parallel.placement import register_placement_rpc

    register_placement_rpc(srv, engine)
    return srv
