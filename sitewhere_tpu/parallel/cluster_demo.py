"""Two-process PRODUCT runtime job: DistributedEngine per rank + crash.

This is the deployment proof for the cluster layer (parallel/cluster.py):
two OS processes, each running a complete DistributedEngine — string
tokens, WAL, feeds — plus its authenticated cluster RPC server and a full
REST gateway. Both ranks ingest batches naming devices of BOTH ranks (raw
payloads forward to owners, the Kafka-producer analog), then each rank
logs in to BOTH REST gateways over HTTP basic auth and asserts the
listings/state agree byte-for-byte regardless of which rank serves them
(KafkaOutboundConnectorHost.java:43-257 replicas +
DeviceStateRouter.java:62-72 routing). Then rank 1 is crashed (os._exit
with events that live only in its WAL tail), restarted in recovery mode,
and the cluster must serve the FULL pre-crash history from either rank
and stay writable — the durability story the reference delegates to
Kafka offsets + k8s restarts (SURVEY.md §5.4/5.5).

Phases hand off through marker files in the shared scratch dir; the
parent (``spawn_cluster_demo``) orchestrates the crash/restart.
"""

from __future__ import annotations

import json
import os
import pathlib
import socket
import subprocess
import sys
import time

N_PER_RANK = 3          # devices owned per rank in the demo traffic
PHASE_TIMEOUT_S = 120.0


def _wait_for(path: pathlib.Path, timeout_s: float = PHASE_TIMEOUT_S) -> None:
    deadline = time.monotonic() + timeout_s
    while not path.exists():
        if time.monotonic() > deadline:
            raise TimeoutError(f"phase marker {path.name} never appeared")
        time.sleep(0.05)


def _tokens_for(rank: int, n_ranks: int, n: int) -> list[str]:
    from sitewhere_tpu.parallel.cluster import owner_rank

    out, i = [], 0
    while len(out) < n:
        tok = f"cd-{i}"
        if owner_rank(tok, n_ranks) == rank:
            out.append(tok)
        i += 1
    return out


def _meas(token: str, name: str, value: float, ts_ms: int) -> bytes:
    return json.dumps({
        "deviceToken": token, "type": "DeviceMeasurements",
        "request": {"measurements": {name: value},
                    "eventDate": ts_ms}}).encode()


def worker_main(rank: int, scratch: str, rpc0: int, rpc1: int, rest0: int,
                rest1: int, base_s: float, devices_per_proc: int = 2,
                recover: bool = False) -> None:
    """One rank of the 2-process product job, booted entirely through
    ``run_rank`` (config in, serving rank out — VERDICT r4 item 5).
    Prints CLUSTER_OK / CLUSTER_RECOVERED lines; any assertion failure
    exits nonzero."""
    os.environ.pop("XLA_FLAGS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import logging

    logging.basicConfig(level=logging.WARNING)  # surface handler errors
    import jax

    jax.config.update("jax_platforms", "cpu")
    from sitewhere_tpu.compat import set_cpu_device_count

    set_cpu_device_count(devices_per_proc)

    import asyncio

    import aiohttp

    from sitewhere_tpu.engine import EngineConfig
    from sitewhere_tpu.instance.instance import InstanceConfig
    from sitewhere_tpu.parallel.cluster import ClusterConfig
    from sitewhere_tpu.parallel.distributed import DistributedConfig
    from sitewhere_tpu.parallel.rank_runtime import RankConfig, run_rank

    scratch_p = pathlib.Path(scratch)
    peers = [f"127.0.0.1:{rpc0}", f"127.0.0.1:{rpc1}"]
    rests = [rest0, rest1]
    secret = "cluster-demo-secret"
    base_ms = int(base_s * 1000)
    ecfg = DistributedConfig(
        n_shards=devices_per_proc, device_capacity_per_shard=64,
        token_capacity_per_shard=128, assignment_capacity_per_shard=128,
        store_capacity_per_shard=512, channels=4,
        batch_capacity_per_shard=16,
        wal_dir=str(scratch_p / f"wal-r{rank}"))
    # connect timeout bounds the ONE stall a dead-peer forward pays
    # before the circuit opens and everything spills instantly
    ccfg = ClusterConfig(rank=rank, n_ranks=2, peers=peers, secret=secret,
                         epoch_base_unix_s=base_s, engine=ecfg,
                         connect_timeout_s=15.0)
    # the WHOLE rank — engine (or crash recovery), cluster RPC on its own
    # loop, REST + pumps + presence + scheduler — from one config
    rt = run_rank(RankConfig(
        cluster=ccfg, instance=InstanceConfig(engine=EngineConfig()),
        rest_port=rests[rank],
        snapshot_dir=str(scratch_p / f"snap-r{rank}") if recover else None,
        presence_interval_s=600.0, forward_retry_interval_s=0.3))
    cluster, inst = rt.cluster, rt.instance
    assert rt.recovered == recover
    toks0 = _tokens_for(0, 2, N_PER_RANK)
    toks1 = _tokens_for(1, 2, N_PER_RANK)
    both = toks0 + toks1

    async def rest_snapshot(session: aiohttp.ClientSession,
                            port: int) -> dict:
        """Login (basic auth, the reference's BasicAuthForJwt flow) and
        read the event listing + per-device state from one gateway."""
        import base64

        basic = base64.b64encode(b"admin:password").decode()
        async with session.get(
                f"http://127.0.0.1:{port}/api/authapi/jwt",
                headers={"Authorization": f"Basic {basic}"}) as r:
            assert r.status == 200, (port, r.status, await r.text())
            jwt = (await r.json())["token"]
        h = {"Authorization": f"Bearer {jwt}"}
        out: dict = {}
        async with session.get(
                f"http://127.0.0.1:{port}/api/events?pageSize=100",
                headers=h) as r:
            assert r.status == 200, (port, r.status, await r.text())
            listing = await r.json()
            out["events"] = [(e["deviceToken"], e["eventDateMs"],
                              e.get("measurements"))
                             for e in listing["events"]]
            out["total"] = listing["total"]
        async with session.get(
                f"http://127.0.0.1:{port}/api/search/events?q=*:*"
                "&pageSize=100", headers=h) as r:
            assert r.status == 200, (port, r.status, await r.text())
            out["search"] = [(d["deviceToken"], d["eventDateMs"])
                             for d in (await r.json())["results"]]
        out["state"] = {}
        for t in both:
            async with session.get(
                    f"http://127.0.0.1:{port}/api/devices/{t}/state",
                    headers=h) as r:
                assert r.status == 200, (port, t, r.status, await r.text())
                st = await r.json()
                out["state"][t] = (st["measurements"], st["presence"])
        return out

    async def both_snapshots() -> tuple:
        async with aiohttp.ClientSession() as session:
            return (await rest_snapshot(session, rests[rank]),
                    await rest_snapshot(session, rests[1 - rank]))

    async def health(port: int) -> dict:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                    f"http://127.0.0.1:{port}/api/instance/health") as r:
                assert r.status == 200, (port, r.status, await r.text())
                return await r.json()

    # phases run on the MAIN thread: facade calls block on peer RPC, and
    # run_rank serves cluster RPC + REST on their own loops, so blocking
    # here can never deadlock the peer's forwarded ingest (rule 1)
    if not recover:
        # the readiness probe carries the composed-rank facts
        h = asyncio.run(health(rests[rank]))
        assert h["status"] == "UP" and h["ready"], h
        assert h["rank"] == rank and h["nRanks"] == 2, h
        # ---- phase 1: mixed ingest from BOTH ranks --------------------
        cluster.ingest_json_batch(
            [_meas(t, "temp", rank * 100.0 + i, base_ms + 1000 * rank + i)
             for i, t in enumerate(both)])
        (scratch_p / f"ingested-r{rank}").touch()
        _wait_for(scratch_p / f"ingested-r{1 - rank}")
        cluster.flush()
        # index this rank's partition (the per-rank search connector),
        # then barrier so both indexes are populated before the
        # cross-rank search-equality snapshot
        rt.pump_outbound()
        (scratch_p / f"indexed-r{rank}").touch()
        _wait_for(scratch_p / f"indexed-r{1 - rank}")
        mine, theirs = asyncio.run(both_snapshots())
        assert mine == theirs, (rank, mine, theirs)
        assert mine["total"] == 2 * len(both), mine["total"]
        assert len(mine["search"]) == 2 * len(both), mine["search"]
        # the first metrics fan-out can catch the peer mid-compile on a
        # starved host (one 45s RPC window < two ranks' worth of jax
        # compiles on 2 cores) — retry unreachable peers within the
        # phase budget instead of failing on the first window
        deadline = time.monotonic() + PHASE_TIMEOUT_S
        while True:
            m = cluster.metrics()
            unreachable = any(isinstance(v, dict) and v.get("unreachable")
                              for v in m.get("by_rank", {}).values())
            if not unreachable or time.monotonic() > deadline:
                break
            time.sleep(1.0)
        assert m["persisted"] == 2 * len(both), m
        # ---- entity plane: admin ONCE at rank 0, usable at rank 1 -----
        # (the reference's shared management DB; entity_sync.py)
        if rank == 0:
            inst.device_management.create_device_type("demo-type",
                                                      "Demo type")
            # pushes run on a background thread: drain before signaling
            # the peer that the type is available
            rt.replicator.drain_pushes()
            (scratch_p / "entity-r0").touch()
        else:
            _wait_for(scratch_p / "entity-r0")
            # the replicated type validates rank 1's create_device, and
            # the new device routes to its owner as usual
            inst.device_management.create_device("cd-extra", "demo-type")
        print(f"CLUSTER_OK rank={rank} phase=1 "
              f"total={mine['total']} persisted={m['persisted']} "
              f"rest_agree=1 entity_plane=1", flush=True)

        if rank == 1:
            # snapshot, then wait for rank 0's extra (WAL-tail-only)
            # traffic and crash WITHOUT closing anything
            cluster.local.save(scratch_p / "snap-r1")
            (scratch_p / "r1-snapshotted").touch()
            _wait_for(scratch_p / "extra-sent")
            # the forwarded events are in OUR WAL (logged at ingest
            # accept time) but NOT in the snapshot — the recovery has
            # real work to do
            print("CLUSTER_CRASHING rank=1", flush=True)
            sys.stdout.flush()
            os._exit(17)    # simulated crash: no clean shutdown
        else:
            _wait_for(scratch_p / "r1-snapshotted")
            cluster.ingest_json_batch(
                [_meas(toks1[0], "temp", 777.0, base_ms + 7777)])
            cluster.flush()
            (scratch_p / "extra-sent").touch()
            # ---- phase 1.5: owner DEAD, ingest keeps accepting --------
            # (durable forwarding: the remote share spills to disk
            # instead of raising mid-batch; DecodedEventsProducer's
            # Kafka-durability analog)
            _wait_for(scratch_p / "r1-dead")
            s = cluster.ingest_json_batch(
                [_meas(toks1[1], "temp", 999.0, base_ms + 9999)])
            assert s.get("spilled") == 1, s
            fm = cluster.forward_queue.metrics()
            assert fm["forward_queue_depth"] == 1, fm
            (scratch_p / "spill-sent").touch()
            # ---- phase 2: peer crashed; wait for its recovery ---------
            _wait_for(scratch_p / "r1-recovered",
                      timeout_s=PHASE_TIMEOUT_S * 2)
            q = cluster.query_events(device_token=toks1[0])
            assert q["total"] == 3, q   # 2 original + WAL-tail event
            assert q["events"][0]["measurements"]["temp"] == 777.0
            # the cluster stays writable through the recovered rank
            cluster.ingest_json_batch(
                [_meas(toks1[0], "temp", 888.0, base_ms + 8888)])
            cluster.flush()
            # the background retry pump must redeliver the spilled event
            # to the recovered owner — ZERO loss across the SIGKILL
            deadline = time.monotonic() + 30.0
            while cluster.query_events(
                    device_token=toks1[1])["total"] < 3:
                assert time.monotonic() < deadline, "spill not redelivered"
                time.sleep(0.2)
            fm = cluster.forward_queue.metrics()
            assert fm["forward_redelivered_batches"] >= 1, fm
            assert fm["forward_queue_depth"] == 0, fm
            rt.pump_outbound()
            (scratch_p / "r0-pumped").touch()
            _wait_for(scratch_p / "r1-pumped")
            mine, theirs = asyncio.run(both_snapshots())
            assert mine == theirs, (mine, theirs)
            assert mine["total"] == 2 * len(both) + 3
            # the recovered rank re-indexed its partition from its
            # rebuilt feed: search is complete again cluster-wide
            assert len(mine["search"]) == mine["total"], mine["search"]
            print(f"CLUSTER_OK rank=0 phase=2 "
                  f"total={mine['total']} "
                  f"recovered_peer_serves_history=1 "
                  f"spill_redelivered=1", flush=True)
            (scratch_p / "r0-done").touch()
            rt.stop()
    else:
        # ---- restarted rank 1: WAL replayed over the snapshot ---------
        h = asyncio.run(health(rests[rank]))
        assert h["recovered"] is True, h
        q = cluster.local.query_events(device_token=toks1[0])
        assert q["total"] == 3, q   # snapshot(2) + WAL tail(1)
        assert q["events"][0]["measurements"]["temp"] == 777.0
        # the entity plane survived the SIGKILL too: the replicated
        # device type replayed from this rank's entity journal
        assert "demo-type" in inst.device_management.device_types
        print(f"CLUSTER_RECOVERED rank=1 "
              f"replayed_total={q['total']} entity_replayed=1", flush=True)
        (scratch_p / "r1-recovered").touch()
        # re-index this rank's partition (fresh in-memory index after
        # the crash; the rebuilt feed replays it) for rank 0's
        # phase-2 search-equality snapshot, then wait for the final
        # post-recovery write to index it too
        _wait_for(scratch_p / "r0-pumped", timeout_s=PHASE_TIMEOUT_S * 2)
        rt.pump_outbound()
        (scratch_p / "r1-pumped").touch()
        _wait_for(scratch_p / "r0-done", timeout_s=PHASE_TIMEOUT_S * 2)
        rt.stop()


def _ports(n: int) -> list[int]:
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    out = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return out


def _spawn(rank: int, scratch: str, ports: list[int], base_s: float,
           devices_per_proc: int, recover: bool) -> subprocess.Popen:
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "from sitewhere_tpu.parallel.cluster_demo import worker_main;"
        f"worker_main({rank}, {scratch!r}, {ports[0]}, {ports[1]}, "
        f"{ports[2]}, {ports[3]}, {base_s}, {devices_per_proc}, "
        f"recover={recover})")
    return subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env)


def spawn_cluster_demo(devices_per_proc: int = 2,
                       timeout_s: float = 300.0) -> list[str]:
    """Run the 2-process product job incl. the crash/recover phase.
    Returns the marker lines (CLUSTER_OK x3, CLUSTER_RECOVERED)."""
    import tempfile

    with tempfile.TemporaryDirectory() as scratch:
        ports = _ports(4)
        base_s = float(int(time.time()))
        p0 = _spawn(0, scratch, ports, base_s, devices_per_proc, False)
        p1 = _spawn(1, scratch, ports, base_s, devices_per_proc, False)
        deadline = time.monotonic() + timeout_s

        def finish(p: subprocess.Popen, name: str) -> tuple[str, str]:
            try:
                return p.communicate(timeout=max(5.0, deadline -
                                                 time.monotonic()))
            except subprocess.TimeoutExpired:
                for q in (p0, p1):
                    q.kill()
                    q.wait()
                raise RuntimeError(f"{name} timed out")

        # rank 1 crashes itself with code 17 after phase 1
        out1, err1 = finish(p1, "rank1")
        if p1.returncode != 17 or "CLUSTER_CRASHING" not in out1:
            p0.kill()
            p0.wait()
            raise RuntimeError(
                f"rank1 phase1 failed rc={p1.returncode}\n{out1}\n"
                f"{err1[-2000:]}")
        # rank 1 is REAPED (truly dead): let rank 0 ingest against the
        # dead owner — the durable forward queue must spill, not lose —
        # BEFORE the replacement process comes up
        pathlib.Path(scratch, "r1-dead").touch()
        _wait_for(pathlib.Path(scratch, "spill-sent"),
                  timeout_s=max(5.0, deadline - time.monotonic()))
        p1b = _spawn(1, scratch, ports, base_s, devices_per_proc, True)
        out1b, err1b = finish(p1b, "rank1-recovered")
        out0, err0 = finish(p0, "rank0")
        errs = []
        if p0.returncode != 0 or "CLUSTER_OK rank=0 phase=2" not in out0:
            errs.append(f"rank0 rc={p0.returncode}\n{out0}\n{err0[-2000:]}")
        if p1b.returncode != 0 or "CLUSTER_RECOVERED" not in out1b:
            errs.append(
                f"rank1b rc={p1b.returncode}\n{out1b}\n{err1b[-2000:]}")
        if errs:
            raise RuntimeError("cluster demo failed:\n" + "\n".join(errs))
        lines = [ln for out in (out0, out1, out1b)
                 for ln in out.splitlines()
                 if ln.startswith(("CLUSTER_OK", "CLUSTER_RECOVERED"))]
        return lines
