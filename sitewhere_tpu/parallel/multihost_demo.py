"""Two-process execution harness: the system actually RUNNING multi-host.

The reference scales horizontally with service replicas over partitioned
Kafka consumer groups (KafkaOutboundConnectorHost.java:43-257, README
Deployment); the TPU-native equivalent is one global mesh spanning
processes — each process stages batches for the shards whose devices it
addresses (multihost.local_shard_ids), the stacked shard_map step runs as
one SPMD program, and cross-process reductions ride the same collectives
that span DCN on a real pod.

``worker_main`` is one process of the job (rank r of N over the CPU
backend with ``devices_per_proc`` virtual devices each);
``spawn_two_process_demo`` launches and checks a 2-process run — used by
both tests/test_multihost.py and __graft_entry__.dryrun_multichip so the
multi-process path is exercised in CI and in the driver's dry run.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys


def worker_main(rank: int, nproc: int, port: int,
                devices_per_proc: int = 4) -> None:
    """One process of the multi-host job. Prints one MULTIHOST_OK line on
    success; any assertion failure exits nonzero."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from sitewhere_tpu.compat import set_cpu_device_count

    set_cpu_device_count(devices_per_proc)

    from sitewhere_tpu.parallel import multihost

    assert multihost.initialize(f"localhost:{port}", nproc, rank)
    assert jax.process_count() == nproc, jax.process_count()
    n_global = nproc * devices_per_proc
    assert len(jax.devices()) == n_global

    import jax.numpy as jnp

    from sitewhere_tpu.core.events import HostEventBuffer
    from sitewhere_tpu.core.types import EventType
    from sitewhere_tpu.parallel.sharded import ShardedEngine

    eng = ShardedEngine(
        device_capacity_per_shard=64, token_capacity_per_shard=128,
        assignment_capacity_per_shard=128, store_capacity_per_shard=512,
        channels=4)
    assert eng.n_shards == n_global
    local = multihost.local_shard_ids(eng.mesh)
    assert len(local) == devices_per_proc, local
    # disjoint ownership: rank r owns exactly its devices' shard rows
    assert all(
        (eng.mesh.devices.flat[s].process_index == rank) for s in local)

    # each process ingests events ONLY for its own shards (the partitioned
    # consumer-group analog): 8 events per shard, shard-local device ids.
    # THREE steps: registration (miss path), lookup hits on the same
    # devices, then a later-timestamped round — exercising the steady
    # state, not just cold start, as one SPMD program per step.
    per_shard = 8

    def make_stacked(ts0: int) -> object:
        batches = {}
        for s in local:
            buf = HostEventBuffer(16, channels=4)
            for k in range(per_shard):
                buf.append(EventType.MEASUREMENT, token_id=k, tenant_id=0,
                           ts_ms=ts0 + k, received_ms=ts0 + k,
                           values=[float(s * 100 + k)])
            batches[s] = buf.emit()
        return multihost.assemble_stacked_batch(eng.mesh, batches)

    for step_i, ts0 in enumerate((1000, 2000, 3000)):
        eng.step(make_stacked(ts0))
        # global metrics after EVERY step: SPMD reduction over the whole
        # mesh — all processes must compute identical replicated totals
        m = eng.global_metrics()
        expect = per_shard * n_global * (step_i + 1)
        assert m["persisted"] == expect, (step_i, m)
    assert m["registered"] == per_shard * n_global, m   # first step only
    # "found" counts every resolved event, including just-registered ones
    # re-looked-up within their own step — so all three steps contribute
    assert m["found"] == 3 * per_shard * n_global, m

    # global store scan (query agreement) from EVERY process
    store = eng.state.store
    n_valid = int(jnp.sum(store.valid))
    n_late = int(jnp.sum(store.valid & (store.ts_ms >= 3000)))
    assert n_valid == 3 * per_shard * n_global, n_valid
    assert n_late == per_shard * n_global, n_late

    # presence sweep as a mesh-wide collective pass: with a 0ms horizon
    # every registered device on every shard goes MISSING consistently
    # (the private _stacked_sweep is deliberate: the public presence_sweep
    # does a host readback that is not multi-host-safe)
    from sitewhere_tpu.parallel.sharded import _stacked_sweep

    eng.state, newly = _stacked_sweep(eng.state, jnp.int32(10_000),
                                      jnp.int32(0))
    n_missing = int(jnp.sum(newly))
    assert n_missing == per_shard * n_global, n_missing
    print(f"MULTIHOST_OK rank={rank}/{nproc} shards={local} "
          f"persisted={m['persisted']} store_valid={n_valid} "
          f"found={m['found']} missing={n_missing}", flush=True)


def _spawn_once(devices_per_proc: int, timeout_s: float) -> list[str]:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c",
             "from sitewhere_tpu.parallel.multihost_demo import worker_main;"
             f"worker_main({r}, 2, {port}, {devices_per_proc})"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for r in range(2)
    ]
    lines = []
    errs = []
    for r, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            # one rank failing fast leaves the other stuck in a collective
            # barrier — kill it but keep the FAILED rank's output, which is
            # the root cause the operator needs
            for q in procs:
                q.kill()
                q.wait()
            raise RuntimeError(
                f"rank {r} timed out after {timeout_s}s"
                + ("; earlier failures:\n" + "\n".join(errs) if errs else ""))
        ok = [ln for ln in out.splitlines() if ln.startswith("MULTIHOST_OK")]
        if p.returncode != 0 or not ok:
            errs.append(f"rank {r} rc={p.returncode}\n{out}\n{err[-2000:]}")
        else:
            lines.append(ok[0])
    if errs:
        raise RuntimeError("multi-process demo failed:\n" + "\n".join(errs))
    return lines


def spawn_two_process_demo(devices_per_proc: int = 4,
                           timeout_s: float = 240.0,
                           attempts: int = 3) -> list[str]:
    """Launch the 2-process job and return the two MULTIHOST_OK lines.
    Retries on coordinator-port races (the ephemeral port is probed then
    released before jax.distributed binds it — another process can steal
    it in between); genuine worker failures raise after ``attempts``."""
    last: RuntimeError | None = None
    for _ in range(attempts):
        try:
            return _spawn_once(devices_per_proc, timeout_s)
        except RuntimeError as e:
            last = e
            transient = any(tok in str(e) for tok in
                            ("in use", "UNAVAILABLE", "DEADLINE_EXCEEDED",
                             "failed to connect"))
            if not transient:
                raise
    raise last
