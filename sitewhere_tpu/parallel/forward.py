"""Durable cross-rank ingest forwarding: spill, retry, dead-letter.

In the reference, the ingest edge hands decoded events to a durable,
partitioned Kafka topic (DecodedEventsProducer.java:17-28) — a consumer
replica being down never loses data, because the broker holds the batch
until the partition's consumer returns. Round-4's cluster forwarded raw
payloads over a synchronous RPC with one reconnect: a down owner rank
meant the remote share of the batch was simply gone (VERDICT r4 missing
#2). This module is the broker-durability analog for the TPU cluster:

  * every cross-rank forward is TAGGED with a unique forward id and the
    owner records applied ids (``SpillRegistry``), so a redelivery after
    a lost response or a crash-restart is suppressed, not re-ingested —
    at-least-once transport with near-exact application (the residual
    window: owner crash after WAL-ingest but before the id record; the
    engine-level alternate-id deduplicator closes even that);
  * when the owner is unreachable (connection error or timeout), the
    sub-batch SPILLS to a per-peer on-disk queue (CRC-stamped JSON files,
    atomic rename) instead of raising mid-batch; ``ingest_*_batch``
    reports it as ``{"spilled": n}`` in the summary;
  * a background pump retries oldest-first per peer, preserving the
    spill order; after a configurable retry budget the file moves to a
    ``deadletter/`` directory (data is never silently dropped) and a
    counter records it;
  * queue depth and oldest-age surface as metrics (the Kafka lag gauges
    of this path).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import pathlib
import threading
import time
import zlib
from collections import OrderedDict

logger = logging.getLogger(__name__)


def _crc(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


class SpillRegistry:
    """Owner-side record of applied forward ids. Appends are flushed (OS
    buffer) on every record and fsynced periodically: losing a record
    can only cause a duplicate (which the engine deduplicator absorbs),
    never a loss, so per-record fsync is not worth the hot-path cost.

    The in-memory set is CAPPED, so it has an explicit dedup HORIZON:
    when an entry evicts, the eviction watermark (the evicted fid's
    spill-time ns, persisted) advances — a redelivery carrying a fid
    OLDER than the watermark can no longer be distinguished from an
    already-applied forward, so it is REJECTED (dead-lettered + counted)
    instead of silently double-applied. The horizon exports as a gauge
    so an operator sees how much redelivery window the capacity buys."""

    def __init__(self, directory, capacity: int = 200_000,
                 fsync_every: int = 256):
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.path = self.dir / "applied-forwards.log"
        self._horizon_path = self.dir / "horizon"
        self.capacity = capacity
        self.fsync_every = fsync_every
        self._lock = threading.Lock()
        self._seen: OrderedDict[str, None] = OrderedDict()
        self._since_sync = 0
        self._lines = 0
        self.horizon_ns = 0
        self.stale_rejects = 0
        try:
            self.horizon_ns = int(self._horizon_path.read_text().strip())
        except (OSError, ValueError):
            pass
        self._persisted_horizon_ns = self.horizon_ns
        if self.path.exists():
            loaded_horizon = self.horizon_ns
            for line in self.path.read_text().splitlines():
                fid = line.strip()
                if fid:
                    self._remember(fid)
                    self._lines += 1
            if self.horizon_ns != loaded_horizon:
                self._persist_horizon()
        self._fh = open(self.path, "a")

    @staticmethod
    def fid_time_ns(fid: str) -> "int | None":
        """Spill-clock component of a forward id (rank-time_ns-seq);
        None for foreign formats (treated as inside the horizon)."""
        parts = fid.split("-")
        if len(parts) >= 3:
            try:
                return int(parts[1])
            except ValueError:
                return None
        return None

    def _remember(self, fid: str) -> None:
        """Advances the in-memory horizon on eviction; the caller
        persists it ONCE per record (at steady-state capacity every
        record evicts, and a tmp-write+rename per eviction inside the
        lock would tax the hot dedup path — and the post-restart reload
        loop worst of all). A crash-stale horizon is safe: the reloaded
        _seen log still classifies those fids as duplicates."""
        self._seen[fid] = None
        while len(self._seen) > self.capacity:
            evicted, _ = self._seen.popitem(last=False)
            ns = self.fid_time_ns(evicted)
            if ns is not None and ns > self.horizon_ns:
                self.horizon_ns = ns

    def _persist_horizon(self) -> None:
        tmp = self._horizon_path.with_suffix(".tmp")
        tmp.write_text(str(self.horizon_ns))
        tmp.rename(self._horizon_path)
        self._persisted_horizon_ns = self.horizon_ns

    def seen(self, fid: str) -> bool:
        with self._lock:
            return fid in self._seen

    def check(self, fid: str) -> str:
        """Classify a delivery: "new" (apply it), "duplicate" (suppress),
        or "stale" (older than the eviction watermark — the registry can
        no longer prove it wasn't applied; the caller must dead-letter,
        not re-apply)."""
        with self._lock:
            if fid in self._seen:
                return "duplicate"
            ns = self.fid_time_ns(fid)
            if ns is not None and self.horizon_ns and ns <= self.horizon_ns:
                self.stale_rejects += 1
                return "stale"
            return "new"

    def deadletter(self, fid: str, record: dict) -> None:
        """Preserve a rejected (post-horizon) redelivery's payload on
        disk — rejection must never silently drop data."""
        dl = self.dir / "deadletter"
        dl.mkdir(parents=True, exist_ok=True)
        (dl / f"stale-{fid}.json").write_text(json.dumps(record))

    def metrics(self) -> dict:
        with self._lock:
            age_ms = ((time.time_ns() - self.horizon_ns) / 1e6
                      if self.horizon_ns else -1.0)
            return {"forward_dedup_entries": len(self._seen),
                    "forward_dedup_horizon_ns": self.horizon_ns,
                    "forward_dedup_horizon_age_ms": age_ms,
                    "forward_stale_rejects": self.stale_rejects}

    def record(self, fid: str) -> None:
        with self._lock:
            self._remember(fid)
            self._fh.write(fid + "\n")
            self._fh.flush()
            self._since_sync += 1
            self._lines += 1
            if self._since_sync >= self.fsync_every:
                os.fsync(self._fh.fileno())
                # persist the horizon on the same cadence as the fsync:
                # at steady-state capacity EVERY record evicts, and a
                # tmp+rename per record would tax the hot dedup path. A
                # crash-stale horizon only widens the window in which a
                # redelivery classifies via the reloaded _seen log.
                if self.horizon_ns != self._persisted_horizon_ns:
                    self._persist_horizon()
                self._since_sync = 0
            if self._lines > 2 * self.capacity:
                self._compact()

    def _compact(self) -> None:
        """Rewrite the log from the capped in-memory set (lock held):
        the file must not grow without bound on the happy path — one fid
        line lands per forwarded sub-batch, forever."""
        tmp = self.path.with_suffix(".tmp")
        with open(tmp, "w") as fh:
            fh.write("\n".join(self._seen) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        self._fh.close()
        tmp.rename(self.path)
        self._fh = open(self.path, "a")
        self._lines = len(self._seen)

    def close(self) -> None:
        with self._lock:
            if self.horizon_ns != self._persisted_horizon_ns:
                self._persist_horizon()
            self._fh.close()


class ForwardQueue:
    """Sender-side durable spill queue, one subdirectory per peer rank."""

    def __init__(self, cluster, directory, retry_interval_s: float = 0.5,
                 retry_budget_s: float = 300.0,
                 app_reject_attempts: int = 5):
        self.cluster = cluster
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.retry_interval_s = retry_interval_s
        self.retry_budget_s = retry_budget_s
        # a deterministic owner-side reject (poison batch) dead-letters
        # after this many delivery attempts instead of wedging the peer
        # queue for the whole transport retry budget
        self.app_reject_attempts = app_reject_attempts
        self._attempts: dict[str, int] = {}
        # per-file redelivery deferrals (monotonic deadline): a 429
        # owner-shed honors the owner's Retry-After instead of hammering
        # a saturated peer every pump interval. In-memory on purpose: a
        # restart just earns one extra 429.
        self._defer: dict[str, float] = {}
        self.counters = {"spilled_batches": 0, "spilled_payloads": 0,
                         "redelivered_batches": 0, "deadlettered_batches": 0,
                         "retry_failures": 0, "retry_app_rejects": 0,
                         "retry_transport_failures": 0,
                         "deadlettered_poison": 0,
                         # placement redirects (ISSUE 15): 473 replies
                         # seen by the pump, and originals CONSUMED by a
                         # re-route (their payloads re-spill toward the
                         # new owner — a legal terminal disposition in
                         # the conservation forward-queue equation)
                         "retry_redirects": 0, "rerouted_batches": 0}
        self._seq = 0
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # circuit breaker: after one failed forward, later batches spill
        # IMMEDIATELY instead of each paying the peer connect timeout;
        # the retry pump's first successful delivery closes the circuit
        self._open_circuits: set[int] = set()

    def circuit_open(self, rank: int) -> bool:
        return rank in self._open_circuits

    def trip(self, rank: int) -> None:
        if rank not in self._open_circuits:
            logger.warning("forward circuit to rank %d OPEN "
                           "(spilling without attempting)", rank)
        self._open_circuits.add(rank)

    def reset(self, rank: int) -> None:
        if rank in self._open_circuits:
            logger.info("forward circuit to rank %d closed", rank)
            self._open_circuits.discard(rank)

    # ------------------------------------------------------------ spill
    def spill(self, rank: int, kind: str, tenant: str, fid: str,
              payloads: list[bytes] | None = None,
              envelope: dict | None = None,
              defer_s: float | None = None) -> None:
        """Persist one undeliverable forward (kind: "json" | "binary" |
        "envelope"). Atomic write: tmp + rename, CRC over the body. The
        bound traceparent rides the record so a redelivery hours later
        still joins the original batch's trace. ``defer_s`` (an owner
        Retry-After on a 429 shed) delays the first redelivery attempt."""
        from sitewhere_tpu.utils.tracing import current_traceparent

        rec = {"fid": fid, "kind": kind, "tenant": tenant,
               "spilled_ms": time.time() * 1000}
        tp = current_traceparent()
        if tp is not None:
            rec["tp"] = tp
        if payloads is not None:
            rec["payloads"] = [base64.b64encode(p).decode() for p in payloads]
        if envelope is not None:
            rec["envelope"] = envelope
        body = json.dumps(rec).encode()
        doc = json.dumps({"crc": _crc(body),
                          "body": body.decode()}).encode()
        peer_dir = self.dir / f"rank-{rank}"
        peer_dir.mkdir(parents=True, exist_ok=True)
        with self._lock:
            self._seq += 1
            name = f"spill-{time.time_ns():020d}-{self._seq:06d}.json"
        tmp = peer_dir / (name + ".tmp")
        tmp.write_bytes(doc)
        with open(tmp, "rb") as fh:
            os.fsync(fh.fileno())
        tmp.rename(peer_dir / name)
        if defer_s is not None and defer_s > 0:
            self._defer[name] = time.monotonic() + defer_s
        self.counters["spilled_batches"] += 1
        self.counters["spilled_payloads"] += len(payloads or []) or 1
        logger.warning("forward to rank %d spilled (%s, %d payloads)",
                       rank, kind, len(payloads or []) or 1)

    @staticmethod
    def _load(path: pathlib.Path) -> "dict | None":
        try:
            doc = json.loads(path.read_bytes())
            body = doc["body"].encode()
            if _crc(body) != doc["crc"]:
                return None
            return json.loads(body)
        except (ValueError, KeyError, OSError):
            return None

    # ------------------------------------------------------------ retry
    def _deliver(self, rank: int, rec: dict) -> None:
        from sitewhere_tpu.utils.tracing import bind_traceparent

        peer = self.cluster._peer(rank)
        kind = rec["kind"]
        # the spilled record's traceparent re-binds here, so the
        # redelivery span (ISSUE 10) — possibly hours later — still
        # lands on the original batch's timeline
        from sitewhere_tpu.utils.tracing import NULL_SPAN

        tracer = getattr(getattr(self.cluster, "local", self.cluster),
                         "tracer", None)
        with bind_traceparent(rec.get("tp")), \
                (tracer.begin("forward.redeliver", dst=rank,
                              fid=rec["fid"], kind=kind)
                 if tracer is not None else NULL_SPAN):
            if kind == "envelope":
                peer.call("Cluster.forwardEnvelope", fid=rec["fid"],
                          envelope=rec["envelope"], tenant=rec["tenant"])
            else:
                peer.call("Cluster.ingestForward", fid=rec["fid"],
                          payloads=rec["payloads"], tenant=rec["tenant"],
                          encoding=kind)

    def retry_once(self) -> int:
        """One pass over every peer queue, oldest-first; returns batches
        redelivered. Failures classify in two kinds with DIFFERENT
        ordering contracts:

        * TRANSPORT failures (connection refused / timeout — the peer
          itself is unreachable, every later batch would fail the same
          way): stop at the first failing file so spill order is
          preserved across the outage, dead-letter past the time budget.
        * APPLICATION rejects (``RpcError`` — the peer is UP and
          deterministically refused THIS batch): count the attempt,
          dead-letter the poison file after ``app_reject_attempts``, and
          CONTINUE to the next file — one poison batch must not
          head-of-line-block every batch behind it for the whole
          transport budget (up to 5 minutes before this fix).

        A ``code=429`` app reject (owner-side load shed, ISSUE 9) is
        retryABLE by design: it counts in ``retry_app_rejects`` like any
        app reject, but it NEVER counts toward the poison budget (an
        admitted batch must not dead-letter because the owner was
        briefly saturated) and its redelivery defers by the owner's
        Retry-After."""
        from sitewhere_tpu.rpc.protocol import RpcError

        redelivered = 0
        for peer_dir in sorted(self.dir.glob("rank-*")):
            rank = int(peer_dir.name.split("-")[1])
            for path in sorted(peer_dir.glob("spill-*.json")):
                if self._defer.get(path.name, 0.0) > time.monotonic():
                    continue   # owner asked for backoff; later files
                               # may already be due (dedup + the ring
                               # absorb the reorder, like app rejects)
                rec = self._load(path)
                if rec is None:
                    logger.error("corrupt spill %s -> deadletter", path)
                    self._deadletter(path)
                    continue
                age_s = (time.time() * 1000 - rec["spilled_ms"]) / 1000
                try:
                    self._deliver(rank, rec)
                    self.reset(rank)
                except RpcError as e:
                    from sitewhere_tpu.parallel.placement import (
                        REDIRECT_CODE)

                    if getattr(e, "code", None) == REDIRECT_CODE:
                        # placement redirect (ISSUE 15): the owner moved
                        # (or is fencing) while this frame sat spilled.
                        # A MOVED redirect carries the replier's map —
                        # adopt it and RE-ROUTE the frame toward the
                        # current owner(s); a FENCED redirect defers
                        # like a 429 (the commit lands within the fence
                        # window, and the next pass gets the map).
                        # Never the poison budget: the batch is fine,
                        # the address changed.
                        self.counters["retry_redirects"] += 1
                        data = getattr(e, "data", None) or {}
                        adopt = getattr(self.cluster,
                                        "_adopt_redirect_map", None)
                        if adopt is not None:
                            adopt(e, rank)
                        if data.get("fenced") or "map" not in data:
                            ra = (getattr(e, "retry_after_s", None)
                                  or self.retry_interval_s)
                            self._defer[path.name] = time.monotonic() + ra
                            continue
                        self._reroute(path, rec)
                        continue
                    self.counters["retry_failures"] += 1
                    self.counters["retry_app_rejects"] += 1
                    if getattr(e, "code", None) == 429:
                        ra = (getattr(e, "retry_after_s", None)
                              or self.retry_interval_s)
                        self._defer[path.name] = time.monotonic() + ra
                        logger.warning(
                            "forward to rank %d shed by owner (%s); "
                            "deferring %s for %.3fs", rank, e,
                            path.name, ra)
                        continue
                    n = self._attempts.get(path.name, 0) + 1
                    self._attempts[path.name] = n
                    if n >= self.app_reject_attempts:
                        logger.error(
                            "forward to rank %d rejected %d times (%s) "
                            "-> deadletter poison %s", rank, n, e,
                            path.name)
                        self._deadletter(path)
                        self.counters["deadlettered_poison"] += 1
                    continue   # the peer is up: later batches deliver
                except Exception as e:
                    self.counters["retry_failures"] += 1
                    self.counters["retry_transport_failures"] += 1
                    if age_s > self.retry_budget_s:
                        logger.error(
                            "forward to rank %d undeliverable after "
                            "%.0fs (%s) -> deadletter %s", rank, age_s,
                            e, path.name)
                        self._deadletter(path)
                        continue
                    break   # keep order: don't skip ahead of an outage
                self._attempts.pop(path.name, None)
                self._defer.pop(path.name, None)
                path.unlink()
                redelivered += 1
                self.counters["redelivered_batches"] += 1
        return redelivered

    def _reroute(self, path: pathlib.Path, rec: dict) -> None:
        """Re-route one spilled frame to its CURRENT owner(s) per the
        facade's installed placement map (ISSUE 15): payload batches
        re-partition (a mixed batch may split across owners — each
        share re-spills as a fresh durable record with a fresh forward
        id), envelopes route by their device token. The original file
        is CONSUMED by the re-route (``rerouted_batches``), never
        silently dropped — the conservation forward-queue equation
        counts re-route as a legal terminal disposition alongside
        redelivery and dead-letter."""
        from sitewhere_tpu.utils.tracing import bind_traceparent

        cluster = self.cluster
        with bind_traceparent(rec.get("tp")):
            if rec["kind"] == "envelope":
                tok = (rec.get("envelope") or {}).get("deviceToken")
                owner = (cluster.owner(tok) if tok else None)
                if owner is None:
                    # unroutable: dead-letter preserves it (an acked
                    # frame must never silently vanish)
                    self._deadletter(path)
                    return
                # owner == this rank (a drain moved the slot HERE) is
                # fine: the self-spill redelivers over the loopback
                # Cluster.forwardEnvelope exactly like the batch branch
                self.spill(owner, "envelope", rec["tenant"],
                           cluster._next_fid(), envelope=rec["envelope"])
            else:
                payloads = [base64.b64decode(p) for p in rec["payloads"]]
                for r2, pl2 in cluster._partition_payloads(
                        payloads, kind=rec["kind"]).items():
                    self.spill(r2, rec["kind"], rec["tenant"],
                               cluster._next_fid(), payloads=pl2)
        self._attempts.pop(path.name, None)
        self._defer.pop(path.name, None)
        path.unlink()
        self.counters["rerouted_batches"] += 1
        logger.info("spilled forward %s re-routed per placement epoch "
                    "%d", path.name,
                    getattr(getattr(cluster, "placement", None),
                            "epoch", -1))

    def _deadletter(self, path: pathlib.Path) -> None:
        dl = self.dir / "deadletter"
        dl.mkdir(parents=True, exist_ok=True)
        path.rename(dl / path.name)
        self._attempts.pop(path.name, None)
        self._defer.pop(path.name, None)
        self.counters["deadlettered_batches"] += 1

    # ------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._pump,
                                        name="forward-retry", daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        while not self._stop.wait(self.retry_interval_s):
            try:
                self.retry_once()
            except Exception:
                logger.exception("forward retry pass failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # --------------------------------------------------------- metrics
    def metrics(self) -> dict:
        depth = 0
        oldest_ms = None
        now_ns = time.time_ns()
        for peer_dir in self.dir.glob("rank-*"):
            names = [p.name for p in peer_dir.glob("spill-*.json")]
            depth += len(names)
            if names:
                # the filename encodes spill time_ns — no file reads on
                # the scrape path even with a deep backlog
                spilled_ns = int(min(names).split("-")[1])
                age = (now_ns - spilled_ns) / 1e6
                if oldest_ms is None or age > oldest_ms:
                    oldest_ms = age
        out = {"forward_queue_depth": depth,
               "forward_open_circuits": len(self._open_circuits),
               **{f"forward_{k}": v for k, v in self.counters.items()}}
        if oldest_ms is not None:
            out["forward_queue_oldest_ms"] = oldest_ms
        return out
