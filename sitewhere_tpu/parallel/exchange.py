"""On-device event routing across shards via ICI all-to-all.

The reference relies on Kafka partitioners to deliver each event to the
Streams task that owns its key (device token). When ingest hosts cannot
pre-route (multi-host fan-in, BASELINE.json config #5), the TPU engine routes
on device instead: each shard buckets its raw batch by owning shard (token
slice), then one ``lax.all_to_all`` over the ICI mesh delivers every event to
its owner — the collective replacement for the broker hop (SURVEY.md §2.9
"distributed communication backend").

Buckets are fixed-capacity (static shapes): capacity_factor * B/n per
destination. Overflow events are counted and dropped to the host dead-letter
path, mirroring Kafka's bounded-queue backpressure semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.events import EventBatch
from sitewhere_tpu.core.types import NULL_ID
from sitewhere_tpu.ops.segment import lex_argsort
from sitewhere_tpu.parallel.mesh import SHARD_AXIS


class ExchangeResult(NamedTuple):
    batch: EventBatch      # locally-owned events after the exchange
    n_overflow: jax.Array  # int32[] events dropped for bucket overflow


def _bucket_events(
    batch: EventBatch, n_shards: int, tokens_per_shard: int, bucket: int
) -> tuple[EventBatch, jax.Array]:
    """Sort local events into [n_shards * bucket] rows grouped by owner."""
    target = jnp.where(batch.valid, batch.token_id // tokens_per_shard, n_shards)
    target = jnp.clip(target, 0, n_shards)  # garbage tokens -> padding group
    _, perm = lex_argsort([target, batch.seq])
    s_target = target[perm]
    # rank within destination group
    from sitewhere_tpu.ops.segment import segment_ranks

    rank, _ = segment_ranks(s_target)
    fits = (s_target < n_shards) & (rank < bucket)
    n_overflow = jnp.sum((s_target < n_shards) & (rank >= bucket))
    slot = jnp.where(fits, s_target * bucket + rank, n_shards * bucket)

    def scatter(lane, fill):
        shape = (n_shards * bucket,) + lane.shape[1:]
        return jnp.full(shape, fill, lane.dtype).at[slot].set(lane[perm], mode="drop")

    out = EventBatch(
        valid=scatter(batch.valid, False),
        etype=scatter(batch.etype, 0),
        token_id=scatter(batch.token_id, NULL_ID),
        tenant_id=scatter(batch.tenant_id, NULL_ID),
        ts_ms=scatter(batch.ts_ms, 0),
        received_ms=scatter(batch.received_ms, 0),
        values=scatter(batch.values, 0.0),
        vmask=scatter(batch.vmask, False),
        aux=scatter(batch.aux, NULL_ID),
        seq=jnp.arange(n_shards * bucket, dtype=jnp.int32),
    )
    return out, n_overflow.astype(jnp.int32)


def exchange_events(
    batch: EventBatch, n_shards: int, tokens_per_shard: int, bucket: int
) -> ExchangeResult:
    """Route events to their owning shard. Must run inside ``shard_map`` over
    the ``shard`` mesh axis. Returns the locally-owned batch (capacity
    n_shards * bucket) with **local** token ids (owner offset subtracted)."""
    bucketed, n_overflow = _bucket_events(batch, n_shards, tokens_per_shard, bucket)

    def a2a(lane):
        lane = lane.reshape((n_shards, bucket) + lane.shape[1:])
        out = jax.lax.all_to_all(lane, SHARD_AXIS, split_axis=0, concat_axis=0, tiled=False)
        return out.reshape((n_shards * bucket,) + lane.shape[2:])

    shard_id = jax.lax.axis_index(SHARD_AXIS)
    routed = EventBatch(
        valid=a2a(bucketed.valid),
        etype=a2a(bucketed.etype),
        token_id=a2a(bucketed.token_id),
        tenant_id=a2a(bucketed.tenant_id),
        ts_ms=a2a(bucketed.ts_ms),
        received_ms=a2a(bucketed.received_ms),
        values=a2a(bucketed.values),
        vmask=a2a(bucketed.vmask),
        aux=a2a(bucketed.aux),
        seq=jnp.arange(n_shards * bucket, dtype=jnp.int32),
    )
    # globalize -> localize token ids for the owner's local tables
    local_tokens = jnp.where(
        routed.valid, routed.token_id - shard_id * tokens_per_shard, NULL_ID
    )
    import dataclasses

    routed = dataclasses.replace(routed, token_id=local_tokens)
    return ExchangeResult(batch=routed, n_overflow=n_overflow)
