"""Event-plane replication (RF>=2): follower feeds, health, fire-over.

In the reference, every replica serves all data because storage is a
shared DB — a SIGKILL'd pod costs nothing but capacity. Here the
event/device-state plane was RF=1 per rank: a dead rank's partition was
unreadable until restart, and replicated schedules pinned to that owner
silently stopped firing (ROADMAP open item #1). This module closes the
gap with three pieces:

``ReplicaFeed`` (leader side)
    Streams the rank's WAL-durable ingest batches to ``rf - 1``
    followers chosen deterministically from the rank ring
    (:func:`replica_ring`). Publication happens at the WAL append (same
    engine-lock critical section, so feed order == WAL order), but the
    sender gates every transmission on ``wal.wait_durable(ticket)`` —
    a follower can never hold a frame the owner could still lose. A
    follower that gaps (restart, backlog overflow) is RESYNCED from the
    leader's own WAL segments, so the standby always converges to the
    full acked history. Every frame carries a monotonic OWNERSHIP EPOCH
    (persisted beside the WAL); a follower that took over schedule
    firing answers with a higher fencing epoch and the leader re-syncs
    entity state before firing again (no double-fire on recovery).

``ReplicaApplier`` (follower side)
    Applies feed batches IN ORDER into a standby ``DistributedEngine``
    built from the leader's own engine config, through the existing
    byte-identical decode path (the leader ships its staging clock per
    batch, so standby store bytes equal the owner's — pinned by
    tests/test_replication.py). Serves failover reads
    (query_events / device_state / state search) from the standby with
    an explicit ``stale_ms`` watermark, and detects leader death from
    feed/heartbeat silence (``leader_alive``) — the signal scheduler
    fire-over keys on.

``PeerHealth``
    A small shared tracker with explicit UP / SUSPECT / DOWN states fed
    by ``_SyncPeer`` transport outcomes, plus exponential probe backoff
    so a dead rank doesn't cost a connect timeout per read.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib
import struct
import threading
import time
import zlib
from collections import deque

logger = logging.getLogger(__name__)

UP, SUSPECT, DOWN = "up", "suspect", "down"


def replica_ring(rank: int, n_ranks: int, rf: int) -> list[int]:
    """The follower ranks of ``rank``: its ``rf - 1`` successors on the
    rank ring — deterministic from topology alone, so every rank (and
    every reader doing failover) agrees on who holds which standby
    without coordination."""
    rf = max(1, min(rf, n_ranks))
    return [(rank + i) % n_ranks for i in range(1, rf)]


class PeerHealth:
    """Explicit per-rank health: UP -> SUSPECT on the first transport
    failure, SUSPECT -> DOWN after ``down_after`` consecutive failures
    (a timeout counts like a refusal — both leave the result unknown).
    DOWN ranks are probed with exponential backoff so the read path
    re-discovers recovery without paying a connect timeout per call."""

    def __init__(self, down_after: int = 2, probe_base_s: float = 0.5,
                 probe_max_s: float = 10.0):
        self.down_after = down_after
        self.probe_base_s = probe_base_s
        self.probe_max_s = probe_max_s
        self._lock = threading.Lock()
        self._fails: dict[int, int] = {}
        self._state: dict[int, str] = {}
        self._next_probe: dict[int, float] = {}
        self.transitions = 0

    def record_success(self, rank: int) -> None:
        with self._lock:
            if self._state.get(rank, UP) != UP:
                self.transitions += 1
                logger.info("peer rank %d back UP", rank)
            self._state[rank] = UP
            self._fails[rank] = 0
            self._next_probe.pop(rank, None)

    def record_failure(self, rank: int) -> None:
        with self._lock:
            n = self._fails.get(rank, 0) + 1
            self._fails[rank] = n
            new = DOWN if n >= self.down_after else SUSPECT
            if self._state.get(rank, UP) != new:
                self.transitions += 1
                logger.warning("peer rank %d marked %s (%d consecutive "
                               "failures)", rank, new.upper(), n)
            self._state[rank] = new
            backoff = min(self.probe_max_s,
                          self.probe_base_s * (2 ** min(n - 1, 8)))
            self._next_probe[rank] = time.monotonic() + backoff

    def state(self, rank: int) -> str:
        with self._lock:
            return self._state.get(rank, UP)

    def is_down(self, rank: int) -> bool:
        return self.state(rank) == DOWN

    def mark_down(self, rank: int) -> None:
        """Force DOWN (the applier's feed-silence detector uses this so
        reads skip a rank whose feed died even before any read failed)."""
        with self._lock:
            if self._state.get(rank, UP) != DOWN:
                self.transitions += 1
            self._state[rank] = DOWN
            self._fails[rank] = max(self._fails.get(rank, 0),
                                    self.down_after)
            self._next_probe.setdefault(
                rank, time.monotonic() + self.probe_base_s)

    def should_probe(self, rank: int) -> bool:
        """True when a DOWN rank's backoff window has elapsed — the
        caller may spend one real attempt on it. SUSPECT/UP always
        probe (the state is not yet confident)."""
        with self._lock:
            if self._state.get(rank, UP) != DOWN:
                return True
            due = self._next_probe.get(rank, 0.0)
            if time.monotonic() >= due:
                # re-arm immediately so concurrent readers don't stampede
                self._next_probe[rank] = time.monotonic() + self.probe_base_s
                return True
            return False

    def snapshot(self) -> dict[str, str]:
        with self._lock:
            return {str(r): s for r, s in sorted(self._state.items())}


# --------------------------------------------------------------------------
# leader side: the replica feed
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _Pub:
    seq: int
    kind: str                 # "json" | "binary"
    tenant: str
    payloads: list[bytes]
    ticket: int               # WAL append sequence (durability gate)
    now_ms: int               # leader staging clock (byte-identity pin)
    publish_ms: float
    tp: str | None = None     # originating batch's traceparent: the
                              # sender binds it around the apply RPC so
                              # the standby-apply span (and any standby
                              # records) join the ingest trace (ISSUE 10)


def _standby_config(engine) -> dict:
    """The leader's engine config as shipped to followers: same shapes
    and semantics, but the standby must never journal, archive, or
    record flight lifecycles of its own."""
    cfg = dataclasses.asdict(engine.config)
    cfg["n_shards"] = engine.n_shards
    cfg["wal_dir"] = None
    cfg["archive_dir"] = None
    cfg["flight_recorder"] = False
    cfg["span_trace"] = False   # apply spans are recorded by the HOST
    #                             rank's tracer, on the ingest trace
    return cfg


class ReplicaFeed:
    """One per rank (the leader role): buffers WAL-order publications
    and streams them to each follower on its own sender thread."""

    def __init__(self, cluster, directory, rf: int = 2,
                 heartbeat_s: float = 0.5, max_buffer: int = 4096,
                 resync_chunk: int = 256, fence_grace_s: float = 10.0):
        self.cluster = cluster
        self.rank = cluster.rank
        self.rf = max(1, min(rf, cluster.n_ranks))
        self.followers = replica_ring(self.rank, cluster.n_ranks, self.rf)
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.heartbeat_s = heartbeat_s
        self.max_buffer = max_buffer
        self.resync_chunk = resync_chunk
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._buffer: deque[_Pub] = deque()
        self._seq = 0
        self._cursors = {f: 1 for f in self.followers}   # next seq to send
        self._needs_resync = {f: True for f in self.followers}
        self._acked = {f: 0 for f in self.followers}
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # monotonic ownership epoch, persisted across restarts: a
        # follower that fired over fences the old epoch out, and the
        # recovering leader adopts the higher one before firing again
        self._epoch_path = self.dir / "epoch"
        try:
            self.epoch = int(self._epoch_path.read_text().strip())
        except (OSError, ValueError):
            self.epoch = 1
            self._persist_epoch()
        # fencing gate for the leader's OWN schedule firing: pending
        # until EVERY follower's round-trip confirms no outstanding
        # fence (or the grace expires with no follower reachable —
        # availability wins). One confirmed follower is not enough: with
        # rf >= 3 the fencing follower may simply not have been heard
        # yet while another answers first.
        self._fence_pending = bool(self.followers)
        self._fence_deadline = time.monotonic() + fence_grace_s
        self._fence_confirmed: set[int] = set()
        self.on_fenced = None      # callback: pull entity state before
        #                            resuming schedule firing
        self.counters = {"published": 0, "sent": 0, "heartbeats": 0,
                         "resyncs": 0, "send_failures": 0, "fenced": 0,
                         "buffer_overflows": 0}

    # ------------------------------------------------------------- publish
    def publish(self, tag: bytes, payloads: list[bytes], tenant: str,
                ticket: int, now_ms: int) -> None:
        """Record one WAL append for streaming. Called under the engine
        lock right after the append, so buffer order == WAL order; the
        sender thread still gates on ``wait_durable(ticket)`` before
        the bytes leave this host."""
        from sitewhere_tpu.engine import WAL_JSON

        if not self.followers:
            return
        kind = "json" if tag == WAL_JSON else "binary"
        # the publishing thread is the ingest thread with its flight
        # record bound: carry the batch's trace so the follower's apply
        # span (ISSUE 10) lands on the same timeline
        tp = None
        rec = self.cluster.local.flight.current()
        if rec.trace_id is not None:
            from sitewhere_tpu.utils.tracing import new_traceparent

            tp = new_traceparent(self.rank, trace_id=rec.trace_id)
        with self._cv:
            self._seq += 1
            self._buffer.append(_Pub(self._seq, kind, tenant,
                                     list(payloads), ticket, int(now_ms),
                                     time.time() * 1000, tp))
            self.counters["published"] += 1
            _replication_instruments()["published"].inc()
            if len(self._buffer) > self.max_buffer:
                # a follower lagging past the buffer re-converges by WAL
                # resync; the buffer itself must stay bounded
                dropped = self._buffer.popleft()
                self.counters["buffer_overflows"] += 1
                for f in self.followers:
                    if self._cursors[f] <= dropped.seq:
                        self._needs_resync[f] = True
                        self._cursors[f] = dropped.seq + 1
            self._cv.notify_all()

    def _trim_locked(self) -> None:
        if not self._buffer:
            return
        floor = min(self._cursors.values())
        while self._buffer and self._buffer[0].seq < floor:
            self._buffer.popleft()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        for f in self.followers:
            t = threading.Thread(target=self._sender, args=(f,),
                                 name=f"replica-feed-{self.rank}-to-{f}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    # -------------------------------------------------------------- fencing
    def can_fire(self) -> bool:
        """May this rank fire its own schedules? False while a fencing
        round-trip is pending (restart before first follower contact) —
        a follower may have fired over and hold newer job state."""
        if not self._fence_pending:
            return True
        if time.monotonic() >= self._fence_deadline:
            # no follower reachable within the grace: availability over
            # strictness (documented failure-model tradeoff)
            self._fence_pending = False
            return True
        return False

    def _persist_epoch(self) -> None:
        tmp = self._epoch_path.with_suffix(".tmp")
        tmp.write_text(str(self.epoch))
        tmp.rename(self._epoch_path)

    def _handle_reply(self, follower: int, reply: dict) -> None:
        fence = reply.get("fence")
        if fence is not None and int(fence) > self.epoch:
            # a follower fired over while we were dead: adopt its epoch
            # and pull entity state (replicated last_fired_ms) BEFORE
            # resuming our own schedule firing — the no-double-fire half
            # of fire-over
            self.counters["fenced"] += 1
            logger.warning("rank %d fenced by follower %d (epoch %d -> "
                           "%d): syncing before resuming schedules",
                           self.rank, follower, self.epoch, int(fence))
            with self._lock:
                self._fence_pending = True
                self._fence_confirmed.clear()
            cb = self.on_fenced
            if cb is not None:
                try:
                    cb()
                except Exception:
                    # the follower's fired marks were NOT pulled: keep
                    # the fence up and retry on the next reply (the
                    # fence field rides every frame until adopted)
                    logger.exception("on_fenced sync failed; schedule "
                                     "firing stays fenced")
                    return
            self.epoch = int(fence)
            self._persist_epoch()
        with self._lock:
            self._fence_confirmed.add(follower)
            if self._fence_confirmed >= set(self.followers):
                self._fence_pending = False

    # --------------------------------------------------------------- sender
    def _sender(self, follower: int) -> None:
        backoff = 0.1
        while not self._stop.is_set():
            try:
                if self._needs_resync.get(follower):
                    self._resync(follower)
                    backoff = 0.1
                    continue
                pub = None
                with self._cv:
                    cur = self._cursors[follower]
                    for entry in self._buffer:
                        if entry.seq == cur:
                            pub = entry
                            break
                    if pub is None and not self._stop.is_set():
                        self._cv.wait(self.heartbeat_s)
                        for entry in self._buffer:
                            if entry.seq == cur:
                                pub = entry
                                break
                if self._stop.is_set():
                    return
                if pub is None:
                    self._heartbeat(follower)
                    backoff = 0.1
                    continue
                self._send(follower, pub)
                backoff = 0.1
            except (ConnectionError, TimeoutError, OSError) as e:
                self.counters["send_failures"] += 1
                self.cluster.health.record_failure(follower)
                logger.debug("replica feed to %d failed: %s", follower, e)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 2.0)
            except Exception:
                self.counters["send_failures"] += 1
                logger.exception("replica feed to %d errored", follower)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 2.0)

    def _send(self, follower: int, pub: _Pub) -> None:
        from sitewhere_tpu.utils.tracing import (bind_traceparent,
                                                 trace_id_of)

        eng = self.cluster.local
        if eng.wal is not None:
            # the durability gate: a follower must never apply a frame
            # the owner could still lose to a crash
            eng.wal.wait_durable(pub.ticket)
        lens = [len(p) for p in pub.payloads]
        with self._lock:
            adv = self._seq
        t0 = time.perf_counter_ns()
        with bind_traceparent(pub.tp):
            # the bound traceparent rides the RPC frame: the follower's
            # handler (and its apply span) joins the batch's trace
            reply = self.cluster._peer(follower).call(
                "Cluster.replicaApply", leader=self.rank, seq=pub.seq,
                epoch=self.epoch, encoding=pub.kind, tenant=pub.tenant,
                lens=lens, nowMs=pub.now_ms, publishMs=pub.publish_ms,
                adv=adv, _attachment=b"".join(pub.payloads))
        tracer = getattr(eng, "tracer", None)
        if tracer is not None and tracer.enabled and pub.tp is not None:
            tracer.record("repl.send", t0, time.perf_counter_ns(),
                          trace_id=trace_id_of(pub.tp),
                          follower=follower, seq=pub.seq,
                          payloads=len(pub.payloads))
        self.cluster.health.record_success(follower)
        if reply.get("unknown"):
            self._needs_resync[follower] = True
            return
        if "expect" in reply:
            exp = int(reply["expect"])
            with self._cv:
                base = self._buffer[0].seq if self._buffer else self._seq + 1
                if exp >= base:
                    self._cursors[follower] = exp
                else:
                    self._needs_resync[follower] = True
            self._handle_reply(follower, reply)
            return
        with self._cv:
            self._cursors[follower] = pub.seq + 1
            self._acked[follower] = pub.seq
            self._trim_locked()
        self.counters["sent"] += 1
        self._handle_reply(follower, reply)

    def _heartbeat(self, follower: int) -> None:
        with self._lock:
            adv = self._seq
        reply = self.cluster._peer(follower).call(
            "Cluster.replicaHeartbeat", leader=self.rank, seq=adv,
            epoch=self.epoch)
        self.cluster.health.record_success(follower)
        self.counters["heartbeats"] += 1
        if reply.get("unknown"):
            self._needs_resync[follower] = True
            return
        self._handle_reply(follower, reply)

    # --------------------------------------------------------------- resync
    def _wal_extents(self) -> tuple[int, dict[str, int]]:
        """(base_seq, {segment name: readable bytes}) captured atomically
        against publications: taken under the ENGINE lock, so every
        publish <= base_seq is inside the extents and nothing beyond it
        is. Group-commit mode waits for the durable watermark (the
        extents must not include a torn user-space tail)."""
        eng = self.cluster.local
        wal = eng.wal
        with eng.lock:
            with self._lock:
                base_seq = self._seq
            if wal is None:
                return base_seq, {}
            if wal.group_commit:
                wal.wait_durable(getattr(eng, "_wal_last_seq", 0))
                return base_seq, wal.durable_view()
            wal.flush()
            return base_seq, {
                p.name: p.stat().st_size
                for p in sorted(wal.dir.glob("segment-*.log"))}

    def _resync(self, follower: int) -> None:
        """Rebuild the follower's standby from this rank's own WAL: the
        full acked history, not just the live tail — after this the
        standby can serve failover reads over everything the owner ever
        acknowledged."""
        self.counters["resyncs"] += 1
        eng = self.cluster.local
        base_seq, extents = self._wal_extents()
        peer = self.cluster._peer(follower)
        logger.info("replica resync rank %d -> %d (base seq %d, %d "
                    "segments)", self.rank, follower, base_seq,
                    len(extents))
        peer.call("Cluster.replicaReset", leader=self.rank,
                  config=_standby_config(eng),
                  epochBase=eng.epoch.base_unix_s, epoch=self.epoch)
        self.cluster.health.record_success(follower)
        wal_dir = pathlib.Path(eng.wal.dir) if eng.wal is not None else None
        if wal_dir is not None:
            chunk: list[bytes] = []
            chunk_key: tuple[str, str] | None = None
            idx = 0

            def ship(key, payloads):
                nonlocal idx
                idx += 1
                peer.call("Cluster.replicaWal", leader=self.rank,
                          idx=idx, encoding=key[0], tenant=key[1],
                          lens=[len(p) for p in payloads],
                          _attachment=b"".join(payloads))

            for kind, tenant, payload in _read_wal_records(wal_dir,
                                                           extents):
                key = (kind, tenant)
                if chunk and (key != chunk_key
                              or len(chunk) >= self.resync_chunk):
                    ship(chunk_key, chunk)
                    chunk = []
                chunk_key = key
                chunk.append(payload)
            if chunk:
                ship(chunk_key, chunk)
        peer.call("Cluster.replicaResume", leader=self.rank, seq=base_seq)
        with self._cv:
            self._cursors[follower] = base_seq + 1
            self._acked[follower] = max(self._acked.get(follower, 0),
                                        base_seq)
            self._needs_resync[follower] = False
            self._trim_locked()

    def watermarks(self) -> dict:
        """Feed-side conservation watermarks (ISSUE 14): publish seq,
        the published counter (the two must agree — checked by
        ``check_conservation``), per-follower acked seqs, and the
        retained buffer depth — one consistent read under the feed
        lock."""
        with self._lock:
            return {"seq": self._seq,
                    "published": self.counters["published"],
                    "acked": {str(f): self._acked.get(f, 0)
                              for f in self.followers},
                    "buffer": len(self._buffer)}

    # -------------------------------------------------------------- metrics
    def metrics(self) -> dict:
        with self._lock:
            lag = {f: self._seq - self._acked.get(f, 0)
                   for f in self.followers}
            out = {"replica_feed_seq": self._seq,
                   "replica_feed_epoch": self.epoch,
                   "replica_feed_buffer": len(self._buffer),
                   "replica_feed_max_lag_batches":
                       max(lag.values()) if lag else 0,
                   **{f"replica_feed_{k}": v
                      for k, v in self.counters.items()}}
        return out

    def drained(self) -> bool:
        """Every follower acked every publication (test/bench barrier)."""
        with self._lock:
            return all(self._acked.get(f, 0) >= self._seq
                       and not self._needs_resync.get(f)
                       for f in self.followers)


def _read_wal_records(wal_dir: pathlib.Path, extents: dict[str, int]):
    """Yield ``(kind, tenant, payload)`` for every ingest record inside
    the byte extents (watermark records skipped) — the WAL's head framing
    is ``tag + tenant + b'\\x00' + payload`` (engine._wal_append)."""
    from sitewhere_tpu.engine import WAL_JSON
    from sitewhere_tpu.utils.ingestlog import _MAGIC, _WATERMARK

    for name in sorted(extents):
        cap = extents[name]
        path = wal_dir / name
        if cap <= 0 or not path.exists():
            continue
        with open(path, "rb") as fh:
            probe = fh.read(len(_MAGIC))
            checked = probe == _MAGIC
            if not checked:
                fh.seek(0)
            while fh.tell() < cap:
                head = fh.read(4)
                if len(head) < 4:
                    break
                (n,) = struct.unpack("<I", head)
                wm = n == _WATERMARK
                if wm:
                    head = fh.read(4)
                    if len(head) < 4:
                        break
                    (n,) = struct.unpack("<I", head)
                crc = None
                if checked:
                    raw = fh.read(4)
                    if len(raw) < 4:
                        break
                    (crc,) = struct.unpack("<I", raw)
                if fh.tell() + n > cap:
                    break   # record extends past the durable extent
                body = fh.read(n)
                if len(body) < n:
                    break
                if crc is not None and zlib.crc32(body) != crc:
                    break
                if wm or not body:
                    continue
                tag, rest = body[:1], body[1:]
                sep = rest.find(b"\x00")
                if sep < 0:
                    continue
                tenant = rest[:sep].decode("utf-8", "replace")
                kind = "json" if tag == WAL_JSON else "binary"
                yield kind, tenant, rest[sep + 1:]


# --------------------------------------------------------------------------
# follower side: the standby applier
# --------------------------------------------------------------------------

class _Standby:
    """One leader's standby: engine + stream position + liveness."""

    def __init__(self, engine, epoch: int):
        self.engine = engine
        self.applied_seq = 0
        self.advertised_seq = 0
        self.leader_epoch = epoch
        self.fence_epoch = 0
        self.lock = threading.Lock()
        self.created_mono = time.monotonic()
        self.last_feed_mono: float | None = None
        self.last_caughtup_mono: float | None = None
        self.takeover_mono: float | None = None
        self.applied_batches = 0
        self.applied_payloads = 0


class ReplicaApplier:
    """One per rank (the follower role): standby stores for each leader
    this rank follows, failover read serving, and leader-death detection
    for scheduler fire-over."""

    def __init__(self, cluster, rf: int = 2, detect_s: float = 5.0,
                 catchup_window_s: float = 120.0):
        self.cluster = cluster
        self.rank = cluster.rank
        self.rf = max(1, min(rf, cluster.n_ranks))
        self.detect_s = detect_s
        self.catchup_window_s = catchup_window_s
        self._lock = threading.Lock()
        self._standbys: dict[int, _Standby] = {}
        self.counters = {"applied_batches": 0, "applied_payloads": 0,
                         "resets": 0, "failover_reads": 0,
                         "fireovers": 0, "gap_rejects": 0}

    # the leaders this rank follows (inverse of replica_ring)
    def leaders(self) -> list[int]:
        return [r for r in range(self.cluster.n_ranks)
                if r != self.rank
                and self.rank in replica_ring(r, self.cluster.n_ranks,
                                              self.rf)]

    def follows(self, leader: int) -> bool:
        return leader in self._standbys or leader in self.leaders()

    def _standby(self, leader: int) -> "_Standby | None":
        with self._lock:
            return self._standbys.get(leader)

    # ----------------------------------------------------------- feed RPCs
    def reset(self, leader: int, config: dict, epoch_base: float,
              epoch: int) -> dict:
        from sitewhere_tpu.core.events import EpochBase
        from sitewhere_tpu.parallel.distributed import (DistributedConfig,
                                                        DistributedEngine)

        cfg = DistributedConfig(**config)
        engine = DistributedEngine(cfg)
        engine.epoch = EpochBase(epoch_base)
        st = _Standby(engine, epoch)
        with self._lock:
            old = self._standbys.get(leader)
            if old is not None:
                # the fencing epoch must survive a resync: a leader
                # restart re-streams, it does not un-fence
                st.fence_epoch = old.fence_epoch
                st.takeover_mono = old.takeover_mono
            self._standbys[leader] = st
        self.counters["resets"] += 1
        logger.info("rank %d: standby for leader %d reset (epoch %d)",
                    self.rank, leader, epoch)
        return {"ok": True}

    def _fence_fields(self, st: _Standby, epoch: int) -> dict:
        st.leader_epoch = max(st.leader_epoch, int(epoch))
        if st.fence_epoch > int(epoch):
            return {"fence": st.fence_epoch}
        return {}

    def _ingest(self, st: _Standby, encoding: str, tenant: str,
                payloads: list[bytes], now_ms: "int | None") -> None:
        eng = st.engine
        fn = (eng.ingest_binary_batch if encoding == "binary"
              else eng.ingest_json_batch)
        if now_ms is not None:
            eng._now_override = int(now_ms)
        try:
            fn(payloads, tenant)
        finally:
            eng._now_override = None

    def apply(self, leader: int, seq: int, epoch: int, encoding: str,
              tenant: str, lens: list, nowMs: int, publishMs: float,
              adv: int, _attachment: bytes = None,
              payloads: list = None) -> dict:
        from sitewhere_tpu.parallel.cluster import _wire_payloads

        st = self._standby(leader)
        if st is None:
            return {"unknown": True}
        with st.lock:
            out = self._fence_fields(st, epoch)
            if seq != st.applied_seq + 1:
                self.counters["gap_rejects"] += 1
                return {"expect": st.applied_seq + 1, **out}
            plist = _wire_payloads(payloads, lens, _attachment)
            # standby-apply span (ISSUE 10): the sender bound the
            # originating batch's traceparent around this RPC, so the
            # span lands on the ingest trace — recorded into THIS
            # rank's tracer (the standby engine records nothing itself)
            from sitewhere_tpu.utils.tracing import (current_traceparent,
                                                     trace_id_of)

            tracer = getattr(self.cluster.local, "tracer", None)
            tid = trace_id_of(current_traceparent())
            t0 = time.perf_counter_ns()
            self._ingest(st, encoding, tenant, plist, nowMs)
            if tracer is not None and tracer.enabled and tid is not None:
                tracer.record("repl.apply", t0, time.perf_counter_ns(),
                              trace_id=tid, leader=leader, seq=seq,
                              payloads=len(plist))
            st.applied_seq = seq
            st.advertised_seq = max(int(adv), seq)
            st.last_feed_mono = time.monotonic()
            st.applied_batches += 1
            st.applied_payloads += len(plist)
            if st.applied_seq >= st.advertised_seq:
                st.last_caughtup_mono = st.last_feed_mono
            self.counters["applied_batches"] += 1
            self.counters["applied_payloads"] += len(plist)
            _replication_instruments()["applied"].inc()
            return {"applied": seq, **out}

    def wal(self, leader: int, idx: int, encoding: str, tenant: str,
            lens: list, _attachment: bytes = None,
            payloads: list = None) -> dict:
        """One resync chunk (WAL-order records; no staging-clock pin —
        resync restores logical history, the live stream restores byte
        identity going forward)."""
        from sitewhere_tpu.parallel.cluster import _wire_payloads

        st = self._standby(leader)
        if st is None:
            return {"unknown": True}
        with st.lock:
            plist = _wire_payloads(payloads, lens, _attachment)
            self._ingest(st, encoding, tenant, plist, None)
            st.last_feed_mono = time.monotonic()
            return {"ok": True, "idx": idx}

    def resume(self, leader: int, seq: int) -> dict:
        st = self._standby(leader)
        if st is None:
            return {"unknown": True}
        with st.lock:
            st.applied_seq = int(seq)
            st.advertised_seq = max(st.advertised_seq, int(seq))
            now = time.monotonic()
            st.last_feed_mono = now
            st.last_caughtup_mono = now
            return {"ok": True}

    def heartbeat(self, leader: int, seq: int, epoch: int) -> dict:
        st = self._standby(leader)
        if st is None:
            return {"unknown": True}
        with st.lock:
            out = self._fence_fields(st, epoch)
            st.advertised_seq = max(st.advertised_seq, int(seq))
            now = time.monotonic()
            st.last_feed_mono = now
            if st.applied_seq >= st.advertised_seq:
                st.last_caughtup_mono = now
            return {"applied": st.applied_seq, **out}

    # -------------------------------------------------------- failover reads
    def stale_ms(self, leader: int) -> float:
        """The explicit staleness watermark failover responses carry:
        milliseconds since this standby last provably reflected every
        acknowledged write of the leader."""
        st = self._standby(leader)
        if st is None:
            return -1.0
        anchor = st.last_caughtup_mono or st.created_mono
        return max(0.0, (time.monotonic() - anchor) * 1000.0)

    def applied(self, leader: int) -> int:
        st = self._standby(leader)
        return st.applied_seq if st is not None else -1

    def status(self, leader: int) -> dict:
        st = self._standby(leader)
        if st is None:
            return {"unknown": True}
        return {"applied": st.applied_seq,
                "advertised": st.advertised_seq,
                "staleMs": self.stale_ms(leader),
                "leaderAlive": self.leader_alive(leader),
                "fenceEpoch": st.fence_epoch}

    def _flushed_engine(self, st: _Standby):
        eng = st.engine
        with st.lock:
            if eng.staged_count or eng._pending_outs:
                eng.flush()
        return eng

    def query_events(self, leader: int, **kw) -> "dict | None":
        st = self._standby(leader)
        if st is None:
            return None
        res = self._flushed_engine(st).query_events(**kw)
        res["stale_ms"] = round(self.stale_ms(leader), 3)
        res["served_by_replica"] = self.rank
        self.counters["failover_reads"] += 1
        _replication_instruments()["failover_reads"].inc()
        return res

    def device_state(self, leader: int, token: str) -> "dict | None":
        st = self._standby(leader)
        if st is None:
            return None
        state = self._flushed_engine(st).get_device_state(token)
        self.counters["failover_reads"] += 1
        _replication_instruments()["failover_reads"].inc()
        if state is None:
            return {"stale_ms": round(self.stale_ms(leader), 3),
                    "missing": True}
        state["stale_ms"] = round(self.stale_ms(leader), 3)
        state["served_by_replica"] = self.rank
        return state

    def search_states(self, leader: int, **kw) -> "list | None":
        st = self._standby(leader)
        if st is None:
            return None
        out = self._flushed_engine(st).search_device_states(**kw)
        self.counters["failover_reads"] += 1
        _replication_instruments()["failover_reads"].inc()
        stale = round(self.stale_ms(leader), 3)
        for row in out:
            row["stale_ms"] = stale
            row["served_by_replica"] = self.rank
        return out

    # --------------------------------------------------------- fire-over
    def leader_alive(self, leader: int) -> bool:
        """Feed-silence liveness: the leader streamed or heartbeat
        within ``detect_s``. A standby that has NEVER heard from its
        leader counts alive for its first ``detect_s`` (boot grace)."""
        st = self._standby(leader)
        if st is None:
            return True   # not following: no opinion
        anchor = st.last_feed_mono or st.created_mono
        return (time.monotonic() - anchor) < self.detect_s

    def should_fire_over(self, owner: int) -> bool:
        """Should THIS rank fire schedules owned by ``owner``? Yes when
        the owner's feed went silent past the detection budget and this
        rank is the owner's first follower that is not itself down.
        Takeover bumps the fencing epoch so the recovering owner syncs
        before firing again."""
        st = self._standby(owner)
        if st is None:
            return False
        if self.leader_alive(owner):
            if st.takeover_mono is not None:
                logger.info("rank %d: leader %d back, ending schedule "
                            "fire-over", self.rank, owner)
                st.takeover_mono = None
            return False
        for f in replica_ring(owner, self.cluster.n_ranks, self.rf):
            if f == self.rank:
                break
            if not self.cluster.health.is_down(f):
                return False   # an earlier live follower owns fire-over
        if st.takeover_mono is None:
            with st.lock:
                if st.takeover_mono is None:
                    st.takeover_mono = time.monotonic()
                    st.fence_epoch = max(st.fence_epoch,
                                         st.leader_epoch) + 1
                    self.counters["fireovers"] += 1
                    _replication_instruments()["fireovers"].inc()
                    self.cluster.health.mark_down(owner)
                    logger.warning(
                        "rank %d: taking over schedule firing for dead "
                        "leader %d (fence epoch %d)", self.rank, owner,
                        st.fence_epoch)
        return True

    def in_catchup(self, owner: int) -> bool:
        """True while a fresh takeover may fire windows missed during
        detection (cron catch-up semantics in ScheduleManager)."""
        st = self._standby(owner)
        return (st is not None and st.takeover_mono is not None
                and (time.monotonic() - st.takeover_mono)
                < self.catchup_window_s)

    # -------------------------------------------------------------- metrics
    def standbys_status(self) -> dict:
        """Per-leader standby status keyed by rank string — THE standby
        block every health surface (REST, instance RPC, cluster RPC,
        cluster_status) serves."""
        with self._lock:
            leaders = list(self._standbys)
        return {str(r): self.status(r) for r in leaders}

    def stale_by_leader(self) -> dict[int, float]:
        """Staleness watermark PER LEADER this rank follows — the
        per-peer series behind ``swtpu_replication_stale_ms{leader=...}``
        and the cluster_status health block (a single lagging follower
        must be visible before a failover read hits it, not averaged
        into a max)."""
        with self._lock:
            leaders = list(self._standbys)
        return {r: round(self.stale_ms(r), 3) for r in leaders}

    def metrics(self) -> dict:
        with self._lock:
            leaders = dict(self._standbys)
        out = {f"replica_applier_{k}": v for k, v in self.counters.items()}
        out["replica_standbys"] = len(leaders)
        if leaders:
            out["replica_max_stale_ms"] = max(
                self.stale_ms(r) for r in leaders)
        return out

    def close(self) -> None:
        with self._lock:
            self._standbys.clear()


def install_fireover(scheduler, cluster) -> None:
    """Wire failure-aware schedule routing into a ScheduleManager:
    each schedule fires at its token's owner rank while that rank is
    alive, at its first live follower while it is dead (with missed-
    window catch-up), and never at both (fencing + replicated fired
    state)."""
    me = cluster.rank

    def fire_filter(token: str) -> bool:
        # ownership resolves through the PLACEMENT map (ISSUE 15), the
        # same epoch every other surface reads — a moved schedule token
        # fires at its new owner from the commit epoch on, and never at
        # both (the map is installed atomically per rank and a lower
        # epoch is never adopted)
        owner = cluster.owner(token)
        if owner == me:
            feed = cluster.replica_feed
            return feed is None or feed.can_fire()
        applier = cluster.replica_applier
        return applier is not None and applier.should_fire_over(owner)

    def catchup_filter(token: str) -> bool:
        owner = cluster.owner(token)
        applier = cluster.replica_applier
        return (owner != me and applier is not None
                and applier.in_catchup(owner))

    scheduler.fire_filter = fire_filter
    scheduler.catchup_filter = catchup_filter


def cluster_health_payload(engine) -> dict:
    """Rank-LOCAL health/replication view (no peer fan-out — it must
    answer instantly mid-partition): peer up/suspect/down states, the
    feed's posture, and each standby's staleness watermark. The ONE
    payload behind REST /api/instance/cluster/health, the
    Instance.clusterHealth RPC, and Cluster.health."""
    health = getattr(engine, "health", None)
    if health is None:
        return {"clustered": False}
    out = {"clustered": True, "rank": engine.rank,
           "health": health.snapshot(),
           "replicationFactor": getattr(engine, "replication_factor", 1)}
    feed = getattr(engine, "replica_feed", None)
    if feed is not None:
        out["feed"] = feed.metrics()
    applier = getattr(engine, "replica_applier", None)
    if applier is not None:
        out["standbys"] = applier.standbys_status()
    return out


def register_replication_rpc(srv, applier: ReplicaApplier) -> None:
    """The replica-feed + failover-read surface on the rank's cluster
    RPC server (rides the same authenticated channel as entity sync)."""
    cluster = applier.cluster

    def health():
        return cluster_health_payload(cluster)

    for name, fn in {
        "Cluster.replicaReset": lambda leader, config, epochBase, epoch:
            applier.reset(leader, config, epochBase, epoch),
        "Cluster.replicaApply": applier.apply,
        "Cluster.replicaWal": applier.wal,
        "Cluster.replicaResume": applier.resume,
        "Cluster.replicaHeartbeat": applier.heartbeat,
        "Cluster.replicaStatus": lambda leader: applier.status(leader),
        "Cluster.replicaQueryEvents": lambda leader, **kw:
            applier.query_events(leader, **kw),
        "Cluster.replicaDeviceState": lambda leader, token:
            applier.device_state(leader, token),
        "Cluster.replicaSearchStates": lambda leader, **kw:
            applier.search_states(leader, **kw),
        "Cluster.health": health,
    }.items():
        srv.register(name, fn)


# resolved once: publish runs inside the WAL-append critical section and
# apply/failover-read are the follower's hot paths — six registry
# lookups per event would be pure overhead (the registry returns the
# same instrument objects forever)
_INSTRUMENTS: dict | None = None


def _replication_instruments() -> dict:
    global _INSTRUMENTS
    if _INSTRUMENTS is None:
        from sitewhere_tpu.utils.metrics import replication_metrics

        _INSTRUMENTS = replication_metrics()
    return _INSTRUMENTS
