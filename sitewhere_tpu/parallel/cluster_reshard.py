"""Rank-count elasticity from snapshots + archives, not WAL replay.

Round-4's ``reshard_cluster`` replayed every old rank's FULL WAL through
the new partitioner — O(all events ever), and it refused pruned WALs even
though pruning after a snapshot is a supported operation and long history
is supposed to live in the archive tier (VERDICT r4 missing #3). The
reference's history lives in topology-agnostic storage that survives any
scaling event (InfluxDbDeviceEventManagement.java:63-161); this module is
that property for the TPU cluster:

    new rank state   = re-partitioned old SNAPSHOTS     (O(live state))
    new rank archive = row-copied old ARCHIVES + rows
                       evicted during the re-pack       (no re-decode)
    + per-old-rank WAL TAILS replayed through the live
      new cluster (:func:`replay_wal_tails`)            (O(tail))

Ownership moves from ``token-hash % n_old`` to ``token-hash % n_new``:
every device, its registry/aggregate rows, its ring events, and its
archived history land at the new owner. Unlike the intra-engine
``reshard_snapshot`` (one shared interner space), ranks have PRIVATE
interner spaces — so every id-bearing column (tenant, device type, area,
customer, asset, alert type, alternate/originating event ids) is remapped
through STRING-level union tables built from the old manifests, and
measurement lanes are permuted per old rank into the union channel map.

Operate it like a topology change: drain, snapshot every rank, run
``migrate_cluster_snapshots``, start the new ranks from the produced
snapshot dirs (``run_rank(snapshot_dir=...)`` with fresh WALs), then
``replay_wal_tails`` the old post-snapshot WAL tails through the live
cluster. Pruned WALs are fine — snapshot + archive carry everything the
pruned span held.

Since ISSUE 15 this OFFLINE path is the DISASTER-RECOVERY route, not
the day-to-day one: live rank join/drain and tenant rebalancing run
through ``parallel/placement.py`` (epoch-fenced online handoff, zero
downtime). Reach for this module when the slot space itself must change
(``slots_per_rank`` regrets), when WALs were pruned past what an online
move may replay, or when the cluster is down anyway.
"""

from __future__ import annotations

import json
import logging
import pathlib
import types

import numpy as np

logger = logging.getLogger(__name__)

from sitewhere_tpu.core.types import NULL_ID, EventType
from sitewhere_tpu.parallel.cluster import owner_rank
from sitewhere_tpu.parallel.reshard import _fill_like, _load

# interner-backed manifest lists shared by every target (string union)
_UNION_KINDS = ("tenants", "device_types", "alert_types", "areas",
                "customers", "assets", "event_ids", "channel_names")

# device_state leaves whose LAST axis is the channel-lane axis (recent_*
# slot axes are small ints too — identify lanes by NAME, never by shape)
_LANE_LEAVES = (".device_state.meas_last", ".device_state.meas_last_ms",
                ".device_state.recent_meas", ".device_state.recent_meas_mask")


def _remap(vals: np.ndarray, table: np.ndarray) -> np.ndarray:
    """Translate interner-id VALUES through ``table``; NULL/out-of-range
    pass through as NULL."""
    v = vals.astype(np.int64)
    out = np.full(v.shape, NULL_ID, np.int64)
    ok = (v != NULL_ID) & (v >= 0) & (v < len(table))
    out[ok] = table[v[ok]]
    return out


def _permute_lanes(arr: np.ndarray, src: np.ndarray, dst: np.ndarray,
                   fill) -> np.ndarray:
    """Move channel-lane data (last axis) from old lanes to new lanes;
    unmapped lanes take the leaf's empty-row fill (INT32_MIN for ms
    lanes — a 0 would read as a live sample at the epoch base)."""
    out = np.full(arr.shape, fill, arr.dtype)
    if len(src):
        out[..., dst] = arr[..., src]
    return out


class _Maps:
    """Every remap table for ONE old rank."""

    def __init__(self):
        self.interner: dict[str, np.ndarray] = {}
        self.lane_src = np.zeros(0, np.int64)
        self.lane_dst = np.zeros(0, np.int64)
        # old (shard, local) -> target rank / new local / new shard
        self.dev_target: np.ndarray | None = None
        self.dev_new_local: np.ndarray | None = None
        self.dev_new_shard: np.ndarray | None = None
        self.asg_new_local: np.ndarray | None = None

    def remap_aux(self, aux: np.ndarray, etype: np.ndarray) -> np.ndarray:
        """aux lane semantics depend on the row's event type: lane 0 is an
        alert-type id for ALERT rows, an event-id-interner id for
        COMMAND_RESPONSE / STATE_CHANGE rows, and a raw invocation id for
        COMMAND_INVOCATION rows (passes through); lane 1 is always an
        alternate-id (event-id interner) when set."""
        out = aux.astype(np.int64).copy()
        et = etype.astype(np.int64)
        alert = et == int(EventType.ALERT)
        evid = ((et == int(EventType.COMMAND_RESPONSE))
                | (et == int(EventType.STATE_CHANGE)))
        out[alert, 0] = _remap(aux[alert, 0], self.interner["alert_types"])
        out[evid, 0] = _remap(aux[evid, 0], self.interner["event_ids"])
        out[:, 1] = _remap(aux[:, 1], self.interner["event_ids"])
        return out

    def remap_store_cols(self, cols: dict, so: int,
                         target: int) -> "tuple[dict | None, int]":
        """Remap one batch of ring/archive rows from old shard ``so``;
        returns (rows owned by ``target`` plus a ``__shard__`` column —
        or None when none land here, count of rows whose DEVICE no
        longer maps anywhere). The unmapped count is target-independent;
        callers tally it exactly once (target 0's pass)."""
        devs = cols[".store.device"].astype(np.int64)
        n_cap = self.dev_target.shape[1]
        ok = (devs != NULL_ID) & (devs >= 0) & (devs < n_cap)
        mapped = np.zeros(devs.shape, bool)
        mapped[ok] = self.dev_target[so, devs[ok]] != NULL_ID
        unmapped = int(np.sum(~mapped))
        here = np.zeros(devs.shape, bool)
        here[ok] = self.dev_target[so, devs[ok]] == target
        if not np.any(here):
            return None, unmapped
        sub = {k: v[here] for k, v in cols.items()}
        devs = devs[here]
        sub["__shard__"] = self.dev_new_shard[so, devs]
        sub[".store.device"] = self.dev_new_local[so, devs]
        asgs = sub[".store.assignment"].astype(np.int64)
        g_cap = self.asg_new_local.shape[1]
        oka = (asgs != NULL_ID) & (asgs >= 0) & (asgs < g_cap)
        new_a = np.full_like(asgs, NULL_ID)
        new_a[oka] = self.asg_new_local[so, asgs[oka]]
        sub[".store.assignment"] = new_a
        sub[".store.tenant"] = _remap(sub[".store.tenant"],
                                      self.interner["tenants"])
        sub[".store.area"] = _remap(sub[".store.area"],
                                    self.interner["areas"])
        sub[".store.customer"] = _remap(sub[".store.customer"],
                                        self.interner["customers"])
        sub[".store.asset"] = _remap(sub[".store.asset"],
                                     self.interner["assets"])
        sub[".store.aux"] = self.remap_aux(sub[".store.aux"],
                                           sub[".store.etype"])
        # LOCATION rows use values[0:3] positionally (lat/lon/elev), not
        # channel lanes — permute only the measurement rows
        et = sub[".store.etype"].astype(np.int64)
        is_meas = et == int(EventType.MEASUREMENT)
        for k, fill in ((".store.values", 0.0), (".store.vmask", False)):
            permuted = _permute_lanes(sub[k], self.lane_src,
                                      self.lane_dst, fill)
            sub[k] = np.where(is_meas[:, None], permuted, sub[k])
        return sub, unmapped


def migrate_cluster_snapshots(old_snap_dirs, n_ranks_new: int, out_root,
                              old_archive_dirs=None) -> dict:
    """Re-partition a cluster's snapshots (+ archives) for a NEW rank
    count. Writes ``out_root/rank-N/snapshot`` (+ ``archive``) per new
    rank; returns per-target stats."""
    out_root = pathlib.Path(out_root)
    olds = [_load(pathlib.Path(d)) for d in old_snap_dirs]
    r_old = len(olds)
    r_new = int(n_ranks_new)
    if old_archive_dirs is not None and len(old_archive_dirs) != r_old:
        raise ValueError("one archive dir per old rank")
    cfg = dict(olds[0][0]["config"])
    base = olds[0][0]["epoch_base_unix_s"]
    for host, _ in olds[1:]:
        strip = ("wal_dir", "archive_dir")
        if {k: v for k, v in host["config"].items() if k not in strip} != \
           {k: v for k, v in cfg.items() if k not in strip}:
            raise ValueError("old ranks carry heterogeneous engine configs")
        if abs(host["epoch_base_unix_s"] - base) > 1e-3:
            raise ValueError("old ranks disagree on the epoch base — "
                             "their timestamps live in different domains")
    s_sh = olds[0][0]["n_shards"]
    n_cap = cfg["device_capacity_per_shard"]
    g_cap = cfg["assignment_capacity_per_shard"]
    c_cap = cfg["store_capacity_per_shard"]
    t_cap = cfg["token_capacity_per_shard"]
    channels = cfg["channels"]

    # ---- string-union interner tables (identical on every target) -----
    union: dict[str, list[str]] = {k: [] for k in _UNION_KINDS}
    union_idx: dict[str, dict[str, int]] = {k: {} for k in _UNION_KINDS}
    maps = [_Maps() for _ in range(r_old)]
    for kind in _UNION_KINDS:
        for o, (host, _) in enumerate(olds):
            table = np.full(len(host[kind]), NULL_ID, np.int64)
            for i, s in enumerate(host[kind]):
                j = union_idx[kind].get(s)
                if j is None:
                    j = union_idx[kind][s] = len(union[kind])
                    union[kind].append(s)
                table[i] = j
            maps[o].interner[kind] = table
    # channel-lane permutation per old rank: lane = interner id % channels
    # on both sides; when old lanes collided, the FIRST claimant owns the
    # lane's data (the live engine has the same ambiguity)
    for o, (host, _) in enumerate(olds):
        seen: set[int] = set()
        src, dst = [], []
        for i, name in enumerate(host["channel_names"]):
            lane_o = i % channels
            if lane_o in seen:
                continue
            seen.add(lane_o)
            src.append(lane_o)
            dst.append(union_idx["channel_names"][name] % channels)
        maps[o].lane_src = np.asarray(src, np.int64)
        maps[o].lane_dst = np.asarray(dst, np.int64)

    # ---- token / device / assignment allocation ------------------------
    tokens_new: list[list[str]] = [[] for _ in range(r_new)]
    tok_gid_new: list[dict[str, int]] = [{} for _ in range(r_new)]
    next_dev = np.zeros((r_new, s_sh), np.int64)
    next_asg = np.zeros((r_new, s_sh), np.int64)
    token_device_new: list[dict[str, int]] = [{} for _ in range(r_new)]
    devices_new: list[dict] = [{} for _ in range(r_new)]
    assignments_new: list[dict] = [{} for _ in range(r_new)]
    device_slots_new: list[dict] = [{} for _ in range(r_new)]
    parents_dropped = 0
    for o, (host, _) in enumerate(olds):
        m = maps[o]
        m.dev_target = np.full((s_sh, n_cap), NULL_ID, np.int64)
        m.dev_new_local = np.full((s_sh, n_cap), NULL_ID, np.int64)
        m.dev_new_shard = np.full((s_sh, n_cap), NULL_ID, np.int64)
        m.asg_new_local = np.full((s_sh, g_cap), NULL_ID, np.int64)
        gid_target: dict[int, tuple[int, int]] = {}
        for gid, token in enumerate(host["tokens"]):
            t = owner_rank(token, r_new)
            new_gid = len(tokens_new[t])
            if new_gid >= s_sh * t_cap:
                raise ValueError(f"target rank {t} exceeds token "
                                 f"capacity {s_sh * t_cap}")
            tokens_new[t].append(token)
            tok_gid_new[t][token] = new_gid
            gid_target[gid] = (t, new_gid)
        gdid_map: dict[int, tuple[int, int]] = {}
        for gid_str, old_gdid in sorted(host["token_device"].items(),
                                        key=lambda kv: kv[1]):
            gid = int(gid_str)
            t, new_gid = gid_target[gid]
            sn = new_gid % s_sh
            dn = int(next_dev[t, sn])
            if dn >= n_cap:
                raise ValueError(f"target rank {t} shard {sn} exceeds "
                                 f"device capacity {n_cap}")
            next_dev[t, sn] += 1
            so, do = old_gdid % s_sh, old_gdid // s_sh
            m.dev_target[so, do] = t
            m.dev_new_local[so, do] = dn
            m.dev_new_shard[so, do] = sn
            new_gdid = dn * s_sh + sn
            gdid_map[old_gdid] = (t, new_gdid)
            token_device_new[t][str(new_gid)] = new_gdid
            info = host["devices"].get(str(old_gdid))
            if info is not None:
                devices_new[t][str(new_gdid)] = info
        gaid_map: dict[int, tuple[int, int]] = {}
        for gaid_str in sorted(host["assignments"], key=int):
            gaid = int(gaid_str)
            info = dict(host["assignments"][gaid_str])
            so, ao = gaid % s_sh, gaid // s_sh
            tok = info["device_token"]
            t = owner_rank(tok, r_new)
            new_gid = tok_gid_new[t].get(tok)
            if new_gid is None or str(new_gid) not in token_device_new[t]:
                continue   # device gone: drop the assignment
            sn = new_gid % s_sh
            an = int(next_asg[t, sn])
            if an >= g_cap:
                raise ValueError(f"target rank {t} shard {sn} exceeds "
                                 f"assignment capacity {g_cap}")
            next_asg[t, sn] += 1
            m.asg_new_local[so, ao] = an
            new_gaid = an * s_sh + sn
            gaid_map[gaid] = (t, new_gaid)
            info["id"] = new_gaid
            assignments_new[t][str(new_gaid)] = info
        for k, slots in host["device_slots"].items():
            mapped = gdid_map.get(int(k))
            if mapped is None:
                continue
            t, new_gdid = mapped
            device_slots_new[t][str(new_gdid)] = [
                gaid_map[a][1] if (a != NULL_ID and a in gaid_map
                                   and gaid_map[a][0] == t) else NULL_ID
                for a in slots]

    # ---- per-target assembly -------------------------------------------
    stats: dict = {"targets": []}
    ring_unmapped = 0
    n_arenas = olds[0][1][".store.cursor"].shape[-1]
    acap = c_cap // n_arenas
    data0 = olds[0][1]
    store_keys = [k for k in data0 if k.startswith(".store.")
                  and k not in (".store.cursor", ".store.epoch")]

    for t in range(r_new):
        snap_dir = out_root / f"rank-{t}" / "snapshot"
        snap_dir.mkdir(parents=True, exist_ok=True)
        arch_dir = out_root / f"rank-{t}" / "archive"
        out: dict[str, np.ndarray] = {}

        # ---- registry + device_state + token map ---------------------
        for key, arr0 in data0.items():
            if key in (".next_device", ".next_assignment") or \
               key.startswith(".metrics.") or key.startswith(".store."):
                continue
            if key.endswith("token_to_device"):
                new = np.full((s_sh, t_cap), NULL_ID, arr0.dtype)
                for gid_str, new_gdid in token_device_new[t].items():
                    gid = int(gid_str)
                    new[gid % s_sh, gid // s_sh] = new_gdid // s_sh
                out[key] = new
                continue
            fill = (False if arr0.dtype == np.bool_
                    else _fill_like(key, arr0))
            new = np.full((s_sh,) + arr0.shape[1:], fill, arr0.dtype)
            for o, (host, data) in enumerate(olds):
                m = maps[o]
                arr = data[key]
                if key.startswith(".registry.device") or \
                        key.startswith(".device_state."):
                    so, do = np.nonzero(m.dev_target == t)
                    if not len(so):
                        continue
                    sn = m.dev_new_shard[so, do]
                    dn = m.dev_new_local[so, do]
                    vals, dropped_p = _remap_device_column(
                        key, arr[so, do], so, do, m, t)
                    parents_dropped += dropped_p
                    new[sn, dn] = vals.astype(arr.dtype)
                elif key.startswith(".registry.assignment"):
                    so, ao = np.nonzero(m.asg_new_local != NULL_ID)
                    if not len(so):
                        continue
                    devs = data[".registry.assignment_device"][so, ao]\
                        .astype(np.int64)
                    okd = (devs != NULL_ID) & (devs >= 0) & (devs < n_cap)
                    here = np.zeros(len(so), bool)
                    here[okd] = m.dev_target[so[okd], devs[okd]] == t
                    so, ao, devs = so[here], ao[here], devs[here]
                    if not len(so):
                        continue
                    an = m.asg_new_local[so, ao]
                    sn = m.dev_new_shard[so, devs]
                    vals = arr[so, ao]
                    if key.endswith("assignment_device"):
                        vals = m.dev_new_local[so, devs]
                    elif key.endswith("assignment_area"):
                        vals = _remap(vals, m.interner["areas"])
                    elif key.endswith("assignment_customer"):
                        vals = _remap(vals, m.interner["customers"])
                    elif key.endswith("assignment_asset"):
                        vals = _remap(vals, m.interner["assets"])
                    new[sn, an] = vals.astype(arr.dtype)
                else:
                    raise ValueError(f"unhandled snapshot leaf {key!r}")
            out[key] = new

        # ---- ring rows: remap, merge by event time, re-pack ----------
        chunks: list[dict] = []
        for o, (host, data) in enumerate(olds):
            m = maps[o]
            for so in range(s_sh):
                for a in range(n_arenas):
                    cursor = int(data[".store.cursor"][so][a])
                    epoch = int(data[".store.epoch"][so][a])
                    local = (np.concatenate([np.arange(cursor, acap),
                                             np.arange(cursor)])
                             if epoch > 0 else np.arange(cursor))
                    order = a * acap + local
                    order = order[data[".store.valid"][so][order]]
                    if not len(order):
                        continue
                    cols = {k: data[k][so][order] for k in store_keys}
                    sub, unm = m.remap_store_cols(cols, so, t)
                    if t == 0:      # target-independent; count once
                        ring_unmapped += unm
                    if sub is not None:
                        chunks.append(sub)
        merged = None
        if chunks:
            merged = {k: np.concatenate([c[k] for c in chunks])
                      for k in chunks[0]}
            # event-time order decides ring priority on overflow (oldest
            # drop to the archive) — cross-source append order has no
            # global meaning, timestamps do
            order = np.argsort(merged[".store.ts_ms"].astype(np.int64),
                               kind="stable")
            merged = {k: v[order] for k, v in merged.items()}

        new_cursor = np.zeros((s_sh, n_arenas), np.int32)
        new_epoch = np.zeros((s_sh, n_arenas), np.int32)
        for k in store_keys:
            out[k] = np.zeros((s_sh,) + data0[k].shape[1:],
                              data0[k].dtype)
            if k in (".store.device", ".store.assignment",
                     ".store.tenant", ".store.area", ".store.customer",
                     ".store.asset", ".store.aux"):
                out[k][:] = NULL_ID
        dropped: dict[tuple[int, int], dict] = {}
        kept_rows: dict[tuple[int, int], dict] = {}
        if merged is not None:
            shards = merged.pop("__shard__")
            tenants = merged[".store.tenant"].astype(np.int64)
            arena_col = np.where(tenants >= 0, tenants % n_arenas, 0)
            for sn in range(s_sh):
                for a in range(n_arenas):
                    sel = (shards == sn) & (arena_col == a)
                    n = int(sel.sum())
                    if not n:
                        continue
                    sub = {k: v[sel] for k, v in merged.items()}
                    if n > acap:
                        dropped[(sn, a)] = {k: v[:n - acap]
                                            for k, v in sub.items()}
                        sub = {k: v[n - acap:] for k, v in sub.items()}
                        n = acap
                    kept_rows[(sn, a)] = sub
                    for k in store_keys:
                        out[k][sn, a * acap:a * acap + n] = sub[k]
                    new_cursor[sn, a] = n % acap
                    new_epoch[sn, a] = n // acap

        # ---- archive row-copy ----------------------------------------
        arch_stats = None
        if old_archive_dirs is not None:
            n_kept = {(sn, a): int(new_epoch[sn, a]) * acap
                      + int(new_cursor[sn, a])
                      for sn in range(s_sh) for a in range(n_arenas)}
            arch_stats = _migrate_cluster_archive(
                olds, maps, old_archive_dirs, arch_dir, target=t,
                s_sh=s_sh, n_arenas=n_arenas, acap=acap,
                dropped=dropped, kept_rows=kept_rows, n_kept=n_kept)
            for (sn, a), bump in arch_stats["epoch_bump"].items():
                new_epoch[sn, a] += bump
        out[".store.cursor"] = new_cursor
        out[".store.epoch"] = new_epoch

        # ---- counters + manifests ------------------------------------
        out[".next_device"] = next_dev[t].astype(
            data0[".next_device"].dtype)
        out[".next_assignment"] = next_asg[t].astype(
            data0[".next_assignment"].dtype)
        for key in data0:
            if key.startswith(".metrics."):
                # shard-axis fold only: the per-tenant counter grid keeps
                # its trailing [T, C] shape
                new = np.zeros((s_sh,) + data0[key].shape[1:],
                               data0[key].dtype)
                if t == 0:   # global totals, exact, attributed once
                    new[0] = sum(d[key].sum(axis=0) for _, d in olds)
                out[key] = new
        np.savez_compressed(snap_dir / "sharded_state.npz", **out)

        sharded_manifest = json.loads(
            (pathlib.Path(old_snap_dirs[0]) /
             "sharded_manifest.json").read_text())
        sharded_manifest["n_shards"] = s_sh
        (snap_dir / "sharded_manifest.json").write_text(
            json.dumps(sharded_manifest))

        host_new = {
            "format": 1,
            "config": dict(cfg, n_shards=s_sh, wal_dir=None,
                           archive_dir=(str(arch_dir)
                                        if old_archive_dirs is not None
                                        else None)),
            "n_shards": s_sh,
            "epoch_base_unix_s": base,
            "store_cursor": int((new_epoch.astype(np.int64) * acap
                                 + new_cursor).sum()),
            "next_device": [int(x) for x in next_dev[t]],
            "next_assignment": [int(x) for x in next_asg[t]],
            "tokens": tokens_new[t],
            "token_device": token_device_new[t],
            "devices": devices_new[t],
            "assignments": assignments_new[t],
            "device_slots": device_slots_new[t],
            # union interners: identical tables on every target keep the
            # remapped columns valid everywhere
            **{k: union[k] for k in _UNION_KINDS},
            # dead letters are rank-local diagnostics; they ride with
            # target 0 (duplicating them would double-count)
            "dead_letters": (sum((h["dead_letters"] for h, _ in olds),
                                 [])[-4096:] if t == 0 else []),
        }
        (snap_dir / "host_distributed.json").write_text(
            json.dumps(host_new))
        tstat = {"rank": t, "snapshot": str(snap_dir),
                 "devices": len(devices_new[t]),
                 "ring_rows": int(sum(
                     v[".store.ts_ms"].shape[0]
                     for v in kept_rows.values()))}
        if arch_stats is not None:
            tstat.update(archive=str(arch_dir),
                         archive_rows=arch_stats["migrated_rows"],
                         preserved_overflow_rows=arch_stats[
                             "preserved_overflow_rows"],
                         dropped_unmapped_rows=arch_stats[
                             "dropped_unmapped_rows"])
        stats["targets"].append(tstat)
    stats["cross_target_parents_dropped"] = parents_dropped
    stats["ring_unmapped_rows"] = ring_unmapped
    return stats


def _remap_device_column(key: str, vals: np.ndarray, so: np.ndarray,
                         do: np.ndarray, m: _Maps,
                         target: int) -> tuple[np.ndarray, int]:
    """Remap one gathered device-indexed column; returns (values,
    parents_dropped)."""
    if key.endswith("device_tenant"):
        return _remap(vals, m.interner["tenants"]), 0
    if key.endswith(".registry.device_type"):
        return _remap(vals, m.interner["device_types"]), 0
    if key.endswith("device_area"):
        return _remap(vals, m.interner["areas"]), 0
    if key.endswith("device_customer"):
        return _remap(vals, m.interner["customers"]), 0
    if key.endswith("recent_alert_type"):
        return _remap(vals, m.interner["alert_types"]), 0
    if key.endswith("device_assignments"):
        v = vals.astype(np.int64)
        out = np.full_like(v, NULL_ID)
        ok = (v != NULL_ID) & (v >= 0) & (v < m.asg_new_local.shape[1])
        sh = np.broadcast_to(so.reshape((-1, 1)), v.shape)
        out[ok] = m.asg_new_local[sh[ok], v[ok]]
        return out, 0
    if key.endswith("device_parent"):
        # the parent column is shard-local: it survives only when the
        # parent lands on the SAME target and SAME new shard as the child
        v = vals.astype(np.int64)
        out = np.full_like(v, NULL_ID)
        ok = (v != NULL_ID) & (v >= 0) & (v < m.dev_target.shape[1])
        child_shard = m.dev_new_shard[so, do]
        keep = np.zeros_like(ok)
        keep[ok] = ((m.dev_target[so[ok], v[ok]] == target)
                    & (m.dev_new_shard[so[ok], v[ok]] == child_shard[ok]))
        out[keep] = m.dev_new_local[so[keep], v[keep]]
        return out, int(np.sum(ok & ~keep))
    if key in _LANE_LEAVES:
        fill = (False if vals.dtype == np.bool_ else _fill_like(key, vals))
        return _permute_lanes(vals, m.lane_src, m.lane_dst, fill), 0
    return vals, 0


def _migrate_cluster_archive(olds, maps, old_archive_dirs, arch_dst,
                             *, target: int, s_sh: int, n_arenas: int,
                             acap: int, dropped: dict, kept_rows: dict,
                             n_kept: dict) -> dict:
    """Row-copy one target's share of every old rank's archive (plus the
    re-pack's overflow-dropped rows, plus an eager spill of the kept ring
    rows) into a fresh archive at ``arch_dst`` — the cross-rank analog of
    reshard._migrate_archive, with interner/lane remapping per source.
    Position order per new partition: archived history (old-rank-major,
    old write order), then overflow rows, then the epoch-bumped kept
    rows; gaps are registered so replay never counts phantom loss."""
    from sitewhere_tpu.utils.archive import (_COLUMNS, EventArchive,
                                             mesh_topology)

    arch = EventArchive(pathlib.Path(arch_dst),
                        segment_rows=max(1, acap // 4),
                        topology=mesh_topology(s_sh, n_arenas))
    if arch.total_rows():
        raise ValueError(f"archive destination {arch_dst} is not empty")

    writers: dict[int, list] = {}
    next_pos: dict[int, int] = {}

    def emit(part: int, cols: dict) -> None:
        """Append remapped rows (store-key naming) to a partition,
        flushing full segments. Chunks are normalized (no __shard__,
        always a valid column) so cross-source concatenation is safe."""
        cols = {k: v for k, v in cols.items() if k != "__shard__"}
        n = int(cols[".store.ts_ms"].shape[0])
        if not n:
            return
        cols.setdefault(".store.valid", np.ones(n, bool))
        writers.setdefault(part, []).append(cols)
        pending = sum(int(c[".store.ts_ms"].shape[0])
                      for c in writers[part])
        while pending >= arch.segment_rows:
            pending = _flush(part, arch.segment_rows)

    def _flush(part: int, n: int) -> int:
        mergedc = {k: np.concatenate([c[k] for c in writers[part]])
                   for k in writers[part][0]}
        plain = {k.split(".")[-1]: v for k, v in mergedc.items()}
        arch.append_segment(part, next_pos.get(part, 0),
                            types.SimpleNamespace(
                                **{c: plain[c][:n] for c in _COLUMNS}))
        next_pos[part] = next_pos.get(part, 0) + n
        rest = {k: v[n:] for k, v in mergedc.items()}
        writers[part] = ([rest]
                         if rest[".store.ts_ms"].shape[0] else [])
        return sum(int(c[".store.ts_ms"].shape[0])
                   for c in writers[part])

    migrated = unmapped = 0
    for o, (host, data) in enumerate(olds):
        if old_archive_dirs[o] is None:
            continue
        src = pathlib.Path(old_archive_dirs[o])
        m = maps[o]
        old_cursor = np.asarray(data[".store.cursor"], np.int64)
        old_epoch = np.asarray(data[".store.epoch"], np.int64)
        from sitewhere_tpu.utils.archive import _COLUMNS as AC
        for f in sorted(src.glob("seg-*.npz")):
            with np.load(f) as z:
                part, start = int(z["part"]), int(z["start"])
                so, a_old = part // n_arenas, part % n_arenas
                head = int(old_epoch[so, a_old] * acap
                           + old_cursor[so, a_old])
                boundary = max(0, head - acap)
                cols = {c: np.asarray(z[c]) for c in AC}
            n = cols["ts_ms"].shape[0]
            pos = start + np.arange(n)
            # rows at/above the boundary live in the (migrated) ring —
            # skipping them here keeps the two tiers non-overlapping
            keep = cols["valid"].astype(bool) & (pos < boundary)
            if not np.any(keep):
                continue
            sk = {f".store.{c}": cols[c][keep] for c in AC
                  if c != "valid"}
            sub, unm = m.remap_store_cols(sk, so, target)
            if target == 0:     # target-independent; count once
                unmapped += unm
            if sub is None:
                continue
            migrated += int(sub[".store.ts_ms"].shape[0])
            tenants = sub[".store.tenant"].astype(np.int64)
            arena_new = np.where(tenants >= 0, tenants % n_arenas, 0)
            parts_new = sub["__shard__"] * n_arenas + arena_new
            for p in np.unique(parts_new):
                sel = parts_new == p
                emit(int(p), {k: v[sel] for k, v in sub.items()})

    # re-pack overflow rows follow the archived history
    preserved = 0
    for (sn, a), cols in dropped.items():
        preserved += int(cols[".store.ts_ms"].shape[0])
        emit(sn * n_arenas + a, dict(cols))

    # seal history, compute epoch bumps, eager-spill the kept ring rows
    epoch_bump: dict[tuple[int, int], int] = {}
    all_parts = set(writers) | {sn * n_arenas + a
                                for sn, a in kept_rows}
    for p in sorted(all_parts):
        pending = sum(int(c[".store.ts_ms"].shape[0])
                      for c in writers.get(p, []))
        if pending:
            _flush(p, pending)
        h = next_pos.get(p, 0)
        key = (p // n_arenas, p % n_arenas)
        kept = n_kept.get(key, 0)
        # the ring+archive query merge caps archive reads at head - acap
        # = bump*acap + kept - acap; the bump lifts that cap past H so
        # the migrated tail stays visible even with a part-full ring
        bump = -(-(h + acap - kept) // acap) if h else 0
        epoch_bump[key] = bump
        arch.register_gap(p, h, bump * acap)
        ring = kept_rows.get(key)
        if ring is not None and kept:
            plain = {k.split(".")[-1]: v for k, v in ring.items()}
            plain["valid"] = np.ones(kept, bool)
            from sitewhere_tpu.utils.archive import _COLUMNS as AC
            pos = 0
            while pos < kept:
                n = min(arch.segment_rows, kept - pos)
                arch.append_segment(
                    p, bump * acap + pos, types.SimpleNamespace(
                        **{c: plain[c][pos:pos + n] for c in AC}))
                pos += n
        else:
            arch._spilled[p] = bump * acap
    arch._save_index()
    return {"migrated_rows": migrated,
            "preserved_overflow_rows": preserved,
            "dropped_unmapped_rows": unmapped,
            "epoch_bump": epoch_bump}


def replay_wal_tails(cluster, old_snap_dirs, old_wal_dirs) -> int:
    """Replay each old rank's POST-SNAPSHOT WAL tail through the live
    (already migrated) cluster — the O(tail) finishing step. Unlike
    ``replay_wal_through``, a pruned WAL is fine here: everything at or
    below the snapshot watermark is already carried by the migrated
    snapshot + archive, so only records past the watermark replay (and a
    pruned-away span below it was, by definition, snapshot-covered).

    Fails LOUDLY BUT GRACEFULLY on bad inputs: every (snapshot, WAL)
    pair is validated BEFORE the first record replays, so a missing
    snapshot manifest or a missing/unreadable WAL directory raises with
    nothing applied — never mid-loop with earlier ranks' tails already
    in the new cluster (a half-applied migration the operator cannot
    safely re-run). A WAL directory that EXISTS but holds no segments
    (pruned to nothing after the snapshot — a supported state) is a
    zero-record tail: it logs a warning and replays nothing."""
    from sitewhere_tpu.utils.checkpoint import replay_records
    from sitewhere_tpu.utils.ingestlog import IngestLog

    # materialize ONCE: generator arguments must not be exhausted by the
    # length check (a silently-empty zip afterwards would be exactly the
    # dropped-tail failure this validation exists to prevent)
    old_snap_dirs = list(old_snap_dirs)
    old_wal_dirs = list(old_wal_dirs)
    if len(old_snap_dirs) != len(old_wal_dirs):
        raise ValueError(
            f"{len(old_snap_dirs)} snapshot dirs vs "
            f"{len(old_wal_dirs)} WAL dirs — one WAL tail per "
            "old rank")
    # validate EVERYTHING up front: a failure here strands nothing
    pairs = []
    for i, (snap_dir, wal_dir) in enumerate(zip(old_snap_dirs,
                                                old_wal_dirs)):
        manifest = pathlib.Path(snap_dir) / "host_distributed.json"
        try:
            host = json.loads(manifest.read_text())
        except OSError as e:
            raise ValueError(
                f"old rank {i}: snapshot manifest {manifest} "
                f"unreadable ({e}) — nothing was replayed") from e
        if wal_dir is None:
            raise ValueError(
                f"old rank {i}: WAL dir is None — pass the rank's WAL "
                "directory (an empty one is fine; a missing one is "
                "not). Nothing was replayed")
        wpath = pathlib.Path(wal_dir)
        if not wpath.is_dir():
            raise ValueError(
                f"old rank {i}: WAL dir {wpath} does not exist — a "
                "wrong path here would silently drop the rank's "
                "post-snapshot tail. Nothing was replayed")
        if not sorted(wpath.glob("segment-*.log")):
            logger.warning(
                "old rank %d: WAL dir %s holds no segments (pruned to "
                "nothing after the snapshot) — zero-record tail", i,
                wpath)
        pairs.append((host, wpath))

    total = 0
    for host, wpath in pairs:
        wal = IngestLog(wpath, readonly=True)
        try:
            total += replay_records(wal, cluster.ingest_json_batch,
                                    cluster.ingest_binary_batch,
                                    after_cursor=host["store_cursor"])
        finally:
            wal.close()
    cluster.flush()
    return total
