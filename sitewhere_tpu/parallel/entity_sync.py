"""Cluster-replicated management-entity plane.

In the reference, every replica of a service shares one per-tenant
database: a device type created through any node is instantly usable by
all replicas (RdbDeviceManagement.java:127-159 persists device types,
commands, areas, customers, zones, and groups through a shared JPA entity
manager). Round-4's cluster kept these EntityStores rank-local, with a
documented "repeat the admin call per rank" recipe — the last structural
gap between cluster demo and cluster product (VERDICT r4 missing #1).

This module closes it with STATE-BASED replication over the cluster RPC:

  * every management mutation — device types, commands, statuses,
    customers/areas/zones, groups + elements, assets, schedules/jobs,
    tenants, users/roles — fires an ``on_change`` hook that ships the
    entity's POST-state (not the operation), so closure-based updates
    (the REST tier's ``_store_update`` PUT handlers), password hashing
    (only the PBKDF2 hash ever leaves the process), and audit metadata
    (ids, created/updated stamps) replicate byte-identically;
  * each op carries ``(origin_rank, seq, ts)``: per-origin sequences make
    delivery idempotent and gap-detectable, and last-writer-wins on
    ``(ts, origin)`` makes concurrent same-entity writes converge to the
    SAME value on every rank — eventual consistency with deterministic
    tie-break, the multi-master analog of the reference's single shared
    DB row;
  * ops journal to a CRC'd segmented log (the ingest WAL's framing)
    BEFORE broadcast, so a SIGKILL'd rank replays its full entity plane
    on restart, then pulls anything it missed from any live peer
    (``entityOpsSince`` anti-entropy — every rank journals every op it
    has seen, own or received, so ONE live peer can backfill everything);
  * broadcast is push for latency + pull for convergence: a peer that
    detects a sequence gap answers with its vector and the sender
    back-fills the exact missing range; a periodic anti-entropy pull
    (rank_runtime) heals ranks that were down during a push.

Engine-plane records (devices, assignments, events, state) are NOT
routed through this module — they already replicate by ownership routing
in parallel/cluster.py, exactly as the reference splits Kafka-partitioned
event flow from the shared management DB.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import logging
import queue
import threading
import time
import types
import typing

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------
# generic dataclass <-> JSON-state codec
# --------------------------------------------------------------------------

def to_state(obj):
    """JSON-able post-state of an entity (dataclasses recurse; enums ship
    their value; tuples become lists)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: to_state(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [to_state(x) for x in obj]
    if isinstance(obj, dict):
        return {k: to_state(v) for k, v in obj.items()}
    return obj


def _decode(tp, v):
    if v is None or tp is None:
        return v
    origin = typing.get_origin(tp)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _decode(args[0], v) if len(args) == 1 else v
    if origin in (list, tuple):
        args = typing.get_args(tp)
        inner = args[0] if args else None
        out = [_decode(inner, x) for x in v]
        return tuple(out) if origin is tuple else out
    if origin is dict:
        return v
    if isinstance(tp, type):
        if dataclasses.is_dataclass(tp):
            return from_state(tp, v)
        if issubclass(tp, enum.Enum):
            return tp(v)
    return v


def from_state(cls, data: dict):
    """Rebuild an entity dataclass from its shipped state, restoring
    nested dataclasses (EntityMeta, CommandParameter), enums, and tuple
    fields from the type hints."""
    hints = typing.get_type_hints(cls)
    kwargs = {f.name: _decode(hints.get(f.name), data[f.name])
              for f in dataclasses.fields(cls) if f.name in data}
    return cls(**kwargs)


def _entity_types():
    """kind -> dataclass for every store-backed replicated entity."""
    from sitewhere_tpu.instance.tenants import Tenant
    from sitewhere_tpu.management.assets import Asset, AssetType
    from sitewhere_tpu.management.device_management import (
        Area, AreaType, Customer, CustomerType, DeviceAlarm, DeviceGroup,
        DeviceStatus, DeviceType, Zone)
    from sitewhere_tpu.management.schedule import Schedule, ScheduledJob

    return {
        "device-type": DeviceType, "device-status": DeviceStatus,
        "device-alarm": DeviceAlarm, "customer-type": CustomerType,
        "customer": Customer, "area-type": AreaType, "area": Area,
        "zone": Zone, "device-group": DeviceGroup,
        "asset-type": AssetType, "asset": Asset,
        "schedule": Schedule, "scheduled-job": ScheduledJob,
        "tenant": Tenant,
    }


class EntityReplicator:
    """One per rank: taps every management store's ``on_change``,
    journals + broadcasts ops, applies peer ops, and serves the
    anti-entropy surface."""

    def __init__(self, cluster, instance, log_dir=None):
        self.cluster = cluster
        self.instance = instance
        self.rank = cluster.rank
        self._lock = threading.RLock()
        self._my_seq = 0
        # receipt vector: origin -> highest CONTIGUOUS seq seen (applied
        # or LWW-skipped); the journal and the per-origin op index hold
        # everything counted here. Per-origin lists are contiguous by
        # seq (receipt is contiguous), so "ops since seq s" is a slice,
        # not a scan — anti-entropy stays O(result), not O(history).
        self.vector: dict[int, int] = {}
        self._ops_by_origin: dict[int, list[dict]] = {}
        # LWW register per entity: (kind, token) -> (ts, origin)
        self._last: dict[tuple[str, str], tuple[float, int]] = {}
        self.counters = {"emitted": 0, "applied": 0, "lww_skipped": 0,
                         "push_failures": 0, "gap_backfills": 0,
                         "sync_pulls": 0, "apply_errors": 0}
        self._log = None
        if log_dir is not None:
            from sitewhere_tpu.utils.ingestlog import IngestLog

            self._log = IngestLog(log_dir, segment_bytes=8 << 20)
        self._types = _entity_types()
        self._stores: dict[str, object] = {}
        # pushes run on a dedicated thread: the mutating caller (often a
        # REST handler on the gateway loop) must never block on a peer's
        # connect timeout — anti-entropy covers a failed push anyway
        self._push_q: queue.Queue = queue.Queue()
        self._push_thread: threading.Thread | None = None

    # ---------------------------------------------------------- wiring
    def attach(self) -> None:
        """Replay the journal, then subscribe to every mutation hook.
        Bootstrap entities created in the instance constructor (admin
        user, default tenant/type) predate the hooks and are identical on
        every rank by construction — they are deliberately not ops."""
        inst = self.instance
        dm = inst.device_management
        n = self.cluster.n_ranks
        self._stores = {
            "device-type": dm.device_types, "device-status": dm.statuses,
            "device-alarm": dm.alarms, "customer-type": dm.customer_types,
            "customer": dm.customers, "area-type": dm.area_types,
            "area": dm.areas, "zone": dm.zones, "device-group": dm.groups,
            "asset-type": inst.assets.asset_types,
            "asset": inst.assets.assets,
            "schedule": inst.scheduler.schedules,
            "scheduled-job": inst.scheduler.jobs,
            "tenant": inst.tenants.tenants,
        }
        # rank-namespaced id allocation BEFORE any replay/mutation: two
        # ranks creating entities concurrently must never mint the same
        # id for different tokens (the upsert would clobber the other)
        for store in self._stores.values():
            store.configure_id_space(self.rank, n)
        if self._log is not None:
            replayed = 0
            for payload in self._log.replay():
                op = json.loads(payload)
                with self._lock:
                    if self._count_receipt(op):
                        self._remember(op)
                        self._apply_effect(op)
                        replayed += 1
            if replayed:
                logger.info("rank %d: replayed %d entity ops from journal",
                            self.rank, replayed)
        for store in self._stores.values():
            store.on_change = self._on_store_change
        dm.on_elements_change = self._on_elements_change
        inst.users.on_change = self._on_user_change
        inst.command_registry.on_change = self._on_command_change
        # surface replication metrics on the rank's metric schema (both
        # the facade's local leg and the Cluster.metrics handler read
        # these via local_rank_metrics)
        self.cluster.entity_replicator = self
        self.cluster.local.entity_replicator = self
        # replicated schedules exist on every rank: fire each at exactly
        # one (its token's owner under the device partitioner)
        if self.cluster.n_ranks > 1:
            from sitewhere_tpu.parallel.cluster import owner_rank

            inst.scheduler.fire_filter = (
                lambda tok: owner_rank(tok, self.cluster.n_ranks)
                == self.rank)

    # ------------------------------------------------------ local taps
    def _on_store_change(self, action, kind, token, entity) -> None:
        self._emit(action, kind, token,
                   to_state(entity) if entity is not None else None)

    def _on_elements_change(self, group_token, elements) -> None:
        self._emit("upsert", "group-elements", group_token,
                   [to_state(e) for e in elements])

    def _on_user_change(self, action, kind, key, obj) -> None:
        # kind is "user" (obj: User) or "role" (obj: list[str])
        state = None
        if obj is not None:
            state = to_state(obj) if kind == "user" else list(obj)
        self._emit(action, kind, key, state)

    def _on_command_change(self, action, kind, token, cmd) -> None:
        self._emit(action, kind, token,
                   to_state(cmd) if cmd is not None else None)

    def _remember(self, op: dict) -> None:
        """Index one counted op (lock held)."""
        self._ops_by_origin.setdefault(int(op["origin"]), []).append(op)

    def _emit(self, action, kind, token, state) -> None:
        with self._lock:
            self._my_seq += 1
            op = {"origin": self.rank, "seq": self._my_seq,
                  "ts": time.time() * 1000, "action": action,
                  "kind": kind, "token": token, "state": state}
            self.vector[self.rank] = self._my_seq
            self._last[(kind, token)] = (op["ts"], self.rank)
            self._remember(op)
            self._journal(op)
            self.counters["emitted"] += 1
            if self.cluster.n_ranks > 1:
                # start-check under the lock: two concurrent mutators
                # must not race a SECOND pusher into existence (per-
                # origin push order relies on a single consumer)
                if (self._push_thread is None
                        or not self._push_thread.is_alive()):
                    self._push_thread = threading.Thread(
                        target=self._push_loop, name="entity-push",
                        daemon=True)
                    self._push_thread.start()
                self._push_q.put(op)

    def _journal(self, op: dict) -> None:
        if self._log is not None:
            self._log.append(json.dumps(op).encode())
            # fsync per op: the admin plane is low-rate and a SIGKILL'd
            # rank must replay every acknowledged mutation
            self._log.sync()

    # ------------------------------------------------------- broadcast
    def _push_loop(self) -> None:
        """Single pusher thread: preserves per-origin order, and keeps
        peer connect timeouts OFF the mutating thread (a REST admin
        handler must not stall the gateway on a down peer)."""
        while True:
            op = self._push_q.get()
            if op is None:
                return
            self._push(op)

    def drain_pushes(self, timeout_s: float = 30.0) -> None:
        """Block until every queued push attempt has run (tests and
        ordered shutdown; a FAILED push still counts as drained — the
        journal + anti-entropy own delivery, not the queue)."""
        deadline = time.monotonic() + timeout_s
        while not self._push_q.empty():
            if time.monotonic() > deadline:
                raise TimeoutError("entity push queue did not drain")
            time.sleep(0.01)
        self._push_q.join()

    def _push(self, op: dict) -> None:
        """Best-effort push to every peer; a gap answer triggers an exact
        backfill from our op index; a down peer heals via anti-entropy."""
        c = self.cluster
        try:
            for r in range(c.n_ranks):
                if r == self.rank:
                    continue
                try:
                    res = c._peer(r).call("Cluster.entityOp", op=op)
                    if isinstance(res, dict) and res.get("gap"):
                        self._backfill(r, res.get("vector", {}))
                except Exception:
                    # transport failures AND peer application errors: a
                    # peer's handler raising must not kill the single
                    # pusher thread — anti-entropy owns convergence
                    self.counters["push_failures"] += 1
                    logger.debug("entity push to rank %d failed", r,
                                 exc_info=True)
        finally:
            self._push_q.task_done()

    def _backfill(self, peer_rank: int, their_vector: dict) -> None:
        missing = self.ops_since(their_vector)
        if missing:
            self.counters["gap_backfills"] += 1
            self.cluster._peer(peer_rank).call("Cluster.entityOps",
                                               ops=missing)

    # ----------------------------------------------------------- apply
    def _count_receipt(self, op: dict) -> bool:
        """Advance the receipt vector; False = duplicate or gap (caller
        handles). Must hold the lock."""
        origin, seq = int(op["origin"]), int(op["seq"])
        last = self.vector.get(origin, 0)
        if seq <= last:
            return False
        if seq > last + 1:
            raise _SequenceGap(origin, last)
        self.vector[origin] = seq
        if origin == self.rank:
            self._my_seq = max(self._my_seq, seq)
        return True

    def _apply_effect(self, op: dict) -> None:
        """Apply the op's state change, last-writer-wins per entity."""
        kind, token = op["kind"], op["token"]
        key = (float(op["ts"]), int(op["origin"]))
        existing = self._last.get((kind, token))
        if existing is not None and key < existing:
            self.counters["lww_skipped"] += 1
            return
        self._last[(kind, token)] = key
        try:
            self._apply_state(kind, token, op["action"], op["state"])
            self.counters["applied"] += 1
        except Exception:
            # a malformed or stale-schema op must not wedge the stream
            self.counters["apply_errors"] += 1
            logger.exception("entity op apply failed: %s %s %s",
                             op["action"], kind, token)

    def _apply_state(self, kind, token, action, state) -> None:
        inst = self.instance
        delete = action == "delete"
        if kind == "user":
            from sitewhere_tpu.instance.auth import User

            inst.users.apply_replicated_user(
                token, None if delete else from_state(User, state))
        elif kind == "role":
            inst.users.apply_replicated_role(
                token, None if delete else state)
        elif kind == "device-command":
            from sitewhere_tpu.commands.model import DeviceCommand

            inst.command_registry.apply_replicated(
                token, None if delete else from_state(DeviceCommand, state))
        elif kind == "group-elements":
            from sitewhere_tpu.management.device_management import (
                DeviceGroupElement)

            inst.device_management.apply_replicated_elements(
                token, [from_state(DeviceGroupElement, s) for s in state])
        else:
            store = self._stores[kind]
            if delete:
                store.remove_replicated(token)
            else:
                store.apply_replicated(
                    token, from_state(self._types[kind], state))
                if kind == "tenant":
                    # the tenant LANE interns on the engine too (the
                    # origin does this in create_tenant)
                    self.cluster.local.tenants.intern(token)

    def apply_op(self, op: dict) -> dict:
        """One pushed op from a peer. Returns the RPC answer: applied,
        duplicate-skip, or a gap signal carrying our vector so the
        sender can backfill exactly what we lack."""
        with self._lock:
            try:
                fresh = self._count_receipt(op)
            except _SequenceGap:
                return {"applied": False, "gap": True,
                        "vector": dict(self.vector)}
            if not fresh:
                return {"applied": False, "duplicate": True}
            self._remember(op)
            self._journal(op)
            self._apply_effect(op)
        return {"applied": True}

    def apply_batch(self, ops: list[dict]) -> int:
        """Ordered backfill/pull application; per-origin contiguous
        runs (a peer's knowledge of any origin is always contiguous)."""
        applied = 0
        for op in sorted(ops, key=lambda o: (o["origin"], o["seq"])):
            res = self.apply_op(op)
            if res.get("applied"):
                applied += 1
        return applied

    def ops_since(self, vector: dict) -> list[dict]:
        """Everything the caller lacks, sliced per origin (each origin's
        list is contiguous by seq, so this is O(result))."""
        out = []
        with self._lock:
            for origin, ops in self._ops_by_origin.items():
                if not ops:
                    continue
                seen = int(vector.get(str(origin), vector.get(origin, 0)))
                start = max(0, seen - ops[0]["seq"] + 1)
                out.extend(ops[start:])
        out.sort(key=lambda o: (o["origin"], o["seq"]))
        return out

    # ---------------------------------------------------- anti-entropy
    def sync_from_peers(self, best_effort: bool = True) -> int:
        """Pull everything we lack from every reachable peer (startup
        catch-up + the periodic heal for pushes we missed while down)."""
        total = 0
        c = self.cluster
        for r in range(c.n_ranks):
            if r == self.rank:
                continue
            try:
                with self._lock:
                    vec = dict(self.vector)
                ops = c._peer(r).call("Cluster.entityOpsSince", vector=vec)
                total += self.apply_batch(ops)
            except (ConnectionError, TimeoutError):
                if not best_effort:
                    raise
        self.counters["sync_pulls"] += 1
        return total

    def metrics(self) -> dict:
        with self._lock:
            return {"entity_ops_known": sum(
                        len(v) for v in self._ops_by_origin.values()),
                    "entity_push_queue_depth": self._push_q.qsize(),
                    "entity_vector": {str(k): v
                                      for k, v in sorted(self.vector.items())},
                    **{f"entity_{k}": v for k, v in self.counters.items()}}

    def close(self) -> None:
        if self._push_thread is not None and self._push_thread.is_alive():
            self._push_q.put(None)
            self._push_thread.join(timeout=5)
        if self._log is not None:
            self._log.close()

    def register_rpc(self, srv) -> None:
        """The replication surface on the rank's cluster RPC server."""
        srv.register("Cluster.entityOp", lambda op: self.apply_op(op))
        srv.register("Cluster.entityOps",
                     lambda ops: {"applied": self.apply_batch(ops)})
        srv.register("Cluster.entityOpsSince",
                     lambda vector: self.ops_since(vector))
        srv.register("Cluster.entityVector",
                     lambda: {str(k): v for k, v in self.vector.items()})


class _SequenceGap(Exception):
    def __init__(self, origin: int, last: int):
        super().__init__(f"gap: origin {origin} after seq {last}")
        self.origin = origin
        self.last = last
