"""Cluster-replicated management-entity plane.

In the reference, every replica of a service shares one per-tenant
database: a device type created through any node is instantly usable by
all replicas (RdbDeviceManagement.java:127-159 persists device types,
commands, areas, customers, zones, and groups through a shared JPA entity
manager). Round-4's cluster kept these EntityStores rank-local, with a
documented "repeat the admin call per rank" recipe — the last structural
gap between cluster demo and cluster product (VERDICT r4 missing #1).

This module closes it with STATE-BASED replication over the cluster RPC:

  * every management mutation — device types, commands, statuses,
    customers/areas/zones, groups + elements, assets, schedules/jobs,
    tenants, users/roles — fires an ``on_change`` hook that ships the
    entity's POST-state (not the operation), so closure-based updates
    (the REST tier's ``_store_update`` PUT handlers), password hashing
    (only the PBKDF2 hash ever leaves the process), and audit metadata
    (ids, created/updated stamps) replicate byte-identically;
  * each op carries ``(origin_rank, seq, ts)``: per-origin sequences make
    delivery idempotent and gap-detectable, and last-writer-wins on
    ``(ts, origin)`` makes concurrent same-entity writes converge to the
    SAME value on every rank — eventual consistency with deterministic
    tie-break, the multi-master analog of the reference's single shared
    DB row;
  * ops journal to a CRC'd segmented log (the ingest WAL's framing)
    BEFORE broadcast, so a SIGKILL'd rank replays its full entity plane
    on restart, then pulls anything it missed from any live peer
    (``entityOpsSince`` anti-entropy — every rank journals every op it
    has seen, own or received, so ONE live peer can backfill everything);
  * broadcast is push for latency + pull for convergence: a peer that
    detects a sequence gap answers with its vector and the sender
    back-fills the exact missing range; a periodic anti-entropy pull
    (rank_runtime) heals ranks that were down during a push.

Engine-plane records (devices, assignments, events, state) are NOT
routed through this module — they already replicate by ownership routing
in parallel/cluster.py, exactly as the reference splits Kafka-partitioned
event flow from the shared management DB.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import logging
import pathlib
import queue
import shutil
import threading
import time
import types
import typing

logger = logging.getLogger(__name__)

# state-transfer paging defaults: each page must comfortably clear the
# RPC frame cap (rpc/protocol.MAX_FRAME, 16 MiB) with json overhead
STATE_PAGE_ENTRIES = 512
STATE_PAGE_BYTES = 2 << 20
_MAX_TRANSFERS = 4   # concurrent in-progress state transfers retained


# --------------------------------------------------------------------------
# generic dataclass <-> JSON-state codec
# --------------------------------------------------------------------------

def to_state(obj):
    """JSON-able post-state of an entity (dataclasses recurse; enums ship
    their value; tuples become lists)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: to_state(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [to_state(x) for x in obj]
    if isinstance(obj, dict):
        return {k: to_state(v) for k, v in obj.items()}
    return obj


def _decode(tp, v):
    if v is None or tp is None:
        return v
    origin = typing.get_origin(tp)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _decode(args[0], v) if len(args) == 1 else v
    if origin in (list, tuple):
        args = typing.get_args(tp)
        inner = args[0] if args else None
        out = [_decode(inner, x) for x in v]
        return tuple(out) if origin is tuple else out
    if origin is dict:
        return v
    if isinstance(tp, type):
        if dataclasses.is_dataclass(tp):
            return from_state(tp, v)
        if issubclass(tp, enum.Enum):
            return tp(v)
    return v


def from_state(cls, data: dict):
    """Rebuild an entity dataclass from its shipped state, restoring
    nested dataclasses (EntityMeta, CommandParameter), enums, and tuple
    fields from the type hints."""
    hints = typing.get_type_hints(cls)
    kwargs = {f.name: _decode(hints.get(f.name), data[f.name])
              for f in dataclasses.fields(cls) if f.name in data}
    return cls(**kwargs)


def _entity_types():
    """kind -> dataclass for every store-backed replicated entity."""
    from sitewhere_tpu.instance.tenants import Tenant
    from sitewhere_tpu.management.assets import Asset, AssetType
    from sitewhere_tpu.management.device_management import (
        Area, AreaType, Customer, CustomerType, DeviceAlarm, DeviceGroup,
        DeviceStatus, DeviceType, Zone)
    from sitewhere_tpu.management.schedule import Schedule, ScheduledJob

    return {
        "device-type": DeviceType, "device-status": DeviceStatus,
        "device-alarm": DeviceAlarm, "customer-type": CustomerType,
        "customer": Customer, "area-type": AreaType, "area": Area,
        "zone": Zone, "device-group": DeviceGroup,
        "asset-type": AssetType, "asset": Asset,
        "schedule": Schedule, "scheduled-job": ScheduledJob,
        "tenant": Tenant,
    }


class EntityReplicator:
    """One per rank: taps every management store's ``on_change``,
    journals + broadcasts ops, applies peer ops, and serves the
    anti-entropy surface."""

    def __init__(self, cluster, instance, log_dir=None,
                 compact_threshold: int = 20_000,
                 compact_keep: int = 2_048):
        self.cluster = cluster
        self.instance = instance
        self.rank = cluster.rank
        self._lock = threading.RLock()
        self._my_seq = 0
        # receipt vector: origin -> highest CONTIGUOUS seq seen (applied
        # or LWW-skipped); the journal and the per-origin op index hold
        # everything counted here. Per-origin lists are contiguous by
        # seq (receipt is contiguous), so "ops since seq s" is a slice,
        # not a scan — anti-entropy stays O(result), not O(history).
        self.vector: dict[int, int] = {}
        self._ops_by_origin: dict[int, list[dict]] = {}
        # LWW register per entity: (kind, token) -> (ts, origin).
        # Deleted entities keep their entry as a TOMBSTONE — state
        # transfer ships it so a late joiner deletes too.
        self._last: dict[tuple[str, str], tuple[float, int]] = {}
        # tombstone index: (kind, token) -> (ts, origin, seq). A churny
        # admin plane must not grow _last (and every state-transfer
        # payload) forever: gc_tombstones() drops tombstones once every
        # rank's receipt vector provably covers the delete op — past
        # that horizon no peer can still ship a pre-delete state that
        # would need the LWW entry to lose against.
        self._tombstones: dict[tuple[str, str], tuple[float, int, int]] = {}
        # peer receipt vectors observed during anti-entropy (the GC
        # horizon's evidence)
        self._peer_vectors: dict[int, dict[int, int]] = {}
        self.tombstone_min_age_ms = 60_000.0
        # memory/disk bound: past compact_threshold indexed ops, the
        # index truncates to the newest compact_keep per origin and the
        # journal rewrites as one state dump + the kept tail. A peer
        # behind the truncation floor converges by LWW state transfer
        # (Cluster.entityState) instead of op backfill.
        self.compact_threshold = int(compact_threshold)
        self.compact_keep = int(compact_keep)
        # paged state transfer (ADVICE r5 medium): Cluster.entityState
        # ships fixed-size chunks with a continuation cursor so an LWW
        # dump larger than one RPC frame (MAX_FRAME) can still converge a
        # late joiner. Knobs are instance attrs so tests can shrink them.
        self.state_page_entries = STATE_PAGE_ENTRIES
        self.state_page_bytes = STATE_PAGE_BYTES
        # in-progress transfers: tid -> (key snapshot, vector snapshot).
        # The snapshot pins ordering (no mid-transfer insert can shift the
        # cursor past an unseen entity) and the vector is captured BEFORE
        # the first page, so any op that lands mid-transfer has a seq
        # ABOVE it and back-fills through the puller's next ops_since.
        self._transfers: "dict[str, tuple[list, dict]]" = {}
        # adaptive re-arm: when a wide cluster's per-origin tails alone
        # exceed the configured threshold (n_ranks * keep > threshold),
        # the next trigger moves to 2x the post-compaction residue so
        # compaction never fires on every single mutation
        self._next_compact_at = self.compact_threshold
        self._total_ops = 0
        self.counters = {"emitted": 0, "applied": 0, "lww_skipped": 0,
                         "push_failures": 0, "gap_backfills": 0,
                         "sync_pulls": 0, "apply_errors": 0,
                         "compactions": 0, "state_transfers": 0,
                         "state_pages_served": 0, "tombstones_gcd": 0}
        self._log = None
        self._log_dir = None
        self._compacting = False           # journal snapshot in flight
        self._compact_extra: list[dict] = []   # ops journaled mid-snapshot
        if log_dir is not None:
            from sitewhere_tpu.utils.ingestlog import IngestLog

            d = pathlib.Path(log_dir)
            self._log_dir = d
            # finish a compaction swap the process died inside of: the
            # .new journal was fully synced BEFORE any rename started,
            # so it wins when the live dir is missing
            new_dir = d.with_name(d.name + ".new")
            old_dir = d.with_name(d.name + ".old")
            if not d.exists() and new_dir.exists():
                new_dir.rename(d)
            elif not d.exists() and old_dir.exists():
                old_dir.rename(d)
            shutil.rmtree(new_dir, ignore_errors=True)
            shutil.rmtree(old_dir, ignore_errors=True)
            self._log = IngestLog(d, segment_bytes=8 << 20)
        self._types = _entity_types()
        self._stores: dict[str, object] = {}
        # pushes run on a dedicated thread: the mutating caller (often a
        # REST handler on the gateway loop) must never block on a peer's
        # connect timeout — anti-entropy covers a failed push anyway
        self._push_q: queue.Queue = queue.Queue()
        self._push_thread: threading.Thread | None = None

    # ---------------------------------------------------------- wiring
    def attach(self) -> None:
        """Replay the journal, then subscribe to every mutation hook.
        Bootstrap entities created in the instance constructor (admin
        user, default tenant/type) predate the hooks and are identical on
        every rank by construction — they are deliberately not ops."""
        inst = self.instance
        dm = inst.device_management
        n = self.cluster.n_ranks
        self._stores = {
            "device-type": dm.device_types, "device-status": dm.statuses,
            "device-alarm": dm.alarms, "customer-type": dm.customer_types,
            "customer": dm.customers, "area-type": dm.area_types,
            "area": dm.areas, "zone": dm.zones, "device-group": dm.groups,
            "asset-type": inst.assets.asset_types,
            "asset": inst.assets.assets,
            "schedule": inst.scheduler.schedules,
            "scheduled-job": inst.scheduler.jobs,
            "tenant": inst.tenants.tenants,
        }
        # rank-namespaced id allocation BEFORE any replay/mutation: two
        # ranks creating entities concurrently must never mint the same
        # id for different tokens (the upsert would clobber the other)
        for store in self._stores.values():
            store.configure_id_space(self.rank, n)
        if self._log is not None:
            replayed = 0
            for payload in self._log.replay():
                rec = json.loads(payload)
                with self._lock:
                    if "dump" in rec:
                        # a compaction / state-transfer marker: restore
                        # the dumped state + vector, then the journal's
                        # tail ops count contiguously above it
                        self._apply_dump_locked(rec["dump"], journal=False)
                        replayed += 1
                        continue
                    if self._count_receipt(rec):
                        self._remember(rec)
                        self._apply_effect(rec)
                        replayed += 1
            if replayed:
                logger.info("rank %d: replayed %d entity records from "
                            "journal", self.rank, replayed)
        for store in self._stores.values():
            store.on_change = self._on_store_change
        dm.on_elements_change = self._on_elements_change
        inst.users.on_change = self._on_user_change
        inst.command_registry.on_change = self._on_command_change
        # surface replication metrics on the rank's metric schema (both
        # the facade's local leg and the Cluster.metrics handler read
        # these via local_rank_metrics)
        self.cluster.entity_replicator = self
        self.cluster.local.entity_replicator = self
        # replicated schedules exist on every rank: fire each at exactly
        # one (its token's owner under the device partitioner). With
        # event-plane replication attached, install_fireover replaces
        # this with the failure-aware predicate (dead owner -> first
        # live follower fires, with fencing).
        if self.cluster.n_ranks > 1:
            # ownership through the facade's PLACEMENT map (ISSUE 15) —
            # the same epoch the ingest router and fire-over read, so a
            # moved schedule token fires at exactly one rank
            inst.scheduler.fire_filter = (
                lambda tok: self.cluster.owner(tok) == self.rank)
            # replicate fired state (fired_count/last_fired_ms) so a
            # recovered owner never re-fires a window its follower
            # already covered
            inst.scheduler.on_fired = self._on_job_fired

    # ------------------------------------------------------ local taps
    def _on_store_change(self, action, kind, token, entity) -> None:
        self._emit(action, kind, token,
                   to_state(entity) if entity is not None else None)

    def _on_elements_change(self, group_token, elements) -> None:
        self._emit("upsert", "group-elements", group_token,
                   [to_state(e) for e in elements])

    def _on_user_change(self, action, kind, key, obj) -> None:
        # kind is "user" (obj: User) or "role" (obj: list[str])
        state = None
        if obj is not None:
            state = to_state(obj) if kind == "user" else list(obj)
        self._emit(action, kind, key, state)

    def _on_command_change(self, action, kind, token, cmd) -> None:
        self._emit(action, kind, token,
                   to_state(cmd) if cmd is not None else None)

    def _on_job_fired(self, job) -> None:
        """Scheduler post-fire hook: ship the job's fired state as a
        normal replicated upsert — LWW converges every rank (including
        a recovering owner) onto the newest last_fired_ms."""
        self._emit("upsert", "scheduled-job", job.meta.token,
                   to_state(job))

    def _remember(self, op: dict) -> None:
        """Index one counted op (lock held)."""
        self._ops_by_origin.setdefault(int(op["origin"]), []).append(op)
        self._total_ops += 1

    def _maybe_compact_prepare(self):
        """Threshold check + in-memory compaction + journal snapshot,
        all under the lock (caller holds it). Returns the prepared
        payload for :meth:`_finish_compaction` — which the caller MUST
        run after releasing the lock — or None when no compaction is
        due. The journal rewrite (write + fsync of the whole dump) is
        the expensive half and must not stall every concurrent mutator
        behind the replicator lock."""
        if self._total_ops <= self._next_compact_at or self._compacting:
            return None
        prep = self._compact_prepare_locked(self.compact_keep)
        self._next_compact_at = max(self.compact_threshold,
                                    2 * self._total_ops)
        return prep

    def _emit(self, action, kind, token, state) -> None:
        with self._lock:
            self._my_seq += 1
            op = {"origin": self.rank, "seq": self._my_seq,
                  "ts": time.time() * 1000, "action": action,
                  "kind": kind, "token": token, "state": state}
            self.vector[self.rank] = self._my_seq
            self._last[(kind, token)] = (op["ts"], self.rank)
            self._note_tombstone(kind, token, action, op["ts"], self.rank,
                                 self._my_seq)
            self._remember(op)
            self._journal(op)
            self.counters["emitted"] += 1
            compact_prep = self._maybe_compact_prepare()
            if self.cluster.n_ranks > 1:
                # start-check under the lock: two concurrent mutators
                # must not race a SECOND pusher into existence (per-
                # origin push order relies on a single consumer)
                if (self._push_thread is None
                        or not self._push_thread.is_alive()):
                    self._push_thread = threading.Thread(
                        target=self._push_loop, name="entity-push",
                        daemon=True)
                    self._push_thread.start()
                self._push_q.put(op)
        if compact_prep is not None:
            self._finish_compaction(compact_prep)

    def _journal(self, op: dict) -> None:
        if self._log is not None:
            self._log.append(json.dumps(op).encode())
            # fsync per op: the admin plane is low-rate and a SIGKILL'd
            # rank must replay every acknowledged mutation
            self._log.sync()
            if self._compacting:
                # a compaction snapshot is being written out: this op is
                # durable in the OLD journal, but the new journal's
                # snapshot predates it — queue it so the swap appends it
                # to the new journal before the rename
                self._compact_extra.append(op)

    # ------------------------------------------------------- broadcast
    def _push_loop(self) -> None:
        """Single pusher thread: preserves per-origin order, and keeps
        peer connect timeouts OFF the mutating thread (a REST admin
        handler must not stall the gateway on a down peer)."""
        while True:
            op = self._push_q.get()
            if op is None:
                return
            self._push(op)

    def drain_pushes(self, timeout_s: float = 30.0) -> None:
        """Block until every queued push attempt has run (tests and
        ordered shutdown; a FAILED push still counts as drained — the
        journal + anti-entropy own delivery, not the queue)."""
        deadline = time.monotonic() + timeout_s
        while not self._push_q.empty():
            if time.monotonic() > deadline:
                raise TimeoutError("entity push queue did not drain")
            time.sleep(0.01)
        self._push_q.join()

    def _push(self, op: dict) -> None:
        """Best-effort push to every peer; a gap answer triggers an exact
        backfill from our op index; a down peer heals via anti-entropy."""
        c = self.cluster
        try:
            for r in range(c.n_ranks):
                if r == self.rank:
                    continue
                try:
                    res = c._peer(r).call("Cluster.entityOp", op=op)
                    if isinstance(res, dict) and res.get("gap"):
                        self._backfill(r, res.get("vector", {}))
                except Exception:
                    # transport failures AND peer application errors: a
                    # peer's handler raising must not kill the single
                    # pusher thread — anti-entropy owns convergence
                    self.counters["push_failures"] += 1
                    logger.debug("entity push to rank %d failed", r,
                                 exc_info=True)
        finally:
            self._push_q.task_done()

    def _backfill(self, peer_rank: int, their_vector: dict) -> None:
        missing = self.ops_since(their_vector)
        if isinstance(missing, dict):
            # peer is behind our compaction floor: it converges by
            # pulling Cluster.entityState on its next anti-entropy pass
            logger.info("peer %d behind the entity compaction floor; "
                        "deferring to its state-transfer pull", peer_rank)
            return
        if missing:
            self.counters["gap_backfills"] += 1
            self.cluster._peer(peer_rank).call("Cluster.entityOps",
                                               ops=missing)

    # ----------------------------------------------------------- apply
    def _count_receipt(self, op: dict) -> bool:
        """Advance the receipt vector; False = duplicate or gap (caller
        handles). Must hold the lock."""
        origin, seq = int(op["origin"]), int(op["seq"])
        last = self.vector.get(origin, 0)
        if seq <= last:
            return False
        if seq > last + 1:
            raise _SequenceGap(origin, last)
        self.vector[origin] = seq
        if origin == self.rank:
            self._my_seq = max(self._my_seq, seq)
        return True

    def _apply_effect(self, op: dict) -> None:
        """Apply the op's state change, last-writer-wins per entity."""
        kind, token = op["kind"], op["token"]
        key = (float(op["ts"]), int(op["origin"]))
        existing = self._last.get((kind, token))
        if existing is not None and key < existing:
            self.counters["lww_skipped"] += 1
            return
        self._last[(kind, token)] = key
        self._note_tombstone(kind, token, op["action"], float(op["ts"]),
                             int(op["origin"]), int(op["seq"]))
        try:
            self._apply_state(kind, token, op["action"], op["state"])
            self.counters["applied"] += 1
        except Exception:
            # a malformed or stale-schema op must not wedge the stream
            self.counters["apply_errors"] += 1
            logger.exception("entity op apply failed: %s %s %s",
                             op["action"], kind, token)

    def _apply_state(self, kind, token, action, state) -> None:
        inst = self.instance
        delete = action == "delete"
        if kind == "user":
            from sitewhere_tpu.instance.auth import User

            inst.users.apply_replicated_user(
                token, None if delete else from_state(User, state))
        elif kind == "role":
            inst.users.apply_replicated_role(
                token, None if delete else state)
        elif kind == "device-command":
            from sitewhere_tpu.commands.model import DeviceCommand

            inst.command_registry.apply_replicated(
                token, None if delete else from_state(DeviceCommand, state))
        elif kind == "group-elements":
            from sitewhere_tpu.management.device_management import (
                DeviceGroupElement)

            inst.device_management.apply_replicated_elements(
                token, [from_state(DeviceGroupElement, s) for s in state])
        else:
            store = self._stores[kind]
            if delete:
                store.remove_replicated(token)
            else:
                store.apply_replicated(
                    token, from_state(self._types[kind], state))
                if kind == "tenant":
                    # the tenant LANE interns on the engine too (the
                    # origin does this in create_tenant)
                    self.cluster.local.tenants.intern(token)

    def apply_op(self, op: dict) -> dict:
        """One pushed op from a peer. Returns the RPC answer: applied,
        duplicate-skip, or a gap signal carrying our vector so the
        sender can backfill exactly what we lack."""
        with self._lock:
            try:
                fresh = self._count_receipt(op)
            except _SequenceGap:
                return {"applied": False, "gap": True,
                        "vector": dict(self.vector)}
            if not fresh:
                return {"applied": False, "duplicate": True}
            self._remember(op)
            self._journal(op)
            self._apply_effect(op)
            compact_prep = self._maybe_compact_prepare()
        if compact_prep is not None:
            self._finish_compaction(compact_prep)
        return {"applied": True}

    def apply_batch(self, ops: list[dict]) -> int:
        """Ordered backfill/pull application; per-origin contiguous
        runs (a peer's knowledge of any origin is always contiguous)."""
        applied = 0
        for op in sorted(ops, key=lambda o: (o["origin"], o["seq"])):
            res = self.apply_op(op)
            if res.get("applied"):
                applied += 1
        return applied

    def ops_since(self, vector: dict) -> "list[dict] | dict":
        """Everything the caller lacks, sliced per origin (each origin's
        list is contiguous by seq, so this is O(result)). When the caller
        is behind a compaction floor — we no longer hold the ops it needs
        — returns ``{"reset": True}``: the caller must converge by LWW
        state transfer (:meth:`state_dump`) instead of op backfill."""
        out = []
        with self._lock:
            for origin, have in self.vector.items():
                seen = int(vector.get(str(origin), vector.get(origin, 0)))
                if seen >= have:
                    continue
                ops = self._ops_by_origin.get(origin) or []
                if not ops or ops[0]["seq"] > seen + 1:
                    return {"reset": True}
                out.extend(ops[seen - ops[0]["seq"] + 1:])
        out.sort(key=lambda o: (o["origin"], o["seq"]))
        return out

    # --------------------------------------------- state dump / compaction
    def _current_state(self, kind: str, token: str):
        """The entity's live post-state (None = deleted/absent)."""
        inst = self.instance
        if kind == "user":
            u = inst.users.users.get(token)
            return to_state(u) if u is not None else None
        if kind == "role":
            r = inst.users.roles.get(token)
            return list(r) if r is not None else None
        if kind == "device-command":
            c = inst.command_registry.get(token)
            return to_state(c) if c is not None else None
        if kind == "group-elements":
            els = inst.device_management._group_elements.get(token)
            return ([to_state(e) for e in els]
                    if els is not None else None)
        store = self._stores.get(kind)
        if store is None:
            return None
        e = store.try_get(token)
        return to_state(e) if e is not None else None

    def _state_dump_locked(self, vector: dict | None = None) -> dict:
        """Every entity the plane has ever touched (tombstones included)
        with its LWW key, plus a receipt vector. ``vector`` overrides the
        shipped vector: compaction journals the dump with the vector
        REWOUND to just below the kept tail so replay re-counts (and
        re-indexes) the tail contiguously above it."""
        entries = [{"kind": k, "token": t, "ts": ts, "origin": origin,
                    "state": self._current_state(k, t)}
                   for (k, t), (ts, origin) in self._last.items()]
        return {"vector": dict(self.vector if vector is None else vector),
                "entries": entries}

    def state_dump(self) -> dict:
        """The FULL state-transfer payload — journal/compaction form (the
        journal has no frame cap). The RPC surface serves the PAGED form
        (:meth:`state_page`) instead, so a dump larger than MAX_FRAME can
        still cross the wire."""
        with self._lock:
            return self._state_dump_locked()

    def state_page(self, cursor: dict | None = None) -> dict:
        """One page of the LWW state transfer (Cluster.entityState).

        First call (``cursor=None``) snapshots the entity KEY list and
        the receipt vector, then every page resolves entries lazily
        against CURRENT state (mid-transfer mutations are LWW-safe: the
        entry ships the newer state, and its op's seq sits above the
        snapshot vector, so the puller's next ops_since heals any
        ordering edge). The final page carries the snapshot ``vector``;
        earlier pages carry a continuation ``cursor``. A page never
        exceeds ~``state_page_bytes`` of entry payload or
        ``state_page_entries`` entries, bounding the frame well under
        MAX_FRAME (ADVICE r5 medium: one oversized dump permanently
        prevented a late joiner from converging)."""
        with self._lock:
            if cursor is None:
                tid = f"{self.rank}-{time.time_ns()}"
                keys = sorted(self._last)
                self._transfers[tid] = (keys, dict(self.vector))
                # cap scales with the cluster: every OTHER rank may be a
                # late joiner paging concurrently, and evicting an active
                # transfer makes its puller restart (mutual-eviction
                # thrash); oldest-first eviction only bounds abandonment
                cap = max(_MAX_TRANSFERS, self.cluster.n_ranks)
                while len(self._transfers) > cap:
                    # oldest first (insertion-ordered dict)
                    self._transfers.pop(next(iter(self._transfers)))
                pos = 0
            else:
                tid = cursor.get("tid")
                entry = self._transfers.get(tid)
                if entry is None:
                    # snapshot evicted (server restart / LRU): the caller
                    # restarts the transfer — LWW application makes the
                    # repeated entries idempotent
                    return {"expired": True}
                keys = entry[0]
                pos = int(cursor.get("pos", 0))
            keys_snap, vector = self._transfers[tid]
            entries, size = [], 0
            while (pos < len(keys_snap) and len(entries) <
                   self.state_page_entries and size < self.state_page_bytes):
                kind, token = keys_snap[pos]
                pos += 1
                lww = self._last.get((kind, token))
                if lww is None:
                    continue
                e = {"kind": kind, "token": token, "ts": lww[0],
                     "origin": lww[1],
                     "state": self._current_state(kind, token)}
                entries.append(e)
                size += len(json.dumps(e, default=str))
            self.counters["state_pages_served"] += 1
            if pos >= len(keys_snap):
                del self._transfers[tid]
                return {"entries": entries, "vector": vector}
            return {"entries": entries, "cursor": {"tid": tid, "pos": pos}}

    def _apply_dump_locked(self, dump: dict, journal: bool) -> int:
        """Converge onto a peer's (or the journal's) state dump: apply
        each entry last-writer-wins, then adopt the dump's vector. Safe
        against anything we already hold — LWW keys decide, exactly as
        for pushed ops."""
        applied = 0
        for e in dump["entries"]:
            key = (float(e["ts"]), int(e["origin"]))
            kt = (e["kind"], e["token"])
            existing = self._last.get(kt)
            if existing is not None and tuple(existing) >= key:
                continue
            self._last[kt] = key
            # dump entries carry no per-op seq; bound the delete by the
            # dump vector's coverage of its origin (conservative: GC
            # waits at least until every rank covers the whole dump)
            vec = dump.get("vector", {})
            bound = int(vec.get(str(e["origin"]), vec.get(e["origin"], 0)))
            self._note_tombstone(
                e["kind"], e["token"],
                "delete" if e["state"] is None else "upsert",
                key[0], key[1], bound)
            try:
                self._apply_state(
                    e["kind"], e["token"],
                    "delete" if e["state"] is None else "upsert",
                    e["state"])
                applied += 1
            except Exception:
                self.counters["apply_errors"] += 1
                logger.exception("state-transfer apply failed: %s %s",
                                 e["kind"], e["token"])
        for o, s in dump["vector"].items():
            o, s = int(o), int(s)
            if s > self.vector.get(o, 0):
                self.vector[o] = s
                # any indexed ops now sit BELOW the adopted watermark:
                # they are already reflected in the transferred state,
                # and keeping them would break per-origin contiguity
                # (ops_since slices, compaction floors, replay counting)
                # the moment the origin's next op appends above the jump
                stale = self._ops_by_origin.get(o)
                if stale:
                    self._total_ops -= len(stale)
                    self._ops_by_origin[o] = []
                if o == self.rank:
                    self._my_seq = max(self._my_seq, s)
        if journal:
            self._journal({"dump": dump})
        return applied

    def apply_state_dump(self, dump: dict) -> int:
        """Adopt a peer's full state (the reset path of sync_from_peers)."""
        with self._lock:
            n = self._apply_dump_locked(dump, journal=True)
            self.counters["state_transfers"] += 1
            return n

    def _compact_prepare_locked(self, keep_recent: int):
        """Phase 1 of compaction (lock held): truncate the op index to
        the newest ``keep_recent`` per origin and SNAPSHOT everything the
        journal rewrite needs. Disk and memory stay O(live entities +
        tail) for the cluster's whole lifetime. Returns the payload for
        :meth:`_finish_compaction`, or None when there is no journal."""
        for origin in list(self._ops_by_origin):
            ops = self._ops_by_origin[origin]
            if len(ops) > keep_recent:
                self._ops_by_origin[origin] = ops[len(ops) - keep_recent:]
        self._total_ops = sum(len(v)
                              for v in self._ops_by_origin.values())
        self.counters["compactions"] += 1
        if self._log is None:
            return None
        # journal vector rewound to below each kept tail so replay
        # re-counts the tail and rebuilds the op index
        floor_vec = dict(self.vector)
        for origin, ops in self._ops_by_origin.items():
            if ops:
                floor_vec[origin] = ops[0]["seq"] - 1
        dump = self._state_dump_locked(vector=floor_vec)
        tail = sorted((o for ops in self._ops_by_origin.values()
                       for o in ops),
                      key=lambda o: (o["origin"], o["seq"]))
        # from here until the swap, _journal mirrors every new op into
        # _compact_extra (while still writing the old journal, so
        # durability never lapses)
        self._compacting = True
        self._compact_extra = []
        return {"dump": dump, "tail": tail}

    def _finish_compaction(self, prep: dict) -> None:
        """Phase 2: write + fsync the new journal OUTSIDE the lock (the
        expensive half — a full state dump plus the kept tail must not
        stall every mutator behind the replicator lock), then swap
        ``self._log`` back under the lock. Crash-safe: the new journal is
        fully synced before any rename, and __init__ finishes an
        interrupted swap."""
        from sitewhere_tpu.utils.ingestlog import IngestLog

        d = self._log_dir
        new_dir = d.with_name(d.name + ".new")
        old_dir = d.with_name(d.name + ".old")
        try:
            shutil.rmtree(new_dir, ignore_errors=True)
            nlog = IngestLog(new_dir, segment_bytes=8 << 20)
            nlog.append(json.dumps({"dump": prep["dump"]}).encode())
            for op in prep["tail"]:
                nlog.append(json.dumps(op).encode())
            nlog.sync()
            with self._lock:
                # ops journaled while the snapshot was written: durable
                # in the old journal, appended to the new one before the
                # swap so the rename never drops them
                for op in self._compact_extra:
                    nlog.append(json.dumps(op).encode())
                nlog.sync()
                nlog.close()
                self._log.close()
                shutil.rmtree(old_dir, ignore_errors=True)
                try:
                    d.rename(old_dir)
                    new_dir.rename(d)
                finally:
                    # a failed half-swap must not leave the replicator on
                    # a closed journal: roll the live dir back if needed
                    # and reopen whatever now lives at ``d``
                    if not d.exists() and old_dir.exists():
                        old_dir.rename(d)
                    self._log = IngestLog(d, segment_bytes=8 << 20)
        finally:
            # ALWAYS re-arm: a failed compaction (ENOSPC, rename error)
            # must not wedge _compacting=True forever — that would grow
            # _compact_extra unboundedly and disable compaction for the
            # process lifetime
            with self._lock:
                self._compacting = False
                self._compact_extra = []
        shutil.rmtree(old_dir, ignore_errors=True)
        logger.info("rank %d: entity journal compacted to %d ops",
                    self.rank, self._total_ops)

    # ------------------------------------------------------ tombstone GC
    def _note_tombstone(self, kind: str, token: str, action: str,
                        ts: float, origin: int, seq: int) -> None:
        """Track (or clear) the delete op behind an LWW tombstone (lock
        held) — the evidence gc_tombstones() needs."""
        if action == "delete":
            self._tombstones[(kind, token)] = (ts, origin, seq)
        else:
            self._tombstones.pop((kind, token), None)

    def gc_tombstones(self, min_age_ms: float | None = None) -> int:
        """Drop tombstones past the cluster-wide sync horizon: every
        rank's receipt vector covers the delete op (observed during
        anti-entropy), so no peer can still hold — or ship — a
        pre-delete state the LWW entry would need to beat. An age floor
        keeps very fresh deletes out of the race with in-flight state
        transfers. Returns tombstones collected.

        Safety argument (pinned by test): after GC, a replayed pre-
        delete OP is blocked by the receipt vector (seq <= vector), and
        a pre-delete STATE entry cannot exist on any rank whose vector
        covered the delete (its own LWW register already resolved the
        delete as the winner)."""
        min_age = (self.tombstone_min_age_ms if min_age_ms is None
                   else min_age_ms)
        now = time.time() * 1000
        n = self.cluster.n_ranks
        removed = 0
        with self._lock:
            for key, (ts, origin, seq) in list(self._tombstones.items()):
                if now - ts < min_age:
                    continue
                if self.vector.get(origin, 0) < seq:
                    continue
                covered = True
                for r in range(n):
                    if r == self.rank:
                        continue
                    vec = self._peer_vectors.get(r)
                    if vec is None or vec.get(origin, 0) < seq:
                        covered = False
                        break
                if not covered:
                    continue
                del self._tombstones[key]
                self._last.pop(key, None)
                removed += 1
                self.counters["tombstones_gcd"] += 1
        if removed:
            logger.info("rank %d: GC'd %d entity tombstones", self.rank,
                        removed)
        return removed

    # ---------------------------------------------------- anti-entropy
    def sync_from_peers(self, best_effort: bool = True) -> int:
        """Pull everything we lack from every reachable peer (startup
        catch-up + the periodic heal for pushes we missed while down)."""
        from sitewhere_tpu.rpc.protocol import RpcError

        total = 0
        c = self.cluster
        for r in range(c.n_ranks):
            if r == self.rank:
                continue
            try:
                with self._lock:
                    vec = dict(self.vector)
                ops = c._peer(r).call("Cluster.entityOpsSince", vector=vec)
                if isinstance(ops, dict) and ops.get("reset"):
                    # we are behind the peer's compaction floor: pull its
                    # full LWW state instead of an op backfill
                    total += self._pull_state(r)
                else:
                    total += self.apply_batch(ops)
                # the peer's receipt vector is the tombstone-GC horizon
                # evidence: a delete op covered by EVERY rank's vector
                # can never be contradicted by a late pre-delete state
                pv = c._peer(r).call("Cluster.entityVector")
                with self._lock:
                    self._peer_vectors[r] = {int(k): int(v)
                                             for k, v in pv.items()}
            except (ConnectionError, TimeoutError, RpcError):
                # RpcError too: one peer answering garbage (version skew,
                # mid-restart handler) must not abort best-effort healing
                # from the remaining healthy peers
                if not best_effort:
                    raise
        self.counters["sync_pulls"] += 1
        return total

    def _pull_state(self, peer_rank: int) -> int:
        """Paged LWW state transfer from one peer: walk the continuation
        cursor until the final page (which carries the vector), assemble
        the full dump, then apply + journal it atomically through the
        existing apply_state_dump path. Each page is bounded under
        MAX_FRAME, so an entity plane of ANY size converges."""
        peer = self.cluster._peer(peer_rank)
        entries: list[dict] = []
        cursor = None
        restarts = 0
        while True:
            page = peer.call("Cluster.entityState", cursor=cursor)
            if page.get("expired"):
                # the peer evicted our transfer snapshot (restart / LRU
                # pressure): start over — entries re-apply idempotently
                restarts += 1
                if restarts > 3:
                    raise ConnectionError(
                        f"entity state transfer from rank {peer_rank} "
                        "kept expiring")
                entries, cursor = [], None
                continue
            entries.extend(page.get("entries", ()))
            if "vector" in page:
                return self.apply_state_dump(
                    {"entries": entries, "vector": page["vector"]})
            cursor = page["cursor"]

    def metrics(self) -> dict:
        with self._lock:
            return {"entity_ops_known": sum(
                        len(v) for v in self._ops_by_origin.values()),
                    "entity_tombstones": len(self._tombstones),
                    "entity_push_queue_depth": self._push_q.qsize(),
                    "entity_vector": {str(k): v
                                      for k, v in sorted(self.vector.items())},
                    **{f"entity_{k}": v for k, v in self.counters.items()}}

    def close(self) -> None:
        if self._push_thread is not None and self._push_thread.is_alive():
            self._push_q.put(None)
            self._push_thread.join(timeout=5)
        if self._log is not None:
            self._log.close()

    def register_rpc(self, srv) -> None:
        """The replication surface on the rank's cluster RPC server."""
        srv.register("Cluster.entityOp", lambda op: self.apply_op(op))
        srv.register("Cluster.entityOps",
                     lambda ops: {"applied": self.apply_batch(ops)})
        srv.register("Cluster.entityOpsSince",
                     lambda vector: self.ops_since(vector))
        # paged: a dump larger than one frame ships as cursor-chained
        # pages (ADVICE r5 medium — see state_page)
        srv.register("Cluster.entityState",
                     lambda cursor=None: self.state_page(cursor))
        srv.register("Cluster.entityVector",
                     lambda: {str(k): v for k, v in self.vector.items()})


class _SequenceGap(Exception):
    def __init__(self, origin: int, last: int):
        super().__init__(f"gap: origin {origin} after seq {last}")
        self.origin = origin
        self.last = last
