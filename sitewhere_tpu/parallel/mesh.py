"""Device meshes and shardings for the distributed engine.

The reference scales by partitioning Kafka topics on device token and running
one Streams task per partition (SURVEY.md §2.9 "partition parallelism";
producers key by device token at EventSourcesManager.java:183). The TPU-native
equivalent is a 1-D ``shard`` mesh over ICI: every shard owns a contiguous
slice of the token space and the device-row space, so the whole hot pipeline
is shard-local — the partition-locality guarantee Kafka gives the reference.
Cross-shard traffic (mis-routed ingest, global queries) rides XLA collectives
(parallel/exchange.py), not a broker.

Multi-host: the same mesh spans hosts via jax.distributed; ingest workers
route host-side by token hash exactly like Kafka partitioners, and the ICI/DCN
boundary is handled by XLA's collective lowering.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


def make_mesh(n_shards: int | None = None, devices: list | None = None) -> Mesh:
    """1-D pipeline mesh over ``n_shards`` devices (default: all)."""
    devs = devices if devices is not None else jax.devices()
    if n_shards is None:
        n_shards = len(devs)
    return Mesh(np.asarray(devs[:n_shards]), (SHARD_AXIS,))


def shard_leading(mesh: Mesh) -> NamedSharding:
    """Shard a stacked [n_shards, ...] pytree leaf along its leading axis."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def stack_sharding(mesh: Mesh, tree):
    """Apply leading-axis sharding to every leaf of a stacked state pytree."""
    sh = shard_leading(mesh)
    return jax.tree_util.tree_map(lambda _: sh, tree)
