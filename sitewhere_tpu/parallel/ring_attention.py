"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long telemetry windows (SURVEY.md §5.7 — the long-context design axis the
reference lacks) can exceed one chip's HBM/VMEM budget. Two standard
TPU-native decompositions, both pure XLA collectives over the ICI mesh:

  * **Ring attention** (`ring_attention`): shard the sequence axis over mesh
    axis ``sp``. Each device keeps its query shard pinned and streams the
    key/value shards around the ring with ``lax.ppermute`` (neighbor hops —
    exactly the ICI-friendly pattern), folding each arriving block into the
    flash-attention running softmax. Compute and communication overlap: the
    matmul for block t hides the permute for block t+1 (XLA schedules the
    ppermute async). Memory per device: O(S/n) — no full-sequence tensor
    anywhere.

  * **Ulysses all-to-all** (`ulysses_attention`): for moderate sequences with
    enough heads, ``lax.all_to_all`` re-shards [B, S/n, H, D] -> [B, S, H/n, D],
    runs dense local attention per head group, and re-shards back. Two
    all-to-alls total, best when H >= n and S fits per-device after the swap.

Both are written to run INSIDE ``shard_map`` (they take the mesh axis name),
with `*_sharded` wrappers that build the shard_map over a Mesh. Causal
masking uses global positions derived from ``lax.axis_index``, so results are
bit-for-bit the same attention as the single-device oracle.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sitewhere_tpu.ops.attention import mha_reference

_NEG_INF = -1e30


def _block_scores(q, k, q_off, k_off, scale, causal):
    """Scaled (+ causally masked) scores for one ring step.

    q: [B, Sq, H, D], k: [B, Sk, H, D] -> [B, H, Sq, Sk] float32.
    Offsets are the global positions of the first row/col of each shard.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        row = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((col > row)[None, None], _NEG_INF, s)
    return s


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jax.Array:
    """Ring attention over sequence shards. Call inside shard_map.

    q, k, v: [B, S/n, H, D] local shards (sequence axis sharded over
    ``axis_name``); returns the local [B, S/n, H, D] output shard.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / float(d) ** 0.5
    q_off = idx * sq

    # Initial accumulators are device-varying (they fold in shard-local
    # scores), so mark them varying along the mesh axis for shard_map's
    # manual-axes type system.
    from sitewhere_tpu.compat import pcast

    m = pcast(jnp.full((b, h, sq), _NEG_INF, jnp.float32), axis_name,
              to="varying")
    l = pcast(jnp.zeros((b, h, sq), jnp.float32), axis_name, to="varying")
    acc = pcast(jnp.zeros((b, sq, h, d), jnp.float32), axis_name,
                to="varying")
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(t, carry):
        k_cur, v_cur, m, l, acc = carry
        # After t forward hops, this device holds the block that originated
        # on device (idx - t) mod n.
        k_off = ((idx - t) % n) * sq
        s = _block_scores(q, k_cur, q_off, k_off, scale, causal)  # [B,H,Sq,Sk]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        acc = acc * jnp.swapaxes(alpha, 1, 2)[..., None] + pv
        # Rotate KV one hop around the ring. The final iteration's hop is
        # unused (one redundant neighbor transfer), the price of a uniform
        # loop body that compiles to a single scan region.
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m_new, l, acc

    _, _, m, l, acc = lax.fori_loop(0, n, body, (k, v, m, l, acc))
    l = jnp.swapaxes(l, 1, 2)[..., None]                  # [B, Sq, H, 1]
    safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism. Call inside
    shard_map. Requires H % n == 0.

    [B, S/n, H, D] --a2a--> [B, S, H/n, D] --local attention--> --a2a--> back.
    """
    # split heads across devices, gather the sequence
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = mha_reference(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def _sharded(
    fn: Callable,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str,
    **kw,
) -> jax.Array:
    spec = P(None, axis, None, None)
    from sitewhere_tpu.compat import shard_map

    mapped = shard_map(
        functools.partial(fn, axis_name=axis, **kw),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sh = NamedSharding(mesh, spec)
    return mapped(jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh))


def ring_attention_sharded(q, k, v, mesh: Mesh, axis: str = "sp", *,
                           causal: bool = False, sm_scale: float | None = None):
    """Full-array convenience wrapper: shards [B, S, H, D] over ``axis`` and
    runs ring attention. S must divide evenly by the axis size."""
    return _sharded(ring_attention, q, k, v, mesh, axis,
                    causal=causal, sm_scale=sm_scale)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, axis: str = "sp", *,
                              causal: bool = False, sm_scale: float | None = None):
    """Full-array convenience wrapper for Ulysses all-to-all attention."""
    return _sharded(ulysses_attention, q, k, v, mesh, axis,
                    causal=causal, sm_scale=sm_scale)
