"""Elastic re-sharding: transform an N-shard snapshot into an M-shard one.

The reference recovers from lost/added workers via Kafka consumer-group
rebalancing — partitions reassign to the surviving consumers and the durable
topics replay (SURVEY.md §5.4). Here shard state lives in HBM arrays, so
elasticity is a host-side permutation: every token's owner is a pure
function of its interner id (``gid % n_shards``), so changing the shard
count moves each device, its assignments, its aggregated state rows, and
its persisted events to the new owner — all as vectorized numpy scatters
over the snapshot, no mesh required. Restore the result with
``restore_distributed`` on the new mesh size.

Notes:
  * Per-shard ring stores are re-packed in (old-shard, append-order); when
    a new shard's merged events exceed its ring capacity the OLDEST drop,
    exactly like live ring overwrite.
  * Outbound feed offsets are per-ring positions and do not survive a
    reshard; consumers restart from the rebuilt rings (the Kafka analog:
    a rebalance resets to the committed group offset of a NEW partition
    map, which the reference also cannot carry over).
  * Pair a reshard with a fresh WAL directory: the old WAL's watermark
    refers to the old cursor line and is preserved in the host manifest,
    so recovery replays the same tail, but new watermarks should not be
    appended to the old log.
"""

from __future__ import annotations

import json
import pathlib

import numpy as np

from sitewhere_tpu.core.types import NULL_ID


def _load(src: pathlib.Path) -> tuple[dict, dict]:
    host = json.loads((src / "host_distributed.json").read_text())
    data = dict(np.load(src / "sharded_state.npz"))
    return host, data


def reshard_snapshot(src_dir, dst_dir, n_shards_new: int) -> dict:
    """Rewrite the snapshot at ``src_dir`` for ``n_shards_new`` shards into
    ``dst_dir``; returns the new host manifest."""
    src, dst = pathlib.Path(src_dir), pathlib.Path(dst_dir)
    dst.mkdir(parents=True, exist_ok=True)
    host, data = _load(src)
    s_old = host["n_shards"]
    m = n_shards_new
    cfg = host["config"]
    n_cap = cfg["device_capacity_per_shard"]
    g_cap = cfg["assignment_capacity_per_shard"]
    c_cap = cfg["store_capacity_per_shard"]
    t_cap = cfg["token_capacity_per_shard"]

    tokens: list[str] = host["tokens"]
    token_gid = {t: i for i, t in enumerate(tokens)}
    if len(tokens) > m * t_cap:
        raise ValueError(
            f"{len(tokens)} tokens exceed new global capacity {m * t_cap}")

    # ---- device map: old (shard, local) -> new (shard, local) -------------
    # New locals allocate in old-global-id order per new shard, so the
    # mapping is deterministic and dense.
    next_dev = np.zeros(m, np.int64)
    dev_old_s, dev_old_d, dev_new_s, dev_new_d = [], [], [], []
    dmap = np.full((s_old, n_cap), NULL_ID, np.int64)      # -> new local did
    dshard = np.full((s_old, n_cap), NULL_ID, np.int64)    # -> new shard
    gdid_map: dict[int, int] = {}                          # old gdid -> new
    for gid_str, old_gdid in sorted(host["token_device"].items(),
                                    key=lambda kv: kv[1]):
        gid = int(gid_str)
        so, do = old_gdid % s_old, old_gdid // s_old
        sn = gid % m
        dn = int(next_dev[sn])
        next_dev[sn] += 1
        if dn >= n_cap:
            raise ValueError(
                f"shard {sn} would exceed device capacity {n_cap}")
        dev_old_s.append(so)
        dev_old_d.append(do)
        dev_new_s.append(sn)
        dev_new_d.append(dn)
        dmap[so, do] = dn
        dshard[so, do] = sn
        gdid_map[old_gdid] = dn * m + sn
    dev_old_s = np.asarray(dev_old_s, np.int64)
    dev_old_d = np.asarray(dev_old_d, np.int64)
    dev_new_s = np.asarray(dev_new_s, np.int64)
    dev_new_d = np.asarray(dev_new_d, np.int64)

    # ---- assignment map (assignment shard == its device's new shard) ------
    next_asg = np.zeros(m, np.int64)
    asg_old_s, asg_old_a, asg_new_s, asg_new_a = [], [], [], []
    amap = np.full((s_old, g_cap), NULL_ID, np.int64)
    gaid_map: dict[int, int] = {}
    for gaid_str in sorted(host["assignments"], key=int):
        gaid = int(gaid_str)
        info = host["assignments"][gaid_str]
        so, ao = gaid % s_old, gaid // s_old
        gid = token_gid.get(info["device_token"])
        if gid is None:
            continue
        sn = gid % m
        an = int(next_asg[sn])
        next_asg[sn] += 1
        if an >= g_cap:
            raise ValueError(
                f"shard {sn} would exceed assignment capacity {g_cap}")
        asg_old_s.append(so)
        asg_old_a.append(ao)
        asg_new_s.append(sn)
        asg_new_a.append(an)
        amap[so, ao] = an
        gaid_map[gaid] = an * m + sn
    asg_old_s = np.asarray(asg_old_s, np.int64)
    asg_old_a = np.asarray(asg_old_a, np.int64)
    asg_new_s = np.asarray(asg_new_s, np.int64)
    asg_new_a = np.asarray(asg_new_a, np.int64)

    def remap_values(vals: np.ndarray, old_shard: np.ndarray,
                     table: np.ndarray) -> np.ndarray:
        """Translate shard-local id VALUES (e.g. assignment ids stored in
        device rows) through ``table[old_shard, value]``; NULL passes."""
        ok = vals != NULL_ID
        out = np.full_like(vals, NULL_ID)
        sh = np.broadcast_to(old_shard.reshape((-1,) + (1,) * (vals.ndim - 1)),
                             vals.shape)
        out[ok] = table[sh[ok], vals[ok]]
        return out

    out: dict[str, np.ndarray] = {}

    # ---- registry + device_state leaves -----------------------------------
    old_shard_col = np.arange(s_old)
    for key, arr in data.items():
        if key in (".next_device", ".next_assignment") or \
           key.startswith(".metrics.") or key.startswith(".store."):
            continue
        if key.endswith("token_to_device"):
            new = np.full((m, t_cap), NULL_ID, arr.dtype)
            gids = np.asarray([int(g) for g in host["token_device"]], np.int64)
            if len(gids):
                new_d = np.asarray(
                    [gdid_map[host["token_device"][str(g)]] // m
                     for g in gids], np.int64)
                new[gids % m, gids // m] = new_d.astype(arr.dtype)
            out[key] = new
            continue
        if key.startswith(".registry.device") or key.startswith(".device_state."):
            fill = (np.zeros((), arr.dtype) if arr.dtype == np.bool_
                    else _fill_like(key, arr))
            new = np.full((m,) + arr.shape[1:], fill, arr.dtype)
            vals = arr[dev_old_s, dev_old_d]
            if key.endswith("device_assignments"):
                vals = remap_values(vals.astype(np.int64), dev_old_s,
                                    amap).astype(arr.dtype)
            elif key.endswith("device_parent"):
                # parent column is shard-local; it survives only when the
                # parent moved to the same new shard as the child
                vals = vals.astype(np.int64)
                ok = vals != NULL_ID
                same = np.zeros_like(ok)
                same[ok] = dshard[dev_old_s[ok], vals[ok]] == dev_new_s[ok]
                moved = remap_values(vals, dev_old_s, dmap)
                vals = np.where(ok & same, moved, NULL_ID).astype(arr.dtype)
            new[dev_new_s, dev_new_d] = vals
            out[key] = new
            continue
        if key.startswith(".registry.assignment"):
            fill = _fill_like(key, arr)
            new = np.full((m,) + arr.shape[1:], fill, arr.dtype)
            vals = arr[asg_old_s, asg_old_a]
            if key.endswith("assignment_device"):
                vals = remap_values(vals.astype(np.int64), asg_old_s,
                                    dmap).astype(arr.dtype)
            new[asg_new_s, asg_new_a] = vals
            out[key] = new
            continue
        raise ValueError(f"unhandled snapshot leaf {key!r}")

    # ---- event ring re-pack ----------------------------------------------
    store_keys = [k for k in data if k.startswith(".store.")
                  and k not in (".store.cursor", ".store.epoch")]
    n_arenas = data[".store.cursor"].shape[-1]
    acap = c_cap // n_arenas
    rows_per_new: list[list[dict]] = [[] for _ in range(m)]
    for so in range(s_old):
        # linearize each arena's sub-ring in its own append order
        for a in range(n_arenas):
            cursor = int(data[".store.cursor"][so][a])
            epoch = int(data[".store.epoch"][so][a])
            local = (np.concatenate([np.arange(cursor, acap),
                                     np.arange(cursor)])
                     if epoch > 0 else np.arange(cursor))
            order = a * acap + local
            valid = data[".store.valid"][so][order]
            order = order[valid]
            if not len(order):
                continue
            devs = data[".store.device"][so][order].astype(np.int64)
            new_s = np.where(devs != NULL_ID, dshard[so, devs], NULL_ID)
            cols = {k: data[k][so][order] for k in store_keys}
            cols[".store.device"] = remap_values(devs, np.full_like(devs, so),
                                                 dmap)
            asgs = data[".store.assignment"][so][order].astype(np.int64)
            cols[".store.assignment"] = remap_values(
                asgs, np.full_like(asgs, so), amap)
            for sn in range(m):
                sel = new_s == sn
                if np.any(sel):
                    rows_per_new[sn].append(
                        {k: v[sel] for k, v in cols.items()})
    new_cursor = np.zeros((m, n_arenas), np.int32)
    new_epoch = np.zeros((m, n_arenas), np.int32)
    for k in store_keys:
        out[k] = np.zeros((m,) + data[k].shape[1:], data[k].dtype)
        if k in (".store.device", ".store.assignment", ".store.tenant",
                 ".store.area", ".store.customer", ".store.asset",
                 ".store.aux"):
            out[k][:] = NULL_ID
    for sn in range(m):
        if not rows_per_new[sn]:
            continue
        merged = {k: np.concatenate([c[k] for c in rows_per_new[sn]])
                  for k in store_keys}
        # re-derive each row's arena from its tenant (content-addressed)
        tenants = merged[".store.tenant"].astype(np.int64)
        arenas = np.where(tenants >= 0, tenants % n_arenas, 0)
        for a in range(n_arenas):
            sel = arenas == a
            n = int(sel.sum())
            if not n:
                continue
            sub = {k: v[sel] for k, v in merged.items()}
            if n > acap:                   # arena overflow: oldest drop
                sub = {k: v[n - acap:] for k, v in sub.items()}
                n = acap
            for k in store_keys:
                out[k][sn, a * acap:a * acap + n] = sub[k]
            new_cursor[sn, a] = n % acap
            new_epoch[sn, a] = n // acap
    out[".store.cursor"] = new_cursor
    out[".store.epoch"] = new_epoch

    # ---- counters + metrics ----------------------------------------------
    out[".next_device"] = next_dev.astype(data[".next_device"].dtype)
    out[".next_assignment"] = next_asg.astype(data[".next_assignment"].dtype)
    for key in data:
        if key.startswith(".metrics."):
            # per-shard attribution doesn't survive a reshard; keep the
            # global totals exact by folding them onto shard 0
            new = np.zeros(m, data[key].dtype)
            new[0] = data[key].sum()
            out[key] = new

    np.savez_compressed(dst / "sharded_state.npz", **out)

    # ---- manifests --------------------------------------------------------
    sharded_manifest = json.loads((src / "sharded_manifest.json").read_text())
    sharded_manifest["n_shards"] = m
    (dst / "sharded_manifest.json").write_text(json.dumps(sharded_manifest))

    host["n_shards"] = m
    # wal_dir is dropped: the resharded engine must NOT append watermarks
    # into the original live WAL (its cursor line no longer matches);
    # attach a fresh WAL explicitly after restore
    host["config"] = dict(cfg, n_shards=m, wal_dir=None)
    host["next_device"] = [int(x) for x in next_dev]
    host["next_assignment"] = [int(x) for x in next_asg]
    host["token_device"] = {
        g: gdid_map[old] for g, old in host["token_device"].items()}
    host["devices"] = {
        str(gdid_map[int(k)]): v for k, v in host["devices"].items()
        if int(k) in gdid_map}
    new_assignments = {}
    for k, v in host["assignments"].items():
        if int(k) in gaid_map:
            v = dict(v, id=gaid_map[int(k)])
            new_assignments[str(gaid_map[int(k)])] = v
    host["assignments"] = new_assignments
    host["device_slots"] = {
        str(gdid_map[int(k)]): [gaid_map.get(a, NULL_ID) if a != NULL_ID
                                else NULL_ID for a in v]
        for k, v in host["device_slots"].items() if int(k) in gdid_map}
    (dst / "host_distributed.json").write_text(json.dumps(host))
    return host


def _fill_like(key: str, arr: np.ndarray):
    """Empty-row fill matching the zeros() initializers of the state
    dataclasses (NULL for id lanes, INT32_MIN for timestamp lanes)."""
    if arr.dtype == np.bool_:
        return False
    if arr.dtype == np.float32:
        return 0.0
    if key.endswith("_ms") or "last_interaction" in key:
        return np.iinfo(np.int32).min
    if "presence" in key or "event_counts" in key or "status" in key \
            or key.endswith("etype"):
        return 0
    return NULL_ID
