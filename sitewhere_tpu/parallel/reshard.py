"""Elastic re-sharding: transform an N-shard snapshot into an M-shard one.

The reference recovers from lost/added workers via Kafka consumer-group
rebalancing — partitions reassign to the surviving consumers and the durable
topics replay (SURVEY.md §5.4). Here shard state lives in HBM arrays, so
elasticity is a host-side permutation: every token's owner is a pure
function of its interner id (``gid % n_shards``), so changing the shard
count moves each device, its assignments, its aggregated state rows, and
its persisted events to the new owner — all as vectorized numpy scatters
over the snapshot, no mesh required. Restore the result with
``restore_distributed`` on the new mesh size.

Notes:
  * Per-shard ring stores are re-packed in (old-shard, append-order); when
    a new shard's merged events exceed its ring capacity the OLDEST drop,
    exactly like live ring overwrite.
  * Outbound feed offsets are per-ring positions and do not survive a
    reshard; consumers restart from the rebuilt rings (the Kafka analog:
    a rebalance resets to the committed group offset of a NEW partition
    map, which the reference also cannot carry over).
  * Pair a reshard with a fresh WAL directory: the old WAL's watermark
    refers to the old cursor line and is preserved in the host manifest,
    so recovery replays the same tail, but new watermarks should not be
    appended to the old log.
  * Since ISSUE 15 the OFFLINE snapshot paths (this module and
    cluster_reshard.py) are the DISASTER-RECOVERY route: live topology
    changes — rank join/drain, tenant rebalancing — run online through
    parallel/placement.py with zero downtime. Use the offline route when
    the cluster is down anyway, or when pruned WALs rule out the online
    handoff's replay-based catch-up.
"""

from __future__ import annotations

import json
import pathlib

import numpy as np

from sitewhere_tpu.core.types import NULL_ID


def _load(src: pathlib.Path) -> tuple[dict, dict]:
    host = json.loads((src / "host_distributed.json").read_text())
    data = dict(np.load(src / "sharded_state.npz"))
    return host, data


def reshard_snapshot(src_dir, dst_dir, n_shards_new: int,
                     archive_dir=None, archive_dst=None) -> dict:
    """Rewrite the snapshot at ``src_dir`` for ``n_shards_new`` shards into
    ``dst_dir``; returns the new host manifest.

    With ``archive_dir``/``archive_dst`` set, the long-term archive
    migrates WITH the topology (VERDICT r3 missing #2 — the reference's
    event history lives in topology-agnostic stores and survives any
    scaling event, InfluxDbDeviceEventManagement.java:63-161): every
    archived row is re-partitioned under the new shard count (device →
    new shard via the same id maps as the live state, tenant → arena),
    written to ``archive_dst`` under the new topology stamp, and the new
    rings' epochs are bumped so migrated history occupies absolute
    positions [0, H) BELOW the live ring's positions — ring + archive
    stay non-overlapping, so queries never double-count. Ring rows that
    drop on arena overflow during the reshard are preserved into the
    archive instead of being lost."""
    src, dst = pathlib.Path(src_dir), pathlib.Path(dst_dir)
    dst.mkdir(parents=True, exist_ok=True)
    if (archive_dir is None) != (archive_dst is None):
        raise ValueError("archive_dir and archive_dst go together")
    host, data = _load(src)
    s_old = host["n_shards"]
    m = n_shards_new
    cfg = host["config"]
    n_cap = cfg["device_capacity_per_shard"]
    g_cap = cfg["assignment_capacity_per_shard"]
    c_cap = cfg["store_capacity_per_shard"]
    t_cap = cfg["token_capacity_per_shard"]

    tokens: list[str] = host["tokens"]
    token_gid = {t: i for i, t in enumerate(tokens)}
    if len(tokens) > m * t_cap:
        raise ValueError(
            f"{len(tokens)} tokens exceed new global capacity {m * t_cap}")

    # ---- device map: old (shard, local) -> new (shard, local) -------------
    # New locals allocate in old-global-id order per new shard, so the
    # mapping is deterministic and dense.
    next_dev = np.zeros(m, np.int64)
    dev_old_s, dev_old_d, dev_new_s, dev_new_d = [], [], [], []
    dmap = np.full((s_old, n_cap), NULL_ID, np.int64)      # -> new local did
    dshard = np.full((s_old, n_cap), NULL_ID, np.int64)    # -> new shard
    gdid_map: dict[int, int] = {}                          # old gdid -> new
    for gid_str, old_gdid in sorted(host["token_device"].items(),
                                    key=lambda kv: kv[1]):
        gid = int(gid_str)
        so, do = old_gdid % s_old, old_gdid // s_old
        sn = gid % m
        dn = int(next_dev[sn])
        next_dev[sn] += 1
        if dn >= n_cap:
            raise ValueError(
                f"shard {sn} would exceed device capacity {n_cap}")
        dev_old_s.append(so)
        dev_old_d.append(do)
        dev_new_s.append(sn)
        dev_new_d.append(dn)
        dmap[so, do] = dn
        dshard[so, do] = sn
        gdid_map[old_gdid] = dn * m + sn
    dev_old_s = np.asarray(dev_old_s, np.int64)
    dev_old_d = np.asarray(dev_old_d, np.int64)
    dev_new_s = np.asarray(dev_new_s, np.int64)
    dev_new_d = np.asarray(dev_new_d, np.int64)

    # ---- assignment map (assignment shard == its device's new shard) ------
    next_asg = np.zeros(m, np.int64)
    asg_old_s, asg_old_a, asg_new_s, asg_new_a = [], [], [], []
    amap = np.full((s_old, g_cap), NULL_ID, np.int64)
    gaid_map: dict[int, int] = {}
    for gaid_str in sorted(host["assignments"], key=int):
        gaid = int(gaid_str)
        info = host["assignments"][gaid_str]
        so, ao = gaid % s_old, gaid // s_old
        gid = token_gid.get(info["device_token"])
        if gid is None:
            continue
        sn = gid % m
        an = int(next_asg[sn])
        next_asg[sn] += 1
        if an >= g_cap:
            raise ValueError(
                f"shard {sn} would exceed assignment capacity {g_cap}")
        asg_old_s.append(so)
        asg_old_a.append(ao)
        asg_new_s.append(sn)
        asg_new_a.append(an)
        amap[so, ao] = an
        gaid_map[gaid] = an * m + sn
    asg_old_s = np.asarray(asg_old_s, np.int64)
    asg_old_a = np.asarray(asg_old_a, np.int64)
    asg_new_s = np.asarray(asg_new_s, np.int64)
    asg_new_a = np.asarray(asg_new_a, np.int64)

    def remap_values(vals: np.ndarray, old_shard: np.ndarray,
                     table: np.ndarray) -> np.ndarray:
        """Translate shard-local id VALUES (e.g. assignment ids stored in
        device rows) through ``table[old_shard, value]``; NULL passes."""
        ok = vals != NULL_ID
        out = np.full_like(vals, NULL_ID)
        sh = np.broadcast_to(old_shard.reshape((-1,) + (1,) * (vals.ndim - 1)),
                             vals.shape)
        out[ok] = table[sh[ok], vals[ok]]
        return out

    out: dict[str, np.ndarray] = {}

    # ---- registry + device_state leaves -----------------------------------
    old_shard_col = np.arange(s_old)
    for key, arr in data.items():
        if key in (".next_device", ".next_assignment") or \
           key.startswith(".metrics.") or key.startswith(".store."):
            continue
        if key.endswith("token_to_device"):
            new = np.full((m, t_cap), NULL_ID, arr.dtype)
            gids = np.asarray([int(g) for g in host["token_device"]], np.int64)
            if len(gids):
                new_d = np.asarray(
                    [gdid_map[host["token_device"][str(g)]] // m
                     for g in gids], np.int64)
                new[gids % m, gids // m] = new_d.astype(arr.dtype)
            out[key] = new
            continue
        if key.startswith(".registry.device") or key.startswith(".device_state."):
            fill = (np.zeros((), arr.dtype) if arr.dtype == np.bool_
                    else _fill_like(key, arr))
            new = np.full((m,) + arr.shape[1:], fill, arr.dtype)
            vals = arr[dev_old_s, dev_old_d]
            if key.endswith("device_assignments"):
                vals = remap_values(vals.astype(np.int64), dev_old_s,
                                    amap).astype(arr.dtype)
            elif key.endswith("device_parent"):
                # parent column is shard-local; it survives only when the
                # parent moved to the same new shard as the child
                vals = vals.astype(np.int64)
                ok = vals != NULL_ID
                same = np.zeros_like(ok)
                same[ok] = dshard[dev_old_s[ok], vals[ok]] == dev_new_s[ok]
                moved = remap_values(vals, dev_old_s, dmap)
                vals = np.where(ok & same, moved, NULL_ID).astype(arr.dtype)
            new[dev_new_s, dev_new_d] = vals
            out[key] = new
            continue
        if key.startswith(".registry.assignment"):
            fill = _fill_like(key, arr)
            new = np.full((m,) + arr.shape[1:], fill, arr.dtype)
            vals = arr[asg_old_s, asg_old_a]
            if key.endswith("assignment_device"):
                vals = remap_values(vals.astype(np.int64), asg_old_s,
                                    dmap).astype(arr.dtype)
            new[asg_new_s, asg_new_a] = vals
            out[key] = new
            continue
        raise ValueError(f"unhandled snapshot leaf {key!r}")

    # ---- event ring re-pack ----------------------------------------------
    store_keys = [k for k in data if k.startswith(".store.")
                  and k not in (".store.cursor", ".store.epoch")]
    n_arenas = data[".store.cursor"].shape[-1]
    acap = c_cap // n_arenas
    rows_per_new: list[list[dict]] = [[] for _ in range(m)]
    for so in range(s_old):
        # linearize each arena's sub-ring in its own append order
        for a in range(n_arenas):
            cursor = int(data[".store.cursor"][so][a])
            epoch = int(data[".store.epoch"][so][a])
            local = (np.concatenate([np.arange(cursor, acap),
                                     np.arange(cursor)])
                     if epoch > 0 else np.arange(cursor))
            order = a * acap + local
            valid = data[".store.valid"][so][order]
            order = order[valid]
            if not len(order):
                continue
            devs = data[".store.device"][so][order].astype(np.int64)
            new_s = np.where(devs != NULL_ID, dshard[so, devs], NULL_ID)
            cols = {k: data[k][so][order] for k in store_keys}
            cols[".store.device"] = remap_values(devs, np.full_like(devs, so),
                                                 dmap)
            asgs = data[".store.assignment"][so][order].astype(np.int64)
            cols[".store.assignment"] = remap_values(
                asgs, np.full_like(asgs, so), amap)
            for sn in range(m):
                sel = new_s == sn
                if np.any(sel):
                    rows_per_new[sn].append(
                        {k: v[sel] for k, v in cols.items()})
    new_cursor = np.zeros((m, n_arenas), np.int32)
    new_epoch = np.zeros((m, n_arenas), np.int32)
    for k in store_keys:
        out[k] = np.zeros((m,) + data[k].shape[1:], data[k].dtype)
        if k in (".store.device", ".store.assignment", ".store.tenant",
                 ".store.area", ".store.customer", ".store.asset",
                 ".store.aux"):
            out[k][:] = NULL_ID
    # ring rows dropped on arena overflow and ring rows KEPT, per (new
    # shard, arena) — with an archive the dropped rows migrate to disk
    # instead of vanishing, and the kept rows are eagerly spilled so the
    # new archive starts at the live invariant (spilled ≈ head), giving
    # the spooler a full ring of slack before anything can be lost
    dropped: dict[tuple[int, int], dict] = {}
    kept_rows: dict[tuple[int, int], dict] = {}
    for sn in range(m):
        if not rows_per_new[sn]:
            continue
        merged = {k: np.concatenate([c[k] for c in rows_per_new[sn]])
                  for k in store_keys}
        # re-derive each row's arena from its tenant (content-addressed)
        tenants = merged[".store.tenant"].astype(np.int64)
        arenas = np.where(tenants >= 0, tenants % n_arenas, 0)
        for a in range(n_arenas):
            sel = arenas == a
            n = int(sel.sum())
            if not n:
                continue
            sub = {k: v[sel] for k, v in merged.items()}
            if n > acap:                   # arena overflow: oldest drop
                dropped[(sn, a)] = {k: v[:n - acap]
                                    for k, v in sub.items()}
                sub = {k: v[n - acap:] for k, v in sub.items()}
                n = acap
            kept_rows[(sn, a)] = sub
            for k in store_keys:
                out[k][sn, a * acap:a * acap + n] = sub[k]
            new_cursor[sn, a] = n % acap
            new_epoch[sn, a] = n // acap

    archive_stats = None
    if archive_dir is not None:
        n_kept = {(sn, a): int(new_epoch[sn, a]) * acap
                  + int(new_cursor[sn, a])
                  for sn in range(m) for a in range(n_arenas)}
        archive_stats = _migrate_archive(
            pathlib.Path(archive_dir), pathlib.Path(archive_dst), host, data,
            s_old=s_old, m=m, n_arenas=n_arenas, acap=acap,
            dmap=dmap, amap=amap, dshard=dshard, dropped=dropped,
            n_kept=n_kept, kept_rows=kept_rows)
        # bump each new partition's epoch so live ring positions continue
        # ABOVE the migrated history ([0, H) padded so that even a
        # part-full ring's query cap head - acap clears H)
        for (sn, a), bump in archive_stats["epoch_bump"].items():
            new_epoch[sn, a] += bump
    out[".store.cursor"] = new_cursor
    out[".store.epoch"] = new_epoch

    # ---- counters + metrics ----------------------------------------------
    out[".next_device"] = next_dev.astype(data[".next_device"].dtype)
    out[".next_assignment"] = next_asg.astype(data[".next_assignment"].dtype)
    for key in data:
        if key.startswith(".metrics."):
            # per-shard attribution doesn't survive a reshard; keep the
            # global totals exact by folding them onto shard 0 (summing
            # over the shard axis only — the packed per-tenant counter
            # grid keeps its [T, C] shape)
            arr = data[key]
            new = np.zeros((m,) + arr.shape[1:], arr.dtype)
            new[0] = arr.sum(axis=0)
            out[key] = new

    np.savez_compressed(dst / "sharded_state.npz", **out)

    # ---- manifests --------------------------------------------------------
    sharded_manifest = json.loads((src / "sharded_manifest.json").read_text())
    sharded_manifest["n_shards"] = m
    (dst / "sharded_manifest.json").write_text(json.dumps(sharded_manifest))

    host["n_shards"] = m
    # wal_dir is dropped: the resharded engine must NOT append watermarks
    # into the original live WAL (its cursor line no longer matches);
    # attach a fresh WAL explicitly after restore
    # archive_dir: the migrated destination when migrating, else the
    # ORIGINAL dir carries through (restore re-opens it and retires the
    # old-topology files — history parked, fresh spill continues)
    host["config"] = dict(cfg, n_shards=m, wal_dir=None,
                          archive_dir=(str(archive_dst)
                                       if archive_dst is not None
                                       else cfg.get("archive_dir")))
    if archive_stats is not None:
        host["archive_migration"] = {
            "migrated_rows": archive_stats["migrated_rows"],
            "preserved_overflow_rows":
                archive_stats["preserved_overflow_rows"],
            "dropped_unmapped_rows": archive_stats["dropped_unmapped_rows"],
        }
    host["next_device"] = [int(x) for x in next_dev]
    host["next_assignment"] = [int(x) for x in next_asg]
    host["token_device"] = {
        g: gdid_map[old] for g, old in host["token_device"].items()}
    host["devices"] = {
        str(gdid_map[int(k)]): v for k, v in host["devices"].items()
        if int(k) in gdid_map}
    new_assignments = {}
    for k, v in host["assignments"].items():
        if int(k) in gaid_map:
            v = dict(v, id=gaid_map[int(k)])
            new_assignments[str(gaid_map[int(k)])] = v
    host["assignments"] = new_assignments
    host["device_slots"] = {
        str(gdid_map[int(k)]): [gaid_map.get(a, NULL_ID) if a != NULL_ID
                                else NULL_ID for a in v]
        for k, v in host["device_slots"].items() if int(k) in gdid_map}
    (dst / "host_distributed.json").write_text(json.dumps(host))
    return host


def _migrate_archive(archive_src: pathlib.Path, archive_dst: pathlib.Path,
                     host: dict, data: dict, *, s_old: int, m: int,
                     n_arenas: int, acap: int, dmap: np.ndarray,
                     amap: np.ndarray, dshard: np.ndarray,
                     dropped: dict, n_kept: dict, kept_rows: dict) -> dict:
    """Re-partition archived history into the new topology (see
    reshard_snapshot). Sources, in position order per new partition:
    (a) archived rows strictly EVICTED from the old rings (pos <
    old head - acap — the same boundary the live ring+archive query merge
    uses, so ring-window duplicates are skipped); (b) ring rows dropped on
    arena overflow during the reshard; (c) the KEPT ring rows, eagerly
    spilled at their new ring positions so the new archive starts at the
    live invariant (spilled ≈ head). Device/assignment columns are
    rewritten to the new shard-local id spaces; each row's new partition
    is (device's new shard) * arenas + (tenant % arenas). Rows whose
    device no longer maps are dropped and counted. Streaming: one source
    segment in memory at a time, per-partition write buffers bounded at
    one output segment."""
    import types

    from sitewhere_tpu.utils.archive import (_COLUMNS, EventArchive,
                                             mesh_topology)

    old_stamp = mesh_topology(s_old, n_arenas)
    arch = EventArchive(archive_dst, segment_rows=max(1, acap // 4),
                        topology=mesh_topology(m, n_arenas))
    if arch.total_rows():
        raise ValueError(f"archive_dst {archive_dst} is not empty")

    class _PartWriter:
        """Buffers remapped rows for one new partition and flushes full
        output segments — migration memory stays O(segment), never
        O(history)."""

        def __init__(self, part: int):
            self.part = part
            self.next_pos = 0
            self.pending: list[dict] = []
            self.pending_rows = 0

        def add(self, cols: dict) -> None:
            n = int(cols["ts_ms"].shape[0])
            if not n:
                return
            self.pending.append(cols)
            self.pending_rows += n
            while self.pending_rows >= arch.segment_rows:
                self._flush_one(arch.segment_rows)

        def _flush_one(self, n: int) -> None:
            merged = {c: np.concatenate([ch[c] for ch in self.pending])
                      for c in _COLUMNS}
            arch.append_segment(self.part, self.next_pos,
                                types.SimpleNamespace(
                                    **{c: merged[c][:n] for c in _COLUMNS}))
            self.next_pos += n
            rest = {c: merged[c][n:] for c in _COLUMNS}
            self.pending = ([rest] if rest["ts_ms"].shape[0] else [])
            self.pending_rows = int(rest["ts_ms"].shape[0])

        def finish(self) -> int:
            if self.pending_rows:
                self._flush_one(self.pending_rows)
            return self.next_pos

    writers: dict[int, _PartWriter] = {}

    def writer(part: int) -> _PartWriter:
        w = writers.get(part)
        if w is None:
            w = writers[part] = _PartWriter(part)
        return w

    # (a) stream the source segments — the glob sort is (part, start)
    # order, so per-target-partition rows arrive in old write order
    migrated = unmapped = 0
    old_cursor = np.asarray(data[".store.cursor"], np.int64)
    old_epoch = np.asarray(data[".store.epoch"], np.int64)
    for f in sorted(archive_src.glob("seg-*.npz")):
        with np.load(f) as z:
            stamp = (str(z["topology"]) if "topology" in z.files
                     else "") or None
            if stamp is not None and stamp != old_stamp:
                raise ValueError(
                    f"archive segment {f.name} carries topology {stamp!r}, "
                    f"expected {old_stamp!r} — wrong archive directory?")
            part, start = int(z["part"]), int(z["start"])
            so, a_old = part // n_arenas, part % n_arenas
            head = old_epoch[so, a_old] * acap + old_cursor[so, a_old]
            boundary = max(0, int(head) - acap)
            cols = {c: np.asarray(z[c]) for c in _COLUMNS}
        n = cols["ts_ms"].shape[0]
        pos = start + np.arange(n)
        keep = cols["valid"].astype(bool) & (pos < boundary)
        devs = cols["device"].astype(np.int64)
        in_range = (devs >= 0) & (devs < dmap.shape[1])
        sn = np.full(n, NULL_ID, np.int64)
        sn[in_range] = dshard[so, devs[in_range]]
        mapped = keep & (sn != NULL_ID)
        unmapped += int(np.sum(keep & ~(sn != NULL_ID)))
        if not np.any(mapped):
            continue
        idx = np.nonzero(mapped)[0]
        sub = {c: cols[c][idx] for c in _COLUMNS}
        sub["device"] = dmap[so, devs[idx]].astype(sub["device"].dtype)
        asgs = sub["assignment"].astype(np.int64)
        ok = (asgs != NULL_ID) & (asgs >= 0) & (asgs < amap.shape[1])
        new_asg = np.full_like(asgs, NULL_ID)
        new_asg[ok] = amap[so, asgs[ok]]
        sub["assignment"] = new_asg.astype(sub["assignment"].dtype)
        tenants = sub["tenant"].astype(np.int64)
        arena_new = np.where(tenants >= 0, tenants % n_arenas, 0)
        p_rows = sn[idx] * n_arenas + arena_new
        for p_new in np.unique(p_rows):
            sel = p_rows == p_new
            migrated += int(sel.sum())
            writer(int(p_new)).add({c: sub[c][sel] for c in _COLUMNS})

    # (b) overflow-dropped ring rows (already remapped by the re-pack)
    preserved = 0
    for (sn_i, a_i), cols in dropped.items():
        plain = {k.split(".")[-1]: v for k, v in cols.items()}
        plain["valid"] = np.ones(plain["ts_ms"].shape[0], bool)
        preserved += int(plain["ts_ms"].shape[0])
        writer(sn_i * n_arenas + a_i).add(plain)

    # seal history, compute bumps, then (c) eager-spill the kept rows
    epoch_bump: dict[tuple[int, int], int] = {}
    all_parts = set(writers) | {sn * n_arenas + a for sn, a in kept_rows}
    for p_new in sorted(all_parts):
        h = writers[p_new].finish() if p_new in writers else 0
        key = (p_new // n_arenas, p_new % n_arenas)
        # the ring+archive query merge caps archive reads at
        # head - acap = bump*acap + kept - acap; the bump must lift that
        # cap past H or the tail of the migrated history would be
        # invisible whenever the new ring is not full
        kept = n_kept.get(key, 0)
        bump = -(-(h + acap - kept) // acap) if h else 0
        epoch_bump[key] = bump
        # padding [H, bump*acap) never held data: register it so replay
        # consumers skip it without counting phantom lag_lost
        arch.register_gap(p_new, h, bump * acap)
        ring = kept_rows.get(key)
        if ring is not None:
            plain = {k.split(".")[-1]: v for k, v in ring.items()}
            plain["valid"] = np.ones(kept, bool)
            pos = 0
            while pos < kept:
                n = min(arch.segment_rows, kept - pos)
                arch.append_segment(
                    p_new, bump * acap + pos, types.SimpleNamespace(
                        **{c: plain[c][pos:pos + n] for c in _COLUMNS}))
                pos += n
        else:
            # no ring rows landed here: the watermark still must cover
            # the padding gap so the spooler never reads it
            arch._spilled[p_new] = bump * acap
    arch._save_index()
    return {"migrated_rows": migrated, "preserved_overflow_rows": preserved,
            "dropped_unmapped_rows": unmapped, "epoch_bump": epoch_bump}


def _fill_like(key: str, arr: np.ndarray):
    """Empty-row fill matching the zeros() initializers of the state
    dataclasses (NULL for id lanes, INT32_MIN for timestamp lanes)."""
    if arr.dtype == np.bool_:
        return False
    if arr.dtype == np.float32:
        return 0.0
    if key.endswith("_ms") or "last_interaction" in key:
        return np.iinfo(np.int32).min
    if "presence" in key or "event_counts" in key or "status" in key \
            or key.endswith("etype"):
        return 0
    return NULL_ID
