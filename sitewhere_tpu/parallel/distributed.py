"""DistributedEngine: the full product runtime over the sharded ICI mesh.

``ShardedEngine`` (parallel/sharded.py) proves the collectives: it runs the
fused pipeline over a mesh, but consumes pre-interned integer batches. This
module is the *product* on top — everything the single-node ``Engine``
(engine.py) offers, running against stacked per-shard state:

  * string device tokens, interned once (native C++ interner when available)
    and hash-routed to an owning shard — the host-side analog of the
    reference's token-keyed Kafka partitioner
    (service-event-sources/.../manager/EventSourcesManager.java:183);
  * per-shard staging buffers feeding ONE stacked jit step (shard_map over
    the mesh), so every shard's fused pipeline runs in the same XLA program;
  * WAL durability + snapshot/recovery of the stacked state (the reference
    leans on Kafka offsets + k8s restarts, SURVEY.md §5.4/5.5);
  * admin CRUD, event queries, device-state reads, and presence sweeps
    served from the sharded state — the surface the REST gateway
    (web/rest.py) binds to, mirroring how the reference's REST controllers
    fan out to per-partition services over gRPC;
  * fair multi-tenant batch formation per shard.

Token routing: the global interner hands out dense ids; shard
``gid % n_shards`` owns the token and its local id is ``gid // n_shards``
(round-robin => balanced shards by construction). Global device ids are
``local_id * n_shards + shard`` — bijective, so host mirrors stay flat
dicts like the single-node engine's.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.core.events import EpochBase, EventBatch
from sitewhere_tpu.core.registry import MAX_ACTIVE_ASSIGNMENTS, TokenInterner
from sitewhere_tpu.core.types import (
    AUX_LANES,
    DEFAULT_VALUE_CHANNELS,
    NULL_ID,
    DeviceAssignmentStatus,
    EventType,
    PresenceState,
)
from sitewhere_tpu.engine import (
    WAL_BINARY,
    WAL_JSON,
    AssignmentInfo,
    ChannelMap,
    DeviceInfo,
    IngestHostMixin,
)
from sitewhere_tpu.parallel.sharded import ShardedEngine, _stacked_query
from sitewhere_tpu.pipeline import PipelineConfig, PipelineState, StepOutput


@dataclasses.dataclass
class DistributedConfig:
    """Per-shard capacities + the host-side engine knobs (EngineConfig
    analog). Global token capacity is n_shards * token_capacity_per_shard."""

    n_shards: int | None = None            # default: all local devices
    device_capacity_per_shard: int = 1 << 14
    token_capacity_per_shard: int = 1 << 15
    assignment_capacity_per_shard: int = 1 << 15
    store_capacity_per_shard: int = 1 << 16
    channels: int = DEFAULT_VALUE_CHANNELS
    batch_capacity_per_shard: int = 2048
    flush_interval_s: float = 0.05
    auto_register: bool = True
    default_device_type: str = "default"
    presence_missing_s: float = 8 * 3600.0
    use_native: bool = True
    strict_channels: bool = False
    fair_tenancy: bool = False
    wal_dir: str | None = None
    archive_dir: str | None = None     # long-term retention: spill each
                                       # (shard, arena) sub-ring to disk
                                       # before overwrite (utils/archive.py)
    archive_segment_rows: int = 4096
    archive_max_rows: int | None = None  # per-(shard,arena) retention cap
    archive_max_age_ms: int | None = None  # event-time retention horizon
    archive_cache_segments: int = 8    # LRU segment-decode cache depth
    flight_recorder: bool = True       # batch-lifecycle flight recorder
    flight_capacity: int = 1024        # lifecycle records retained
    span_trace: bool = True            # hierarchical span tracer (ISSUE
                                       # 10) — same contract as
                                       # EngineConfig.span_trace
    span_capacity: int = 4096          # completed spans retained
    span_sample: float = 1.0           # head-based keep fraction
    span_seed: int = 0                 # sampling hash seed
    qos: bool = False                  # overload discipline (utils/qos.py):
                                       # per-tenant token-bucket admission
                                       # consulted at the ingest EDGES
                                       # (REST/RPC/cluster forward), plus
                                       # weighted-fair ingest turns —
                                       # same contract as EngineConfig.qos
    tenant_rates: dict | None = None   # tenant -> admitted events/s
    qos_default_rate_eps: float = 0.0  # rate for unlisted tenants (0 = off)
    qos_burst_s: float = 2.0           # bucket depth, seconds of rate
    tenant_weights: dict | None = None # WFQ weights (default equal)
    shed_threshold: int = 0            # staged-row saturation valve (0 =
                                       # auto: 4 * batch_capacity_per_shard
                                       # * n_shards)
    qos_min_retry_after_s: float = 0.05
    conservation: bool = True          # event conservation ledger
                                       # (ISSUE 14) — same contract as
                                       # EngineConfig.conservation


class _StackedBuffer:
    """Host staging for all shards at once: [S, B, ...] numpy arrays with a
    per-shard fill count. ``emit()`` converts to ONE stacked EventBatch (one
    host->device transfer for the whole mesh step, not one per shard)."""

    def __init__(self, n_shards: int, capacity: int, channels: int):
        self.n_shards = n_shards
        self.capacity = capacity
        self.channels = channels
        self._alloc()

    def _alloc(self) -> None:
        s, b, c = self.n_shards, self.capacity, self.channels
        self.counts = np.zeros(s, np.int64)
        self.etype = np.zeros((s, b), np.int32)
        self.token_id = np.full((s, b), NULL_ID, np.int32)
        self.tenant_id = np.full((s, b), NULL_ID, np.int32)
        self.ts_ms = np.zeros((s, b), np.int32)
        self.received_ms = np.zeros((s, b), np.int32)
        self.values = np.zeros((s, b, c), np.float32)
        self.vmask = np.zeros((s, b, c), np.bool_)
        self.aux = np.full((s, b, AUX_LANES), NULL_ID, np.int32)

    def total(self) -> int:
        return int(self.counts.sum())

    def room(self, shard: int) -> int:
        return self.capacity - int(self.counts[shard])

    def append_row(self, shard: int, etype: int, local_token: int,
                   tenant_id: int, ts: int, recv: int,
                   values: np.ndarray | None, vmask: np.ndarray | None,
                   aux0: int, aux1: int) -> bool:
        i = int(self.counts[shard])
        if i >= self.capacity:
            return False
        self.etype[shard, i] = etype
        self.token_id[shard, i] = local_token
        self.tenant_id[shard, i] = tenant_id
        self.ts_ms[shard, i] = ts
        self.received_ms[shard, i] = recv
        if vmask is not None:
            self.values[shard, i] = values
            self.vmask[shard, i] = vmask
        self.aux[shard, i, 0] = aux0
        self.aux[shard, i, 1] = aux1
        self.counts[shard] = i + 1
        return True

    def emit(self) -> EventBatch:
        s, b = self.n_shards, self.capacity
        valid = np.arange(b)[None, :] < self.counts[:, None]
        # numpy-backed: the sharded jit dispatch transfers all leaves in one
        # grouped hop (no per-field device round trips)
        batch = EventBatch(
            valid=valid,
            etype=self.etype,
            token_id=self.token_id,
            tenant_id=self.tenant_id,
            ts_ms=self.ts_ms,
            received_ms=self.received_ms,
            values=self.values,
            vmask=self.vmask,
            aux=self.aux,
            seq=np.broadcast_to(np.arange(b, dtype=np.int32), (s, b)).copy(),
        )
        self._alloc()
        return batch


class _FairChunk:
    """A run of staged rows for one (shard, tenant) awaiting fair batch
    formation (engine.py _FairChunk analog, shard-local)."""

    __slots__ = ("etype", "token", "ts", "recv", "values", "vmask",
                 "aux0", "aux1", "pos")

    def __init__(self, etype, token, ts, recv, values, vmask, aux0, aux1):
        self.etype = etype
        self.token = token
        self.ts = ts
        self.recv = recv
        self.values = values
        self.vmask = vmask
        self.aux0 = aux0
        self.aux1 = aux1
        self.pos = 0

    @property
    def remaining(self) -> int:
        return len(self.etype) - self.pos


# --------------------------------------------------------------------------
# admin-path jit updaters over the STACKED state (leading shard axis). Used
# on the REST/API path only; the hot path registers on-device in the step.
@jax.jit
def _admin_create_device_stacked(state: PipelineState, shard, token_local,
                                 did, aid, type_id, tenant_id, area_id,
                                 customer_id):
    reg = state.registry
    reg = dataclasses.replace(
        reg,
        token_to_device=reg.token_to_device.at[shard, token_local].set(did),
        device_active=reg.device_active.at[shard, did].set(True),
        device_type=reg.device_type.at[shard, did].set(type_id),
        device_tenant=reg.device_tenant.at[shard, did].set(tenant_id),
        device_area=reg.device_area.at[shard, did].set(area_id),
        device_customer=reg.device_customer.at[shard, did].set(customer_id),
        device_assignments=reg.device_assignments.at[shard, did, 0].set(aid),
        assignment_active=reg.assignment_active.at[shard, aid].set(True),
        assignment_status=reg.assignment_status.at[shard, aid].set(
            jnp.int32(DeviceAssignmentStatus.ACTIVE)),
        assignment_device=reg.assignment_device.at[shard, aid].set(did),
        assignment_area=reg.assignment_area.at[shard, aid].set(area_id),
        assignment_customer=reg.assignment_customer.at[shard, aid].set(customer_id),
    )
    return dataclasses.replace(
        state,
        registry=reg,
        next_device=state.next_device.at[shard].max(did + 1),
        next_assignment=state.next_assignment.at[shard].max(aid + 1),
    )


@jax.jit
def _admin_set_device_active_stacked(state: PipelineState, shard, did, active):
    reg = state.registry
    return dataclasses.replace(
        state, registry=dataclasses.replace(
            reg, device_active=reg.device_active.at[shard, did].set(active)))


@jax.jit
def _admin_update_device_stacked(state: PipelineState, shard, did, type_id,
                                 area_id, customer_id):
    reg = state.registry
    return dataclasses.replace(
        state, registry=dataclasses.replace(
            reg,
            device_type=reg.device_type.at[shard, did].set(type_id),
            device_area=reg.device_area.at[shard, did].set(area_id),
            device_customer=reg.device_customer.at[shard, did].set(customer_id),
        ))


@jax.jit
def _admin_set_parent_stacked(state: PipelineState, shard, did, parent_did):
    reg = state.registry
    return dataclasses.replace(
        state, registry=dataclasses.replace(
            reg, device_parent=reg.device_parent.at[shard, did].set(parent_did)))


@jax.jit
def _admin_add_assignment_stacked(state: PipelineState, shard, did, aid, slot,
                                  asset_id, area_id, customer_id):
    reg = state.registry
    reg = dataclasses.replace(
        reg,
        device_assignments=reg.device_assignments.at[shard, did, slot].set(aid),
        assignment_active=reg.assignment_active.at[shard, aid].set(True),
        assignment_status=reg.assignment_status.at[shard, aid].set(
            jnp.int32(DeviceAssignmentStatus.ACTIVE)),
        assignment_device=reg.assignment_device.at[shard, aid].set(did),
        assignment_asset=reg.assignment_asset.at[shard, aid].set(asset_id),
        assignment_area=reg.assignment_area.at[shard, aid].set(area_id),
        assignment_customer=reg.assignment_customer.at[shard, aid].set(customer_id),
    )
    return dataclasses.replace(
        state, registry=reg,
        next_assignment=state.next_assignment.at[shard].max(aid + 1))


@jax.jit
def _admin_update_assignment_stacked(state: PipelineState, shard, aid,
                                     asset_id, area_id, customer_id):
    """Stacked-axis analog of engine._admin_update_assignment (REST PUT
    path; reference: Assignments.java:144 -> updateDeviceAssignment)."""
    reg = state.registry
    return dataclasses.replace(
        state, registry=dataclasses.replace(
            reg,
            assignment_asset=reg.assignment_asset.at[shard, aid].set(asset_id),
            assignment_area=reg.assignment_area.at[shard, aid].set(area_id),
            assignment_customer=reg.assignment_customer.at[shard, aid].set(
                customer_id),
        ))


@jax.jit
def _admin_set_assignment_status_stacked(state: PipelineState, shard, aid,
                                         status, active):
    reg = state.registry
    did = reg.assignment_device[shard, aid]
    row = reg.device_assignments[shard, did]
    new_row = jnp.where((row == aid) & ~active, jnp.int32(NULL_ID), row)
    reg = dataclasses.replace(
        reg,
        assignment_status=reg.assignment_status.at[shard, aid].set(status),
        assignment_active=reg.assignment_active.at[shard, aid].set(active),
        device_assignments=reg.device_assignments.at[shard, did].set(new_row),
    )
    return dataclasses.replace(state, registry=reg)


def _watch_stacked_admin_jits() -> None:
    """Devicewatch (ISSUE 11): the stacked admin updaters report
    compiles under one ``distributed.admin`` family — unbudgeted, like
    the single-node admin family (shared across every mesh config in
    the process)."""
    from sitewhere_tpu.utils.devicewatch import watched_jit

    g = globals()
    for name in ("_admin_create_device_stacked",
                 "_admin_set_device_active_stacked",
                 "_admin_update_device_stacked",
                 "_admin_set_parent_stacked",
                 "_admin_add_assignment_stacked",
                 "_admin_update_assignment_stacked",
                 "_admin_set_assignment_status_stacked"):
        g[name] = watched_jit(g[name], family="distributed.admin")


_watch_stacked_admin_jits()


class DistributedEngine(IngestHostMixin):
    """Multi-shard product engine: one object per host serving the whole
    mesh. All mutations serialize through one lock (single-writer semantics,
    like the single-node engine); the step itself is one stacked jit. WAL
    and strict-channel behavior come from IngestHostMixin — identical
    semantics to the single-node Engine by construction."""

    def __init__(self, config: DistributedConfig | None = None):
        self.config = c = config or DistributedConfig()
        self.sharded = ShardedEngine(
            n_shards=c.n_shards,
            device_capacity_per_shard=c.device_capacity_per_shard,
            token_capacity_per_shard=c.token_capacity_per_shard,
            assignment_capacity_per_shard=c.assignment_capacity_per_shard,
            store_capacity_per_shard=c.store_capacity_per_shard,
            channels=c.channels,
            config=PipelineConfig(auto_register=c.auto_register,
                                  default_device_type=0),
        )
        self.n_shards = self.sharded.n_shards
        self.epoch = EpochBase()
        self.lock = threading.RLock()
        self.host_counters: dict[str, int] = {}
        token_capacity = c.token_capacity_per_shard * self.n_shards
        self._native_decoder = None
        if c.use_native:
            try:
                from sitewhere_tpu.ingest.fast_decode import NativeBatchDecoder
                from sitewhere_tpu.native.binding import NativeInterner

                self.tokens = NativeInterner(token_capacity)
                self._native_decoder = NativeBatchDecoder(self.tokens, c.channels)
            except (RuntimeError, OSError):
                self._native_decoder = None
        if self._native_decoder is not None:
            self.channel_map = ChannelMap(c.channels, self._native_decoder.names,
                                          strict=c.strict_channels)
            self.alert_types = self._native_decoder.alert_types
        else:
            self.tokens = TokenInterner(token_capacity)
            self.channel_map = ChannelMap(c.channels, strict=c.strict_channels)
            self.alert_types = TokenInterner(1 << 20)
        self.tenants = TokenInterner(1 << 16)
        self.tenants.intern("default")
        self.device_types = TokenInterner(1 << 16)
        self.device_types.intern(c.default_device_type)
        self.areas = TokenInterner(1 << 16)
        self.customers = TokenInterner(1 << 16)
        self.assets = TokenInterner(1 << 16)
        # adopt the native decoder's event-id interner (alternate ids,
        # aux1) so batch-decoded and per-request rows share one id space
        self.event_ids = (self._native_decoder.event_ids
                          if self._native_decoder is not None
                          else TokenInterner(1 << 22))

        self._buf = _StackedBuffer(self.n_shards, c.batch_capacity_per_shard,
                                   c.channels)
        self._last_flush = time.monotonic()
        # host mirrors — flat dicts over GLOBAL ids (local * n_shards + shard)
        self.devices: dict[int, DeviceInfo] = {}
        self.token_device: dict[int, int] = {}        # gid -> global did
        self.assignments: dict[int, AssignmentInfo] = {}
        self.assignment_tokens: dict[str, int] = {}
        self.device_slots: dict[int, list[int]] = {}
        self._next_device = np.zeros(self.n_shards, np.int64)   # per shard
        self._next_assignment = np.zeros(self.n_shards, np.int64)
        self.dead_letters: list[str] = []             # unregistered tokens
        self.outputs: list[dict] = []
        self._pending_outs: list[StepOutput] = []
        self._pending_tenant_fixups: list[tuple[int, int, int]] = []
        # flight recorder (utils/flight.py): Engine-parity lifecycle
        # records for every ingest batch; the mixin's _ingest_batch binds
        # records, flush_async/drain stamp dispatch/device_ready/readback
        from sitewhere_tpu.utils.flight import FlightRecorder

        self.flight = FlightRecorder(capacity=c.flight_capacity,
                                     enabled=c.flight_recorder)
        self._staged_traces: list = []
        self._pending_traces: list[list] = []
        # span tracer + process-unique engine label (ISSUE 10) — same
        # wiring as the single-node Engine; ClusterEngine re-stamps
        # .rank exactly like it does for the flight recorder
        from sitewhere_tpu.utils.metrics import next_engine_label
        from sitewhere_tpu.utils.tracing import SpanTracer

        self.tracer = SpanTracer(capacity=c.span_capacity,
                                 enabled=c.span_trace,
                                 sample=c.span_sample, seed=c.span_seed)
        self.metrics_label = next_engine_label()
        # event conservation ledger (ISSUE 14) — Engine-parity flow
        # counters at the staging/dispatch boundaries of the mesh
        from sitewhere_tpu.utils.conservation import FlowLedger

        self.ledger = FlowLedger(enabled=c.conservation)
        self.conservation_auditor = None
        # fair tenancy: per-shard {tenant_id: deque[_FairChunk]}
        self._fair_queues: list[dict[int, collections.deque]] = [
            {} for _ in range(self.n_shards)]
        self._fair_queued = np.zeros(self.n_shards, np.int64)
        self.wal = None
        self._wal_local = threading.local()
        if c.wal_dir:
            from sitewhere_tpu.utils.ingestlog import IngestLog

            self.wal = IngestLog(c.wal_dir)
        # long-term retention: every (shard, arena) sub-ring spills to one
        # archive partition before its rows can be overwritten
        self.archive = None
        self._rows_since_spool = 0
        if c.archive_dir:
            from sitewhere_tpu.utils.archive import EventArchive, mesh_topology

            arenas = self.state.store.cursor.shape[-1]
            acap = c.store_capacity_per_shard // arenas
            self.archive = EventArchive(
                c.archive_dir,
                segment_rows=max(1, min(c.archive_segment_rows, acap // 4)),
                max_rows_per_part=c.archive_max_rows,
                topology=mesh_topology(self.n_shards, arenas),
                max_age_ms=c.archive_max_age_ms,
                cache_segments=c.archive_cache_segments)
            self._spool_trigger = max(self.archive.segment_rows,
                                      acap // 2 - c.batch_capacity_per_shard)
        # overload discipline (ISSUE 9): same contract as the single-node
        # engine — admission at the edges (the cluster RPC ingest
        # handlers consult engine.qos at the OWNER), WFQ turns on the
        # batch-ingest critical section. The replica applier and WAL
        # recovery call the ingest methods directly and therefore can
        # never shed a durable event.
        if getattr(c, "qos", False):
            from sitewhere_tpu.utils.qos import (AdmissionController,
                                                 WeightedFairGate)

            self.qos = AdmissionController(
                tenant_rates=c.tenant_rates,
                default_rate_eps=c.qos_default_rate_eps,
                burst_s=c.qos_burst_s,
                shed_threshold=(c.shed_threshold
                                or 4 * c.batch_capacity_per_shard
                                * self.n_shards),
                backlog_fn=lambda: self.staged_count,
                min_retry_after_s=c.qos_min_retry_after_s)
            self._wfq_gate = WeightedFairGate(c.tenant_weights)

    # ---------------------------------------------------------------- routing
    def _route(self, gid: int) -> tuple[int, int]:
        """(shard, local_token) for a global interner id."""
        return gid % self.n_shards, gid // self.n_shards

    def _gdid(self, shard: int, local_did: int) -> int:
        return local_did * self.n_shards + shard

    def _split_gdid(self, gdid: int) -> tuple[int, int]:
        return gdid % self.n_shards, gdid // self.n_shards

    @property
    def state(self) -> PipelineState:
        return self.sharded.state

    @property
    def staged_count(self) -> int:
        return self._buf.total() + int(self._fair_queued.sum())

    def _sync_mirrors(self) -> None:
        while self._buf.total() or self._fair_queued.sum():
            self.flush_async()
        if self._pending_outs:
            self.drain()

    # ---------------------------------------------------------------- ingest
    # process() comes from IngestHostMixin; it converts the request to one
    # SoA row and calls _stage_row, which routes it to its owning shard.
    def _stage_row(self, et, token_id, tenant_id, ts, now, values, mask,
                   aux0, aux1):
        """Stage one converted event row into its owning shard's buffer
        (``token_id`` is the GLOBAL interner id). Caller holds the lock."""
        shard, local = self._route(token_id)
        self.ledger.add("staged_rows", 1)
        has_vals = mask is not None and mask.any()
        if self.config.fair_tenancy:
            i32 = np.int32
            self._fair_enqueue(shard, tenant_id, _FairChunk(
                etype=np.array([et], i32),
                token=np.array([local], i32),
                ts=np.array([ts], i32),
                recv=np.array([now], i32),
                values=values[None].copy() if has_vals else None,
                vmask=mask[None].copy() if has_vals else None,
                aux0=np.array([aux0], i32),
                aux1=np.array([aux1], i32),
            ))
            return
        if not self._buf.append_row(shard, et, local, tenant_id, ts, now,
                                    values if has_vals else None,
                                    mask if has_vals else None, aux0, aux1):
            self.flush_async()
            self._buf.append_row(shard, et, local, tenant_id, ts, now,
                                 values if has_vals else None,
                                 mask if has_vals else None, aux0, aux1)
        if self._buf.room(shard) == 0:
            self.flush_async()

    def ingest_json_batch(self, payloads: list[bytes],
                          tenant: str = "default",
                          traceparent: str | None = None) -> dict:
        """Fast path: one native decode call for the batch, vectorized
        shard routing + staging (no per-event Python)."""
        from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder

        return self._ingest_batch(
            payloads, tenant, WAL_JSON, JsonDeviceRequestDecoder(),
            self._native_decoder.decode if self._native_decoder else None,
            traceparent=traceparent)

    def ingest_binary_batch(self, payloads: list[bytes],
                            tenant: str = "default",
                            traceparent: str | None = None) -> dict:
        from sitewhere_tpu.ingest.decoders import BinaryEventDecoder

        return self._ingest_batch(
            payloads, tenant, WAL_BINARY, BinaryEventDecoder(),
            self._native_decoder.decode_binary if self._native_decoder
            else None, traceparent=traceparent)

    def _ingest_decoded(self, res, payloads, tenant, reg_decoder) -> dict:
        """Stage a natively decoded SoA batch, grouped by owning shard with
        one argsort (the vectorized Kafka-partitioner hop)."""
        with self.lock:
            now = self._staging_now()
            base_ms = int(self.epoch.base_unix_s * 1000)
            etype, ok, ts_rel, values, failed, n_reg_ok = \
                self._decode_prologue(res, payloads, tenant, reg_decoder,
                                      now, base_ms)
            idxs = np.nonzero(ok)[0]
            tenant_id = self.tenants.intern(tenant)
            gids = res.token_id[idxs]
            shards = gids % self.n_shards
            locals_ = gids // self.n_shards
            order = np.argsort(shards, kind="stable")
            sidx, sshard, slocal = idxs[order], shards[order], locals_[order]
            bounds = np.searchsorted(sshard, np.arange(self.n_shards + 1))
            staged = 0
            for s in range(self.n_shards):
                rows = sidx[bounds[s]:bounds[s + 1]]
                toks = slocal[bounds[s]:bounds[s + 1]]
                if not len(rows):
                    continue
                if self.config.fair_tenancy:
                    self._fair_enqueue(s, tenant_id, _FairChunk(
                        etype=etype[rows],
                        token=toks.astype(np.int32),
                        ts=ts_rel[rows],
                        recv=np.full(len(rows), now, np.int32),
                        values=values[rows],
                        vmask=res.chmask[rows],
                        aux0=res.aux0[rows],
                        aux1=np.full(len(rows), NULL_ID, np.int32),
                    ))
                    staged += len(rows)
                    continue
                pos = 0
                b = self._buf
                while pos < len(rows):
                    room = b.room(s)
                    if room == 0:
                        self.flush_async()
                        room = b.capacity
                    chunk = rows[pos:pos + room]
                    tchunk = toks[pos:pos + room]
                    lo = int(b.counts[s])
                    hi = lo + len(chunk)
                    b.etype[s, lo:hi] = etype[chunk]
                    b.token_id[s, lo:hi] = tchunk
                    b.tenant_id[s, lo:hi] = tenant_id
                    b.ts_ms[s, lo:hi] = ts_rel[chunk]
                    b.received_ms[s, lo:hi] = now
                    b.values[s, lo:hi] = values[chunk]
                    b.vmask[s, lo:hi] = res.chmask[chunk]
                    b.aux[s, lo:hi, 0] = res.aux0[chunk]
                    b.counts[s] = hi
                    staged += len(chunk)
                    pos += len(chunk)
                if b.room(s) == 0:
                    self.flush_async()
            self.channel_map.collisions += res.collisions
            self.ledger.add("staged_rows", staged)
            return {"decoded": int(np.sum(ok)) + n_reg_ok, "failed": failed,
                    "staged": staged}

    # ----------------------------------------------------------- fair tenancy
    def _fair_enqueue(self, shard: int, tenant_id: int, chunk: _FairChunk) -> None:
        q = self._fair_queues[shard].get(tenant_id)
        if q is None:
            q = self._fair_queues[shard][tenant_id] = collections.deque()
        q.append(chunk)
        self._fair_queued[shard] += chunk.remaining
        if self._fair_queued[shard] >= self.config.batch_capacity_per_shard:
            self.flush_async()

    def fair_backlog(self, tenant: str) -> int:
        with self.lock:
            tid = self.tenants.lookup(tenant)
            return sum(
                c.remaining
                for queues in self._fair_queues
                for c in queues.get(tid, ()))

    def _form_fair_batch(self, shard: int) -> None:
        """Quota-sliced per-shard batch formation across tenants (engine.py
        _form_fair_batch per shard). Caller holds the lock."""
        b = self._buf
        queues = self._fair_queues[shard]
        while self._fair_queued[shard] and b.room(shard):
            active = [t for t, q in queues.items() if q]
            if not active:
                break
            quota = max(1, b.room(shard) // len(active))
            for tid in active:
                q = queues[tid]
                take = quota
                while take > 0 and q and b.room(shard):
                    ch = q[0]
                    k = min(take, ch.remaining, b.room(shard))
                    lo = int(b.counts[shard])
                    hi, p = lo + k, ch.pos
                    b.etype[shard, lo:hi] = ch.etype[p:p + k]
                    b.token_id[shard, lo:hi] = ch.token[p:p + k]
                    b.tenant_id[shard, lo:hi] = tid
                    b.ts_ms[shard, lo:hi] = ch.ts[p:p + k]
                    b.received_ms[shard, lo:hi] = ch.recv[p:p + k]
                    if ch.values is not None:
                        b.values[shard, lo:hi] = ch.values[p:p + k]
                        b.vmask[shard, lo:hi] = ch.vmask[p:p + k]
                    b.aux[shard, lo:hi, 0] = ch.aux0[p:p + k]
                    b.aux[shard, lo:hi, 1] = ch.aux1[p:p + k]
                    b.counts[shard] = hi
                    ch.pos += k
                    take -= k
                    self._fair_queued[shard] -= k
                    if ch.remaining == 0:
                        q.popleft()
        for tid in [t for t, q in queues.items() if not q]:
            del queues[tid]

    # ------------------------------------------------------------------ step
    def maybe_flush(self) -> dict | None:
        with self.lock:
            expired = (time.monotonic() - self._last_flush
                       >= self.config.flush_interval_s)
            if (self._buf.total() or self._fair_queued.sum()) and expired:
                return self.flush()
            if self._pending_outs and expired:
                return self.drain()[-1]
            return None

    def flush(self) -> dict:
        import logging

        from sitewhere_tpu.utils.tracing import stage

        try:
            with self.lock, stage("sharded_step"):
                self.flush_async()
                while self._fair_queued.sum():
                    self.flush_async()
                return self.drain()[-1]
        except Exception:
            self.flight.dump_error(logging.getLogger(__name__))
            raise

    def flush_async(self) -> None:
        """Dispatch one stacked step (no host sync); outputs queue for
        drain()."""
        with self.lock:
            if self._fair_queued.sum():
                for s in range(self.n_shards):
                    if self._fair_queued[s]:
                        self._form_fair_batch(s)
            if not self._buf.total():
                return
            n_staged = int(max(self._buf.counts))  # worst shard's rows
            self.ledger.add("dispatched_rows", self._buf.total())
            batch = self._buf.emit()
            traces, self._staged_traces = self._staged_traces, []
            for rec in traces:
                rec.mark("dispatch")
            out = self.sharded.step(batch)
            self._pending_outs.append(out)
            self._pending_traces.append(traces)
            self._last_flush = time.monotonic()
            if self.archive is not None:
                # per-shard bound: each staged row persists at most one
                # event per active assignment
                self._rows_since_spool += n_staged * MAX_ACTIVE_ASSIGNMENTS
                if self._rows_since_spool >= self._spool_trigger:
                    self._spool()

    def ring_heads(self) -> dict[int, int]:
        """Absolute ring write head per archive partition (part =
        shard * arenas + arena) — the ONE definition shared by the
        archive spooler and the conservation audit plane (ISSUE 14).
        Caller holds the lock (one small device readback)."""
        store = self.state.store
        arenas = store.cursor.shape[-1]
        acap = self.ring_arena_capacity()
        ep = np.asarray(jax.device_get(store.epoch)).astype(np.int64)
        cu = np.asarray(jax.device_get(store.cursor)).astype(np.int64)
        heads = ep * acap + cu
        return {s * arenas + a: int(heads[s, a])
                for s in range(self.n_shards) for a in range(arenas)}

    def ring_arena_capacity(self) -> int:
        """Rows one (shard, arena) sub-ring holds before wrapping."""
        return (self.config.store_capacity_per_shard
                // self.state.store.cursor.shape[-1])

    def _spool(self) -> None:
        """Spill full archive segments from every (shard, arena) sub-ring.
        Caller holds the lock. One fixed-count ``read_range`` program per
        segment (reused across shards via the per-shard tree slice)."""
        from sitewhere_tpu.ops.readback import read_range

        store = self.state.store
        arenas = store.cursor.shape[-1]
        acap = self.ring_arena_capacity()
        rows = self.archive.segment_rows
        heads = self.ring_heads()
        for s in range(self.n_shards):
            shard_store = None
            for a in range(arenas):
                part = s * arenas + a
                head = heads[part]
                start = self.archive.spilled(part)
                if head - start > acap:   # wrapped before we got here
                    self.archive.note_lost(head - acap - start)
                    start = head - acap
                while head - start >= rows:
                    if shard_store is None:
                        shard_store = jax.tree_util.tree_map(
                            lambda x: x[s], store)
                    sl = jax.device_get(read_range(
                        shard_store, jnp.int32(start % acap), rows,
                        arena=a))
                    self.archive.append_segment(part, start, sl)
                    start += rows
        self._rows_since_spool = 0

    def drain(self) -> list[dict]:
        """Absorb queued stacked outputs. Only the [S] scalar counter lanes
        are fetched for the whole backlog; per-shard token lists stay on
        device and are sliced to their actual lengths only for shards that
        registered or dead-lettered (readback bytes proportional to real
        occurrences — bulk readback is the expensive direction through a
        remote-chip tunnel)."""
        with self.lock:
            if not self._pending_outs:
                return [{"found": 0, "missed": 0, "registered": 0,
                         "persisted": 0, "new_tokens": [], "dead_tokens": []}]
            outs, self._pending_outs = self._pending_outs, []
            trace_lists, self._pending_traces = self._pending_traces, []
            scalars = jax.device_get([
                (o.n_found, o.n_missed, o.n_registered, o.n_persisted)
                for o in outs])
            for recs in trace_lists:   # the device_get observed completion
                for rec in recs:
                    if "device_ready" not in rec.stages:
                        rec.mark("device_ready")
                    rec.mark("readback")
            summaries = [self._absorb_output(o, s)
                         for o, s in zip(outs, scalars)]
            self._mirror_new_device_tenants()
            return summaries

    def _absorb_output(self, out: StepOutput, scalars) -> dict:
        """Mirror one stacked step output: per-shard device-side allocation
        order == compacted new_tokens order, exactly like the single-node
        engine's contract."""
        n_found_s, n_missed_s, n_reg_s, n_pers_s = (
            np.asarray(x) for x in scalars)
        new_all: list[str] = []
        dead_all: list[str] = []
        for s in range(self.n_shards):
            k = int(n_reg_s[s])
            if k:
                toks = jax.device_get(out.new_tokens[s, :k])
                for local_tok in (int(t) for t in toks):
                    gid = local_tok * self.n_shards + s
                    did = int(self._next_device[s])
                    aid = int(self._next_assignment[s])
                    self._next_device[s] += 1
                    self._next_assignment[s] += 1
                    gdid = self._gdid(s, did)
                    self.token_device[gid] = gdid
                    token = self.tokens.token(gid)
                    self.devices[gdid] = DeviceInfo(
                        token=token,
                        device_type=self.config.default_device_type,
                        tenant="default",  # fixed up from device column below
                        auto_registered=True,
                    )
                    self._pending_tenant_fixups.append((gdid, s, did))
                    self._record_assignment(self._gdid(s, aid), gdid, slot=0)
                    new_all.append(token)
            dk = min(int(n_missed_s[s]), out.dead_tokens.shape[1])
            if dk:
                for t in jax.device_get(out.dead_tokens[s, :dk]):
                    if int(t) != NULL_ID:
                        dead_all.append(self.tokens.token(
                            int(t) * self.n_shards + s))
        self.dead_letters.extend(dead_all)
        summary = {
            "found": int(n_found_s.sum()),
            "missed": int(n_missed_s.sum()),
            "registered": int(n_reg_s.sum()),
            "persisted": int(n_pers_s.sum()),
            "new_tokens": new_all,
            "dead_tokens": dead_all,
        }
        self.outputs.append(summary)
        del self.outputs[:-256]
        return summary

    def _mirror_new_device_tenants(self) -> None:
        """One gather for every auto-registered device's tenant column
        (instead of a device->host transfer per device)."""
        if not self._pending_tenant_fixups:
            return
        fix, self._pending_tenant_fixups = self._pending_tenant_fixups, []
        sh = jnp.asarray([f[1] for f in fix], jnp.int32)
        dd = jnp.asarray([f[2] for f in fix], jnp.int32)
        tens = np.asarray(jax.device_get(
            self.state.registry.device_tenant[sh, dd]))
        for (gdid, _, _), ten in zip(fix, tens):
            if int(ten) != NULL_ID:
                info = self.devices.get(gdid)
                if info is not None:
                    info.tenant = self.tenants.token(int(ten))
                    aid = (self.device_slots.get(gdid) or [NULL_ID])[0]
                    if aid != NULL_ID and aid in self.assignments:
                        self.assignments[aid].tenant = info.tenant

    # ------------------------------------------------------------------ admin
    def register_device(self, token: str, device_type: str | None = None,
                        tenant: str = "default", area: str | None = None,
                        customer: str | None = None,
                        metadata: dict | None = None) -> int:
        """API-path device creation (get-or-create); returns the GLOBAL
        device id."""
        with self.lock:
            self._sync_mirrors()
            gid = self.tokens.intern(token)
            existing = self.token_device.get(gid)
            if existing is not None:
                return existing
            shard, local_tok = self._route(gid)
            did = int(self._next_device[shard])
            aid = int(self._next_assignment[shard])
            if did >= self.config.device_capacity_per_shard:
                raise RuntimeError(f"device capacity exhausted on shard {shard}")
            type_name = device_type or self.config.default_device_type
            # admin-path registrations ride the WAL + replica feed as
            # their wire-form envelope (standby visibility; PR-6 limit)
            self._wal_admin_register(token, type_name, tenant, area,
                                     customer)
            self._next_device[shard] += 1
            self._next_assignment[shard] += 1
            self.sharded.state = _admin_create_device_stacked(
                self.sharded.state,
                jnp.int32(shard), jnp.int32(local_tok),
                jnp.int32(did), jnp.int32(aid),
                jnp.int32(self.device_types.intern(type_name)),
                jnp.int32(self.tenants.intern(tenant)),
                jnp.int32(self.areas.intern(area) if area else NULL_ID),
                jnp.int32(self.customers.intern(customer) if customer else NULL_ID),
            )
            gdid = self._gdid(shard, did)
            self.token_device[gid] = gdid
            self.devices[gdid] = DeviceInfo(
                token=token, device_type=type_name, tenant=tenant,
                area=area, customer=customer, metadata=metadata or {},
            )
            self._record_assignment(self._gdid(shard, aid), gdid, slot=0,
                                    area=area, customer=customer)
            return gdid

    def delete_device(self, token: str) -> bool:
        with self.lock:
            self._sync_mirrors()
            gid = self.tokens.lookup(token)
            gdid = self.token_device.get(gid)
            if gdid is None:
                return False
            shard, did = self._split_gdid(gdid)
            self.sharded.state = _admin_set_device_active_stacked(
                self.sharded.state, jnp.int32(shard), jnp.int32(did), False)
            return True

    def map_device(self, child_token: str, parent_token: str) -> DeviceInfo:
        """Gateway/composite mapping. The on-device parent column is
        shard-local, so it is set only when parent and child land on the
        same shard; the host mirror always records the mapping (command
        routing uses the mirror)."""
        with self.lock:
            self._sync_mirrors()
            cgid = self.tokens.lookup(child_token)
            cdid = self.token_device.get(cgid)
            if cdid is None:
                raise KeyError(f"device {child_token!r} not registered")
            pgid = self.tokens.lookup(parent_token)
            pdid = self.token_device.get(pgid)
            if pdid is None:
                raise KeyError(f"parent device {parent_token!r} not registered")
            if cdid == pdid:
                raise ValueError("device cannot be its own parent")
            info = self.devices[cdid]
            info.metadata = dict(info.metadata) | {"parentToken": parent_token}
            cs, cd = self._split_gdid(cdid)
            ps, pd = self._split_gdid(pdid)
            if cs == ps:
                self.sharded.state = _admin_set_parent_stacked(
                    self.sharded.state, jnp.int32(cs), jnp.int32(cd),
                    jnp.int32(pd))
            return info

    def _record_assignment(self, gaid: int, gdid: int, slot: int,
                           token: str | None = None, asset: str | None = None,
                           area: str | None = None, customer: str | None = None,
                           metadata: dict | None = None) -> AssignmentInfo:
        dev = self.devices[gdid]
        tok = token or f"{dev.token}:a{gaid}"
        info = AssignmentInfo(
            token=tok, id=gaid, device_token=dev.token, tenant=dev.tenant,
            asset=asset, area=area or dev.area,
            customer=customer or dev.customer,
            metadata=metadata or {}, created_ms=self.epoch.now_ms(),
        )
        self.assignments[gaid] = info
        self.assignment_tokens[tok] = gaid
        slots = self.device_slots.setdefault(
            gdid, [NULL_ID] * MAX_ACTIVE_ASSIGNMENTS)
        slots[slot] = gaid
        return info

    def create_assignment(self, device_token: str, token: str | None = None,
                          asset: str | None = None, area: str | None = None,
                          customer: str | None = None,
                          metadata: dict | None = None) -> AssignmentInfo:
        with self.lock:
            self._sync_mirrors()
            gid = self.tokens.lookup(device_token)
            gdid = self.token_device.get(gid)
            if gdid is None:
                raise KeyError(f"device {device_token!r} not registered")
            if token is not None and token in self.assignment_tokens:
                raise ValueError(f"assignment token {token!r} already exists")
            slots = self.device_slots.setdefault(
                gdid, [NULL_ID] * MAX_ACTIVE_ASSIGNMENTS)
            try:
                slot = slots.index(NULL_ID)
            except ValueError:
                raise ValueError(
                    f"device {device_token!r} already has "
                    f"{MAX_ACTIVE_ASSIGNMENTS} active assignments") from None
            shard, did = self._split_gdid(gdid)
            aid = int(self._next_assignment[shard])
            if aid >= self.config.assignment_capacity_per_shard:
                raise RuntimeError("assignment capacity exhausted")
            self._next_assignment[shard] += 1
            self.sharded.state = _admin_add_assignment_stacked(
                self.sharded.state, jnp.int32(shard), jnp.int32(did),
                jnp.int32(aid), jnp.int32(slot),
                jnp.int32(self.assets.intern(asset) if asset else NULL_ID),
                jnp.int32(self.areas.intern(area) if area else NULL_ID),
                jnp.int32(self.customers.intern(customer) if customer else NULL_ID),
            )
            return self._record_assignment(
                self._gdid(shard, aid), gdid, slot, token=token, asset=asset,
                area=area, customer=customer, metadata=metadata)

    def update_device(self, token: str, device_type: str | None = None,
                      area: str | None = None, customer: str | None = None,
                      metadata: dict | None = None) -> DeviceInfo:
        """Update device columns + host metadata on the owning shard
        (Engine.update_device parity for the REST surface)."""
        with self.lock:
            self._sync_mirrors()
            gid = self.tokens.lookup(token)
            gdid = self.token_device.get(gid)
            if gdid is None:
                raise KeyError(f"device {token!r} not registered")
            info = self.devices[gdid]
            shard, did = self._split_gdid(gdid)
            type_id = jnp.int32(self.device_types.intern(
                device_type if device_type is not None else info.device_type))
            new_area = area if area is not None else info.area
            area_id = jnp.int32(
                self.areas.intern(new_area) if new_area else NULL_ID)
            new_customer = customer if customer is not None else info.customer
            customer_id = jnp.int32(
                self.customers.intern(new_customer) if new_customer else NULL_ID)
            self.sharded.state = _admin_update_device_stacked(
                self.sharded.state, jnp.int32(shard), jnp.int32(did),
                type_id, area_id, customer_id)
            if device_type is not None:
                info.device_type = device_type
            if area is not None:
                info.area = area
            if customer is not None:
                info.customer = customer
            if metadata is not None:
                info.metadata = metadata
            return info

    def get_assignment(self, token: str) -> AssignmentInfo | None:
        aid = self.assignment_tokens.get(token)
        return self.assignments.get(aid) if aid is not None else None

    def list_assignments(self, device_token: str | None = None,
                         status: str | None = None,
                         area: str | None = None,
                         asset: str | None = None,
                         customer: str | None = None) -> list[AssignmentInfo]:
        with self.lock:
            out = [
                a for a in self.assignments.values()
                if (device_token is None or a.device_token == device_token)
                and (status is None or a.status == status)
                and (area is None or a.area == area)
                and (asset is None or a.asset == asset)
                and (customer is None or a.customer == customer)
            ]
            return sorted(out, key=lambda a: a.id)

    def _set_assignment_status(self, token: str,
                               status: DeviceAssignmentStatus) -> AssignmentInfo:
        with self.lock:
            self._sync_mirrors()
            gaid = self.assignment_tokens.get(token)
            if gaid is None:
                raise KeyError(f"assignment {token!r} not found")
            shard, aid = self._split_gdid(gaid)
            active = status is not DeviceAssignmentStatus.RELEASED
            self.sharded.state = _admin_set_assignment_status_stacked(
                self.sharded.state, jnp.int32(shard), jnp.int32(aid),
                jnp.int32(status), active)
            info = self.assignments[gaid]
            info.status = status.name
            if not active:
                info.released_ms = self.epoch.now_ms()
                gdid = self.token_device.get(
                    self.tokens.lookup(info.device_token))
                if gdid is not None and gdid in self.device_slots:
                    self.device_slots[gdid] = [
                        NULL_ID if a == gaid else a
                        for a in self.device_slots[gdid]]
            return info

    def release_assignment(self, token: str) -> AssignmentInfo:
        return self._set_assignment_status(
            token, DeviceAssignmentStatus.RELEASED)

    def mark_assignment_missing(self, token: str) -> AssignmentInfo:
        """Flag an assignment MISSING (reference: Assignments.java
        /assignments/{token}/missing); it stays active so events still
        expand to it — Engine parity for the REST surface."""
        return self._set_assignment_status(
            token, DeviceAssignmentStatus.MISSING)

    def update_assignment(self, token: str, asset: str | None = None,
                          area: str | None = None,
                          customer: str | None = None,
                          metadata: dict | None = None) -> AssignmentInfo:
        """Update an assignment's association columns on its owning shard +
        host metadata (Engine.update_assignment parity; reference:
        Assignments.java:144 PUT)."""
        with self.lock:
            self._sync_mirrors()
            gaid = self.assignment_tokens.get(token)
            if gaid is None:
                raise KeyError(f"assignment {token!r} not found")
            info = self.assignments[gaid]
            shard, aid = self._split_gdid(gaid)
            new_asset = asset if asset is not None else info.asset
            new_area = area if area is not None else info.area
            new_customer = customer if customer is not None else info.customer
            # intern before mutating so a capacity error never half-applies
            asset_id = jnp.int32(
                self.assets.intern(new_asset) if new_asset else NULL_ID)
            area_id = jnp.int32(
                self.areas.intern(new_area) if new_area else NULL_ID)
            customer_id = jnp.int32(
                self.customers.intern(new_customer)
                if new_customer else NULL_ID)
            self.sharded.state = _admin_update_assignment_stacked(
                self.sharded.state, jnp.int32(shard), jnp.int32(aid),
                asset_id, area_id, customer_id)
            info.asset, info.area, info.customer = (
                new_asset, new_area, new_customer)
            if metadata is not None:
                info.metadata = metadata
            return info

    def delete_assignment(self, token: str) -> bool:
        """Delete an assignment (reference: Assignments.java DELETE):
        detach on-device (release semantics) and drop the host record;
        persisted events keep the id — deletes don't rewrite history."""
        with self.lock:
            self._sync_mirrors()
            gaid = self.assignment_tokens.get(token)
            if gaid is None:
                return False
            if self.assignments[gaid].status != "RELEASED":
                self._set_assignment_status(
                    token, DeviceAssignmentStatus.RELEASED)
            del self.assignments[gaid]
            del self.assignment_tokens[token]
            return True

    # ------------------------------------------------------------------ queries
    def get_device(self, token: str) -> DeviceInfo | None:
        if self._pending_outs:
            with self.lock:
                self._sync_mirrors()
        gid = self.tokens.lookup(token)
        gdid = self.token_device.get(gid)
        return self.devices.get(gdid) if gdid is not None else None

    def get_device_state(self, token: str) -> dict | None:
        """One device's aggregated state from its owning shard."""
        from sitewhere_tpu.core.state import RECENT_DEPTH

        with self.lock:
            self._sync_mirrors()
            gid = self.tokens.lookup(token)
            gdid = self.token_device.get(gid)
            if gdid is None:
                return None
            shard, d = self._split_gdid(gdid)
            ds = self.state.device_state
            # slice this device's rows in one device_get
            row = jax.device_get({
                "presence": ds.presence[shard, d],
                "last": ds.last_interaction_ms[shard, d],
                "meas_last": ds.meas_last[shard, d],
                "meas_last_ms": ds.meas_last_ms[shard, d],
                "recent_loc": ds.recent_loc[shard, d],
                "recent_loc_ms": ds.recent_loc_ms[shard, d],
                "recent_loc_valid": ds.recent_loc_valid[shard, d],
                "recent_alert_level": ds.recent_alert_level[shard, d],
                "recent_alert_type": ds.recent_alert_type[shard, d],
                "recent_alert_ms": ds.recent_alert_ms[shard, d],
                "recent_alert_valid": ds.recent_alert_valid[shard, d],
                "event_counts": ds.event_counts[shard, d],
            })
            chans = {}
            for name, nid in self.channel_map.names.items():
                ch = nid % self.config.channels
                ts = int(row["meas_last_ms"][ch])
                if ts > -(2**31) + 10:
                    chans[name] = {"value": float(row["meas_last"][ch]),
                                   "ts_ms": ts}
            recent_locs = [
                {
                    "latitude": float(row["recent_loc"][r, 0]),
                    "longitude": float(row["recent_loc"][r, 1]),
                    "elevation": float(row["recent_loc"][r, 2]),
                    "ts_ms": int(row["recent_loc_ms"][r]),
                }
                for r in range(RECENT_DEPTH)
                if bool(row["recent_loc_valid"][r])
            ]
            recent_alerts = [
                {
                    "level": int(row["recent_alert_level"][r]),
                    "type": self.alert_types.token(int(row["recent_alert_type"][r])),
                    "ts_ms": int(row["recent_alert_ms"][r]),
                }
                for r in range(RECENT_DEPTH)
                if bool(row["recent_alert_valid"][r])
            ]
            return {
                "device": self.devices[gdid].token,
                "shard": shard,
                "presence": PresenceState(int(row["presence"])).name,
                "last_interaction_ms": int(row["last"]),
                "measurements": chans,
                "recent_locations": recent_locs,
                "recent_alerts": recent_alerts,
                "event_counts": {
                    EventType(e).name: int(row["event_counts"][e])
                    for e in range(6)
                },
            }

    def query_events(self, device_token: str | None = None,
                     etype: EventType | None = None,
                     tenant: str | None = None,
                     since_ms: int | None = None,
                     until_ms: int | None = None,
                     limit: int = 100,
                     assignment_id: int | None = None,
                     aux0: int | None = None,
                     area: str | None = None,
                     customer: str | None = None,
                     alternate_id: str | None = None) -> dict:
        """Global newest-first query: every shard scans its ring on its own
        device (vmapped filter + top-k), host merges the per-shard pages
        with one vectorized argsort (scatter-gather across partitions).
        Filter surface matches Engine.query_events so the REST gateway
        serves identically from the sharded state. (``assignment_id`` is a
        GLOBAL id; its shard-local row filters on the owning shard.)"""
        with self.lock:
            self._sync_mirrors()
            dev_filter = NULL_ID
            shard_filter = None
            if device_token is not None:
                gid = self.tokens.lookup(device_token)
                gdid = self.token_device.get(gid, None)
                if gdid is None:
                    return {"total": 0, "events": []}
                shard_filter, dev_filter = self._split_gdid(gdid)
            ten = NULL_ID
            if tenant is not None:
                ten = self.tenants.lookup(tenant)
                if ten == NULL_ID:   # unknown tenant matches NOTHING —
                    return {"total": 0, "events": []}   # never all tenants
            area_id = customer_id = aux1 = None
            if area is not None:
                area_id = self.areas.lookup(area)
                if area_id == NULL_ID:
                    return {"total": 0, "events": []}
            if customer is not None:
                customer_id = self.customers.lookup(customer)
                if customer_id == NULL_ID:
                    return {"total": 0, "events": []}
            if alternate_id is not None:
                aux1 = self.event_ids.lookup(alternate_id)
                if aux1 == NULL_ID:
                    return {"total": 0, "events": []}
            a_local = None
            if assignment_id is not None:
                # global assignment id -> its owning shard's local row;
                # restrict the scan to that shard like the device filter
                a_shard, a_local = self._split_gdid(assignment_id)
                if shard_filter is not None and shard_filter != a_shard:
                    return {"total": 0, "events": []}
                shard_filter = a_shard
            res = _stacked_query(
                self.state.store,
                jnp.int32(int(etype) if etype is not None else NULL_ID),
                jnp.int32(ten),
                jnp.int32(since_ms if since_ms is not None else -(2**31)),
                jnp.int32(until_ms if until_ms is not None else 2**31 - 1),
                limit=limit,
                device=jnp.int32(dev_filter),
                device_shard=(jnp.int32(shard_filter)
                              if shard_filter is not None else None),
                assignment=(jnp.int32(a_local)
                            if a_local is not None else None),
                assignment_shard=(jnp.int32(shard_filter)
                                  if a_local is not None else None),
                aux0=jnp.int32(aux0) if aux0 is not None else None,
                aux1=jnp.int32(aux1) if aux1 is not None else None,
                area=jnp.int32(area_id) if area_id is not None else None,
                customer=(jnp.int32(customer_id)
                          if customer_id is not None else None),
            )
            res = jax.device_get(res)
            ns = np.asarray(res.n)
            ts = np.asarray(res.ts_ms)
            valid = np.arange(ts.shape[1])[None, :] < ns[:, None]
            s_idx, i_idx = np.nonzero(valid)
            order = np.argsort(-ts[s_idx, i_idx], kind="stable")[:limit]
            sel_s, sel_i = s_idx[order], i_idx[order]
            lane_names = self._lane_names()
            events = [
                self._format_event(
                    int(res.etype[s, i]), int(s), int(res.device[s, i]),
                    int(res.assignment[s, i]), int(res.ts_ms[s, i]),
                    int(res.received_ms[s, i]), res.values[s, i],
                    res.vmask[s, i], res.aux[s, i], lane_names)
                for s, i in zip(sel_s, sel_i)
            ]
            total = int(np.sum(np.asarray(res.total)))
            if self.archive is not None and self.archive.segments:
                arenas = self.state.store.cursor.shape[-1]
                parts_of = (
                    frozenset(shard_filter * arenas + a
                              for a in range(arenas))
                    if shard_filter is not None else None)
                total, events = self._merge_archive(
                    total, events, limit, lane_names,
                    device=int(dev_filter) if dev_filter != NULL_ID else None,
                    device_parts=parts_of,
                    etype=int(etype) if etype is not None else None,
                    tenant=ten if ten != NULL_ID else None,
                    since_ms=since_ms, until_ms=until_ms,
                    assignment=a_local,
                    assignment_parts=(parts_of if a_local is not None
                                      else None),
                    aux0=aux0, aux1=aux1, area=area_id,
                    customer=customer_id)
            return {"total": total, "events": events}

    def _merge_archive(self, total: int, events: list[dict], limit: int,
                       lane_names: dict[int, str],
                       **filters) -> tuple[int, list[dict]]:
        """Fold archived (evicted-from-ring) history into a mesh query
        result — same no-overlap cap as Engine._merge_archive, per
        (shard, arena) partition. Caller holds the lock."""
        store = self.state.store
        arenas = store.cursor.shape[-1]
        acap = self.config.store_capacity_per_shard // arenas
        ep = np.asarray(jax.device_get(store.epoch)).astype(np.int64)
        cu = np.asarray(jax.device_get(store.cursor)).astype(np.int64)
        heads = ep * acap + cu
        max_pos = {s * arenas + a: int(heads[s, a]) - acap
                   for s in range(self.n_shards) for a in range(arenas)}
        if all(v <= 0 for v in max_pos.values()):
            return total, events
        a_total, rows = self.archive.query(max_pos=max_pos, limit=limit,
                                           **filters)
        if not a_total:
            return total, events
        a_events = [
            self._format_event(
                int(r["etype"]), int(r["part"]) // arenas, int(r["device"]),
                int(r["assignment"]), int(r["ts_ms"]),
                int(r["received_ms"]), r["values"], r["vmask"], r["aux"],
                lane_names)
            for r in rows
        ]
        merged = sorted(events + a_events,
                        key=lambda e: -e["eventDateMs"])[:limit]
        return total + a_total, merged

    def _lane_names(self) -> dict[int, str]:
        lane_names: dict[int, str] = {}
        for name, nid in self.channel_map.names.items():
            lane_names.setdefault(nid % self.config.channels, name)
        return lane_names

    def _format_event(self, et_i: int, shard: int, device: int,
                      assignment: int, ts: int, received: int, values,
                      vmask, aux, lane_names: dict[int, str]) -> dict:
        """One persisted store row (shard-local ids) -> the REST event dict
        — the single formatter behind the ring query, the archive merge,
        and the by-id lookup, full six-type coverage matching
        Engine._format_event."""
        et = EventType(et_i)
        gdid = self._gdid(shard, device)
        info = self.devices.get(gdid)
        ev = {
            "type": et.name,
            "deviceToken": info.token if info else None,
            "shard": shard,
            "assignmentId": self._gdid(shard, assignment),
            "eventDateMs": ts,
            "receivedDateMs": received,
        }
        if et is EventType.MEASUREMENT:
            ev["measurements"] = {
                lane_names.get(int(c), f"ch{c}"): float(values[c])
                for c in np.nonzero(vmask)[0]
            }
        elif et is EventType.LOCATION:
            if vmask[0]:
                ev["latitude"] = float(values[0])
                ev["longitude"] = float(values[1])
                ev["elevation"] = float(values[2])
            else:
                ev["latitude"] = ev["longitude"] = ev["elevation"] = None
        elif et is EventType.ALERT:
            ev["level"] = int(values[0])
            atype = int(aux[0])
            ev["alertType"] = (
                self.alert_types.token(atype)
                if 0 <= atype < len(self.alert_types) else None)
        elif et is EventType.COMMAND_INVOCATION:
            ev["invocationId"] = int(aux[0])
        elif et is EventType.COMMAND_RESPONSE:
            oid = int(aux[0])
            ev["originatingEventId"] = (
                self.event_ids.token(oid)
                if 0 <= oid < len(self.event_ids) else None)
        elif et is EventType.STATE_CHANGE:
            sid = int(aux[0])
            if 0 <= sid < len(self.event_ids):
                attr, _, change = self.event_ids.token(sid).partition(":")
                ev["attribute"], ev["stateChange"] = attr, change
        return ev

    def search_device_states(self, last_interaction_before_ms: int | None = None,
                             presence: str | None = None,
                             limit: int = 100) -> list[dict]:
        """Vectorized device-state search over the stacked state columns."""
        with self.lock:
            self._sync_mirrors()
            ds = self.state.device_state
            last = np.asarray(jax.device_get(ds.last_interaction_ms))
            pres = np.asarray(jax.device_get(ds.presence))
            n_per = self._next_device
            mask = (np.arange(last.shape[1])[None, :] < n_per[:, None])
            if last_interaction_before_ms is not None:
                mask &= last < last_interaction_before_ms
            if presence is not None:
                mask &= pres == int(PresenceState[presence.upper()])
            out = []
            for s, d in zip(*np.nonzero(mask)):
                if len(out) >= limit:
                    break
                info = self.devices.get(self._gdid(int(s), int(d)))
                if info is None:
                    continue
                out.append({
                    "device": info.token,
                    "deviceType": info.device_type,
                    "tenant": info.tenant,
                    "shard": int(s),
                    "presence": PresenceState(int(pres[s, d])).name,
                    "lastInteractionMs": int(last[s, d]),
                })
            return out

    def presence_sweep(self) -> list[str]:
        """Mark stale devices MISSING on every shard; returns their tokens."""
        with self.lock:
            self._sync_mirrors()
            pairs = self.sharded.presence_sweep(
                self.epoch.now_ms(),
                int(self.config.presence_missing_s * 1000))
            out = []
            for s, d in pairs:
                info = self.devices.get(self._gdid(s, d))
                if info is not None:
                    out.append(info.token)
            return out

    # uniform "sweep THIS engine only" name (see Engine.presence_sweep_local)
    presence_sweep_local = presence_sweep

    def get_event(self, event_id: int,
                  tenant: str | None = None) -> dict | None:
        """Fetch one persisted event by its mesh-global id — the id layout
        DistributedFeedConsumer hands out (``pos * n_parts + shard * arenas
        + arena`` with ``n_parts = n_shards * arenas``), so the REST
        /api/events/id/{eventId} lookup works identically against the
        distributed engine (reference: DeviceEvents.java
        getDeviceEventById). Returns None when the id was never written or
        its ring slot has been overwritten. ``tenant`` scopes the lookup
        (rows of other tenants read as absent — ids are enumerable)."""
        from sitewhere_tpu.ops.readback import read_range

        with self.lock:
            self._sync_mirrors()
            ten = None
            if tenant is not None:
                ten = self.tenants.lookup(tenant)
                if ten == NULL_ID:
                    return None
            store = self.state.store
            if event_id < 0:
                return None
            arenas = store.cursor.shape[-1]
            pos, s, a = split_event_id(event_id, self.n_shards, arenas)
            acap = self.config.store_capacity_per_shard // arenas
            head = (int(jax.device_get(store.epoch[s, a])) * acap
                    + int(jax.device_get(store.cursor[s, a])))
            if pos >= head:
                return None
            if pos < head - acap:
                # evicted from the ring — resolve from the archive so the
                # by-id surface agrees with query_events
                if self.archive is None:
                    return None
                r = self.archive.get_row(s * arenas + a, pos)
                if r is None:
                    return None
                if ten is not None and int(r["tenant"]) != ten:
                    return None
                ev = self._format_event(
                    int(r["etype"]), s, int(r["device"]),
                    int(r["assignment"]), int(r["ts_ms"]),
                    int(r["received_ms"]), r["values"], r["vmask"],
                    r["aux"], self._lane_names())
                ev["eventId"] = event_id
                return ev
            shard_store = jax.tree_util.tree_map(lambda x: x[s], store)
            sl = jax.device_get(read_range(
                shard_store, jnp.int32(pos % acap), 1, arena=a))
            if not bool(sl.valid[0]):
                return None
            if ten is not None and int(sl.tenant[0]) != ten:
                return None
            ev = self._format_event(
                int(sl.etype[0]), s, int(sl.device[0]),
                int(sl.assignment[0]), int(sl.ts_ms[0]),
                int(sl.received_ms[0]), sl.values[0],
                np.asarray(sl.vmask[0]), np.asarray(sl.aux[0]),
                self._lane_names())
            ev["eventId"] = event_id
            return ev

    def make_feed_consumer(self, group_id: str, max_batch: int = 1024,
                           start_from_latest: bool = False):
        """Outbound consumer over the per-shard rings (Engine parity)."""
        return DistributedFeedConsumer(self, group_id, max_batch=max_batch,
                                       start_from_latest=start_from_latest)

    def metrics(self) -> dict:
        m = self.sharded.global_metrics()
        m["channel_collisions"] = self.channel_map.collisions
        m["staged"] = self.staged_count
        m["n_shards"] = self.n_shards
        m["devices"] = int(self._next_device.sum())
        if self.archive is not None:
            m["archived_rows"] = self.archive.total_rows()
            m["archive_lost_rows"] = self.archive.lost_rows
        # counters first would shadow nothing, but m is built from the
        # device metrics; guard the same way — core keys win
        m = dict(self.host_counters) | m
        return m

    def tenant_metrics(self) -> dict[str, dict[str, int]]:
        """Per-tenant event counts over ALL shards: vmap the single-state
        segment-sum (engine._tenant_event_counts) across the stacked
        state and reduce — tenant ids are engine-global, so summing the
        per-shard [t_cap, E] grids is exact (Engine.tenant_metrics
        parity for the Prometheus per-tenant series)."""
        from sitewhere_tpu.engine import (_tenant_event_counts, tenant_cap,
                                          tenant_counts_dict)

        with self.lock:
            self._sync_mirrors()
            n_tenants = len(self.tenants)
            t_cap = tenant_cap(n_tenants)
            per_shard = jax.vmap(
                lambda st: _tenant_event_counts(st, t_cap))(
                    self.sharded.state)                    # [S, T, E]
            counts = np.asarray(per_shard).sum(axis=0)
        return tenant_counts_dict(counts, self.tenants, n_tenants)

    def shard_metrics(self) -> list[dict]:
        """Per-shard counters (the per-partition consumer-lag analog).
        Only scalar counter fields report here; the packed per-tenant
        grid has its own accessor (tenant_pipeline_counters)."""
        mm = jax.device_get(self.state.metrics)
        fields = [f.name for f in dataclasses.fields(mm)
                  if np.ndim(getattr(mm, f.name)) == 1]   # [S] scalars only
        return [
            {name: int(np.asarray(getattr(mm, name))[s]) for name in fields}
            | {"devices": int(self._next_device[s])}
            for s in range(self.n_shards)
        ]

    def tenant_pipeline_counters(self) -> dict[str, dict[str, int]]:
        """Engine-parity device-side per-tenant counter grid, summed over
        shards (tenant ids are engine-global, so the per-shard [T, C]
        grids add exactly). Read back on the scrape path only."""
        from sitewhere_tpu.engine import format_tenant_counter_grid

        with self.lock:
            grid = np.asarray(jax.device_get(
                self.state.metrics.tenant_counters)).sum(axis=0)
            return format_tenant_counter_grid(grid, self.tenants)

    # ------------------------------------------------------------- durability
    def total_cursor(self) -> int:
        """Sum of per-shard absolute store cursors — monotone under appends,
        so it serves as the WAL watermark for the whole mesh."""
        st = self.state.store
        epochs = np.asarray(jax.device_get(st.epoch))   # [S, A]
        cursors = np.asarray(jax.device_get(st.cursor))
        acap = self.config.store_capacity_per_shard // epochs.shape[-1]
        return int(np.sum(epochs.astype(np.int64) * acap + cursors))

    def save(self, directory) -> dict:
        """Full mesh snapshot: stacked device state + host mirrors +
        interners. Pairs with the WAL for exact crash recovery
        (recover_distributed)."""
        import json
        import pathlib

        directory = pathlib.Path(directory)
        with self.lock:
            self._sync_mirrors()
            manifest = self.sharded.save(directory)
            cursor = self.total_cursor()
            host = {
                "format": 1,
                "config": dataclasses.asdict(self.config),
                "n_shards": self.n_shards,
                "epoch_base_unix_s": self.epoch.base_unix_s,
                "store_cursor": cursor,
                "next_device": [int(x) for x in self._next_device],
                "next_assignment": [int(x) for x in self._next_assignment],
                "tokens": [self.tokens.token(i)
                           for i in range(len(self.tokens))],
                "tenants": [self.tenants.token(i)
                            for i in range(len(self.tenants))],
                "device_types": [self.device_types.token(i)
                                 for i in range(len(self.device_types))],
                "channel_names": [self.channel_map.names.token(i)
                                  for i in range(len(self.channel_map.names))],
                "alert_types": [self.alert_types.token(i)
                                for i in range(len(self.alert_types))],
                "areas": [self.areas.token(i) for i in range(len(self.areas))],
                "customers": [self.customers.token(i)
                              for i in range(len(self.customers))],
                "assets": [self.assets.token(i)
                           for i in range(len(self.assets))],
                "event_ids": [self.event_ids.token(i)
                              for i in range(len(self.event_ids))],
                "token_device": {str(k): v for k, v in self.token_device.items()},
                "devices": {str(d): dataclasses.asdict(i)
                            for d, i in self.devices.items()},
                "assignments": {str(a): dataclasses.asdict(i)
                                for a, i in self.assignments.items()},
                "device_slots": {str(k): v
                                 for k, v in self.device_slots.items()},
                "dead_letters": self.dead_letters[-4096:],
            }
            (directory / "host_distributed.json").write_text(json.dumps(host))
            if self.wal is not None:
                self.wal.append_watermark(cursor)
                self.wal.sync()
            manifest["store_cursor"] = cursor
            return manifest


def encode_event_id(pos: int, shard: int, arena: int, n_shards: int,
                    arenas: int) -> int:
    """Mesh-global event id: ``pos * (n_shards*arenas) + shard*arenas +
    arena``. The single place the id layout lives — get_event and
    DistributedFeedConsumer.commit decode with :func:`split_event_id`."""
    return pos * (n_shards * arenas) + shard * arenas + arena


def split_event_id(event_id: int, n_shards: int,
                   arenas: int) -> tuple[int, int, int]:
    """Inverse of :func:`encode_event_id` -> (pos, shard, arena)."""
    parts = n_shards * arenas
    part = event_id % parts
    return event_id // parts, part // arenas, part % arenas


class DistributedFeedConsumer:
    """Outbound consumer group over the mesh engine's per-shard rings —
    the per-partition consumer-group analog (one committed offset per
    (shard, arena) sub-ring). Event ids encode (position, shard, arena)
    via :func:`encode_event_id` so commits are exact and ids stay unique
    across the mesh."""

    def __init__(self, engine: DistributedEngine, group_id: str,
                 max_batch: int = 1024, start_from_latest: bool = False):
        self.engine = engine
        self.group_id = group_id
        self.max_batch = max_batch
        store = engine.state.store
        self.n_shards = engine.n_shards
        self.arenas = store.cursor.shape[-1]
        self.offsets = np.zeros((self.n_shards, self.arenas), np.int64)
        if start_from_latest:
            self.offsets[:] = self._heads(store)
        self.lag_lost = 0

    def _heads(self, store) -> np.ndarray:
        acap = self.engine.config.store_capacity_per_shard // self.arenas
        ep = np.asarray(jax.device_get(store.epoch)).astype(np.int64)
        cu = np.asarray(jax.device_get(store.cursor)).astype(np.int64)
        return ep * acap + cu

    def _events_from_slice(self, sl, base: int, count: int, s: int, a: int,
                           lane_names: dict[int, str]) -> list:
        """Host-enrich one contiguous column slice (ring readback or
        archived segment — both carry the ring column layout)."""
        from sitewhere_tpu.outbound.feed import OutboundEvent

        eng = self.engine
        out = []
        for i in range(count):
            if not bool(sl.valid[i]):
                continue
            gdid = eng._gdid(s, int(sl.device[i]))
            info = eng.devices.get(gdid)
            et = EventType(int(sl.etype[i]))
            meas = {}
            lat = lon = None
            if et is EventType.MEASUREMENT:
                for ch in np.nonzero(np.asarray(sl.vmask[i]))[0]:
                    meas[lane_names.get(int(ch), f"ch{ch}")] = float(
                        sl.values[i, ch])
            elif et is EventType.LOCATION and bool(sl.vmask[i, 0]):
                lat = float(sl.values[i, 0])
                lon = float(sl.values[i, 1])
            out.append(OutboundEvent(
                latitude=lat,
                longitude=lon,
                event_id=encode_event_id(
                    base + i, s, a, self.n_shards, self.arenas),
                etype=et,
                device_token=info.token if info else f"#{gdid}",
                device_id=gdid,
                assignment_id=eng._gdid(s, int(sl.assignment[i])),
                tenant=(eng.tenants.token(int(sl.tenant[i]))
                        if int(sl.tenant[i]) != NULL_ID else "default"),
                area_id=int(sl.area[i]),
                customer_id=int(sl.customer[i]),
                asset_id=int(sl.asset[i]),
                ts_ms=int(sl.ts_ms[i]),
                received_ms=int(sl.received_ms[i]),
                measurements=meas,
                values=[float(v) for v in sl.values[i]],
                aux0=int(sl.aux[i, 0]),
                aux1=int(sl.aux[i, 1]),
            ))
        return out

    def poll(self) -> list:
        # whole-poll engine lock: stacked state is donated through every
        # step, so store references captured outside the lock die under a
        # concurrent flush, and a wrapped ring would serve new rows under
        # old positions (see outbound/feed.py:poll)
        with self.engine.lock:
            if self.engine._pending_outs:
                self.engine.drain()
            return self._poll_locked()

    def _poll_locked(self) -> list:
        """Poll body; caller MUST hold the engine lock (protects the
        donated stacked store AND the archive index)."""
        from sitewhere_tpu.ops.readback import read_range
        from sitewhere_tpu.outbound.feed import OutboundEvent

        store = self.engine.state.store
        acap = self.engine.config.store_capacity_per_shard // self.arenas
        heads = self._heads(store)
        out: list[OutboundEvent] = []
        eng = self.engine
        archive = getattr(eng, "archive", None)
        lane_names: dict[int, str] = {}
        for name, nid in eng.channel_map.names.items():
            lane_names.setdefault(nid % eng.config.channels, name)
        for s in range(self.n_shards):
            shard_store = None
            for a in range(self.arenas):
                head = int(heads[s, a])
                if head <= self.offsets[s, a]:
                    continue
                # a lagging consumer REPLAYS evicted rows from its archive
                # partition (Kafka-consumer at-least-once: falling behind
                # means reading older log segments, not losing events).
                # Replay does NOT advance committed offsets — redelivery
                # until commit(); only unrecoverable gaps advance + count
                # as lag_lost, and replay resumes at the next segment
                oldest = max(0, head - acap)
                budget = self.max_batch
                part = s * self.arenas + a
                if archive is None and self.offsets[s, a] < oldest:
                    self.lag_lost += oldest - int(self.offsets[s, a])
                    self.offsets[s, a] = oldest
                pos = int(self.offsets[s, a])
                while archive is not None and pos < oldest and budget > 0:
                    sl, n = archive.read_rows(
                        part, pos, min(oldest - pos, budget))
                    if n == 0:
                        # gap skip only when nothing replayed-but-
                        # uncommitted precedes it (else a pre-commit
                        # crash would drop those events)
                        if pos != int(self.offsets[s, a]):
                            break   # deliver pre-gap events first
                        nxt = archive.next_start(part, pos)
                        nxt = oldest if nxt is None else min(nxt, oldest)
                        # registered gaps (migration padding) never held
                        # data — skipping them is not loss
                        self.lag_lost += max(
                            0, nxt - pos - archive.gap_rows(part, pos, nxt))
                        self.offsets[s, a] = nxt
                        pos = nxt
                        continue
                    out.extend(self._events_from_slice(
                        sl, pos, n, s, a, lane_names))
                    pos += n
                    budget -= n
                if pos < oldest:
                    continue   # batch full mid-replay; resumes next poll
                count = min(head - pos, budget)
                if count <= 0:
                    continue
                if shard_store is None:
                    shard_store = jax.tree_util.tree_map(
                        lambda x, _s=s: x[_s], store)
                sl = jax.device_get(read_range(
                    shard_store, jnp.int32(pos % acap), count, arena=a))
                out.extend(self._events_from_slice(
                    sl, pos, count, s, a, lane_names))
        return out

    def commit(self, events: list) -> None:
        for ev in events:
            pos, s, a = split_event_id(ev.event_id, self.n_shards,
                                       self.arenas)
            self.offsets[s, a] = max(self.offsets[s, a], pos + 1)


def restore_distributed(directory) -> DistributedEngine:
    """Reconstruct a DistributedEngine from a snapshot directory (same
    shard count; use :func:`reshard_snapshot` to change it first)."""
    import json
    import pathlib

    directory = pathlib.Path(directory)
    host = json.loads((directory / "host_distributed.json").read_text())
    config = DistributedConfig(**host["config"])
    config.n_shards = host["n_shards"]
    eng = DistributedEngine(config)
    eng.sharded.restore(directory)
    eng.epoch = EpochBase(host["epoch_base_unix_s"])
    eng._next_device = np.asarray(host["next_device"], np.int64)
    eng._next_assignment = np.asarray(host["next_assignment"], np.int64)
    for tok in host["tokens"]:
        eng.tokens.intern(tok)
    for t in host["tenants"]:
        eng.tenants.intern(t)
    for t in host["device_types"]:
        eng.device_types.intern(t)
    for n in host["channel_names"]:
        eng.channel_map.names.intern(n)
    for a in host["alert_types"]:
        eng.alert_types.intern(a)
    for a in host["areas"]:
        eng.areas.intern(a)
    for cst in host["customers"]:
        eng.customers.intern(cst)
    for a in host["assets"]:
        eng.assets.intern(a)
    for e in host["event_ids"]:
        eng.event_ids.intern(e)
    eng.token_device = {int(k): v for k, v in host["token_device"].items()}
    eng.devices = {int(k): DeviceInfo(**v)
                   for k, v in host["devices"].items()}
    eng.assignments = {int(k): AssignmentInfo(**v)
                       for k, v in host["assignments"].items()}
    eng.assignment_tokens = {i.token: a for a, i in eng.assignments.items()}
    eng.device_slots = {int(k): list(v)
                        for k, v in host["device_slots"].items()}
    eng.dead_letters = list(host["dead_letters"])
    # conservation ledger (ISSUE 14): rebase over the restored device
    # counters BEFORE any WAL replay (engine.py restore_engine parity)
    eng.ledger.rebase(eng)
    return eng


def recover_distributed(snapshot_dir, wal_dir=None,
                        adopt_wal: bool = False) -> DistributedEngine:
    """Crash recovery for the mesh engine: restore the snapshot, replay the
    WAL tail past its watermark through the wire format that accepted each
    record (at-least-once; the sharded state merge is timestamp-idempotent
    like the single-node path). The replay mechanism is shared with
    recover_engine (utils/checkpoint.replay_wal_into).

    ``adopt_wal=True``: when the snapshot itself carries no WAL (migrated
    or resharded manifests set wal_dir=None), the engine ADOPTS ``wal_dir``
    as its live log after replaying it — the serving-rank boot path. The
    default keeps an explicitly named log READ-ONLY (a preserved recovery
    copy stays byte-identical)."""
    import json
    import pathlib

    from sitewhere_tpu.utils.checkpoint import replay_wal_into

    snapshot_dir = pathlib.Path(snapshot_dir)
    eng = restore_distributed(snapshot_dir)
    host = json.loads((snapshot_dir / "host_distributed.json").read_text())
    if wal_dir is None and eng.config.wal_dir is None:
        return eng
    if adopt_wal and eng.wal is None and wal_dir is not None:
        # the tail in wal_dir replays first, then new ingest journals
        # into the same log (replay never re-logs: replay_wal_into
        # detaches the live WAL while feeding records)
        from sitewhere_tpu.utils.ingestlog import IngestLog

        eng.config.wal_dir = str(wal_dir)
        eng.wal = IngestLog(wal_dir)
    replay_wal_into(eng, host["store_cursor"], wal_dir)
    return eng
