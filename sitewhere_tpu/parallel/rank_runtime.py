"""One-call rank bootstrap: ``run_rank(config)`` composes a whole rank.

The reference never hand-wires a service: its microservice framework
composes Kafka pipeline, gRPC server, tenant engines, and lifecycle in one
bootstrap (service-inbound-processing/.../InboundProcessingMicroservice.java:94-111
builds the full component graph; the k8s operator just runs it). Round-4's
cluster demo hand-wired ~10 pieces per rank instead — engine, cluster RPC
loop/thread, instance, REST, command service, search index, sweep loops —
and a partial wiring (no command service, no search index, shared RPC/REST
event loop) surfaced only at the first failing RPC. This module is that
framework bootstrap for the TPU build:

  * builds (or crash-recovers) the rank's DistributedEngine, wraps it in
    the ClusterEngine router, composes the full SiteWhereTpuInstance over
    it, and VALIDATES the wiring before serving — a missing command
    service, missing search index, or WAL-less durable rank fails at
    startup with a list of problems, not at the first cross-rank call;
  * serves the cluster RPC on its OWN event loop (deployment rule 1 in
    parallel/cluster.py — a shared loop deadlocks two fanning-out ranks),
    and the REST gateway + background pumps (outbound, rank-LOCAL
    presence sweep, analytics) + scheduler tick on a second loop;
  * exposes readiness at the public ``/api/instance/health`` route: the
    rank, peers, and component statuses appear there the moment the rank
    can serve (the reference's k8s readiness probe).

``spawn_cluster_demo`` and the cluster tests boot ranks through this
entry point, so the demo is configuration + ``run_rank``, nothing else.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import pathlib
import threading

from sitewhere_tpu.instance.instance import InstanceConfig, SiteWhereTpuInstance
from sitewhere_tpu.parallel.cluster import (ClusterConfig, ClusterEngine,
                                            build_cluster_rpc)
from sitewhere_tpu.parallel.distributed import recover_distributed

logger = logging.getLogger(__name__)


class RankWiringError(RuntimeError):
    """The composed rank is not a complete product node; raised at
    startup with every problem listed (fail fast, fail loud)."""


@dataclasses.dataclass
class RankConfig:
    """Everything one rank needs — the cluster topology plus the local
    serving surfaces."""

    cluster: ClusterConfig
    instance: InstanceConfig = dataclasses.field(default_factory=InstanceConfig)
    rest_host: str = "127.0.0.1"
    rest_port: int = 0                  # 0 = ephemeral
    rpc_host: str = "127.0.0.1"
    instance_rpc_port: int | None = None  # control-plane RPC (rpc/server.py)
    snapshot_dir: str | None = None     # recover from here when it exists
    presence_interval_s: float = 600.0
    analytics_interval_s: float = 5.0
    scheduler_tick_s: float = 1.0
    require_wal: bool = True            # a durable rank must journal ingest
    entity_log_dir: str | None = None   # entity-op journal; None derives
                                        # "<wal_dir>-entities"
    entity_sync_interval_s: float = 5.0  # anti-entropy pull period
    forward_dir: str | None = None      # cross-rank spill queue; None
                                        # derives "<wal_dir>-forward"
    forward_retry_interval_s: float = 0.5
    forward_retry_budget_s: float = 300.0
    # event-plane replication (RF>=2): each rank streams its WAL-durable
    # ingest to rf-1 followers; their standbys serve reads + schedule
    # fire-over while this rank is dead. 1 disables.
    replication_factor: int = 2
    replica_dir: str | None = None      # feed state (epoch); None derives
                                        # "<wal_dir>-replica"
    replica_heartbeat_s: float = 0.5
    replica_detect_s: float = 5.0       # feed-silence budget before a
                                        # follower declares the owner dead


class _LoopThread:
    """A dedicated event loop on a daemon thread (the cluster RPC and the
    REST gateway each get one — deployment rule 1)."""

    def __init__(self, name: str):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self.loop.run_forever,
                                       name=name, daemon=True)
        self.thread.start()

    def run(self, coro, timeout_s: float = 60.0):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(
            timeout_s)

    def close(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


def _validate_wiring(cfg: RankConfig, cluster: ClusterEngine,
                     inst: SiteWhereTpuInstance) -> None:
    problems = []
    if cluster.command_service is None:
        problems.append(
            "no command-delivery service attached: cross-rank "
            "invocations (Cluster.invokeCommand) would fail at the first "
            "routed command")
    if cluster.search_index is None:
        problems.append(
            "no event-search index attached: Cluster.searchEvents from "
            "peers would return None and every cluster-wide search "
            "would fail loudly")
    if cfg.require_wal and not cfg.cluster.engine.wal_dir:
        problems.append(
            "no WAL configured (cluster.engine.wal_dir): a crash loses "
            "every event since the last snapshot — set require_wal=False "
            "only for throwaway ranks")
    n = cfg.cluster.n_ranks
    if len(cfg.cluster.peers) != n:
        problems.append(
            f"peers list has {len(cfg.cluster.peers)} entries for "
            f"n_ranks={n}")
    if not 0 <= cfg.cluster.rank < n:
        problems.append(f"rank {cfg.cluster.rank} outside 0..{n - 1}")
    if problems:
        raise RankWiringError(
            "rank wiring incomplete:\n  - " + "\n  - ".join(problems))


class RankRuntime:
    """A running rank: engine + cluster RPC + REST + pumps + scheduler.
    ``stop()`` tears everything down in reverse order."""

    def __init__(self, cfg: RankConfig, cluster: ClusterEngine,
                 inst: SiteWhereTpuInstance, recovered: bool,
                 replicator=None):
        self.cfg = cfg
        self.cluster = cluster
        self.instance = inst
        self.recovered = recovered
        self.replicator = replicator
        self.rank = cfg.cluster.rank
        self.rest_port: int | None = None
        self.instance_rpc_port: int | None = None
        self._rpc_loop: _LoopThread | None = None
        self._main_loop: _LoopThread | None = None
        self._cluster_srv = None
        self._instance_srv = None
        self._server_handle = None
        self._bg_tasks: list = []
        self._stopped = False

    # -- composed by run_rank ---------------------------------------------
    def _serve(self) -> None:
        cfg = self.cfg
        secret = cfg.cluster.secret
        rpc_port = int(cfg.cluster.peers[self.rank].rsplit(":", 1)[1])

        # 1) cluster data-plane RPC on its OWN loop: handlers bind to the
        # local engine only, so this loop can always answer a peer even
        # while the REST loop blocks inside a fan-out (rule 1)
        self._rpc_loop = _LoopThread(f"rank{self.rank}-cluster-rpc")
        self._cluster_srv = build_cluster_rpc(self.cluster.local, secret)
        if self.replicator is not None:
            # the entity-replication surface rides the same
            # authenticated cluster RPC server
            self.replicator.register_rpc(self._cluster_srv)
        if self.cluster.replica_applier is not None:
            from sitewhere_tpu.parallel.replication import (
                register_replication_rpc)

            register_replication_rpc(self._cluster_srv,
                                     self.cluster.replica_applier)
        self._rpc_loop.run(
            self._cluster_srv.start(host=cfg.rpc_host, port=rpc_port))

        # 2) optional instance control-plane RPC (all 9 API families)
        if cfg.instance_rpc_port is not None:
            from sitewhere_tpu.rpc.server import build_instance_rpc

            self._instance_srv = build_instance_rpc(self.instance)
            self._rpc_loop.run(self._instance_srv.start(
                host=cfg.rpc_host, port=cfg.instance_rpc_port))
            self.instance_rpc_port = self._instance_srv.port

        # 3) REST gateway + background pumps + scheduler on the serving
        # loop; instance lifecycle drives every child component
        from sitewhere_tpu.web.rest import start_server

        self._main_loop = _LoopThread(f"rank{self.rank}-serving")

        async def boot():
            await self.instance.initialize()
            await self.instance.start()
            handle = await start_server(
                self.instance, cfg.rest_host, cfg.rest_port,
                analytics_interval_s=cfg.analytics_interval_s,
                presence_interval_s=cfg.presence_interval_s)
            self.instance.scheduler.tick_s = cfg.scheduler_tick_s
            await self.instance.scheduler.start()
            if self.replicator is not None and cfg.cluster.n_ranks > 1:
                rep = self.replicator

                async def entity_sync_loop():
                    # pull-based anti-entropy: catches up everything this
                    # rank missed while down (pushes it never saw) and
                    # the initial cold-start backlog, without blocking
                    # startup on unreachable peers
                    while True:
                        try:
                            await asyncio.to_thread(rep.sync_from_peers,
                                                    True)
                            # the pull refreshed every peer's receipt
                            # vector — the safe horizon tombstone GC
                            # needs (never resurrects: see gc_tombstones)
                            await asyncio.to_thread(rep.gc_tombstones)
                        except Exception:
                            logger.exception("entity anti-entropy failed")
                        await asyncio.sleep(cfg.entity_sync_interval_s)

                self._bg_tasks.append(
                    asyncio.create_task(entity_sync_loop()))
            return handle

        self._server_handle = self._main_loop.run(boot())
        self.rest_port = self._server_handle.port
        if self.cluster.forward_queue is not None:
            self.cluster.forward_queue.start()   # background redelivery
        if self.cluster.replica_feed is not None:
            self.cluster.replica_feed.start()    # follower streaming
        # readiness surfaces on the public health route
        self.instance.health_extra = {
            "rank": self.rank,
            "nRanks": cfg.cluster.n_ranks,
            "peers": list(cfg.cluster.peers),
            "recovered": self.recovered,
            "restPort": self.rest_port,
            "clusterRpcPort": rpc_port,
            "ready": True,
        }

    def pump_outbound(self) -> int:
        """Drive one outbound pump synchronously (tests/demos; the
        background pump loop does this continuously)."""
        return self._main_loop.run(self.instance.pump_outbound())

    def run_on_serving_loop(self, coro, timeout_s: float = 60.0):
        return self._main_loop.run(coro, timeout_s)

    def hard_kill(self) -> None:
        """Simulated SIGKILL for chaos tests: sever every serving socket
        and background thread WITHOUT flushing, saving, or closing the
        engine — on-disk state is left exactly as a real kill would
        (whatever the WAL fsync'd). The process-local python objects are
        abandoned; recovery is ``run_rank`` over the same dirs."""
        self._stopped = True
        if self.cluster.replica_feed is not None:
            self.cluster.replica_feed.stop()
        if self.cluster.forward_queue is not None:
            self.cluster.forward_queue.stop()
        if self._rpc_loop is not None:
            for srv in (self._instance_srv, self._cluster_srv):
                if srv is not None:
                    try:
                        self._rpc_loop.run(srv.stop(), 10.0)
                    except Exception:
                        pass
            self._rpc_loop.close()
        if self._main_loop is not None:
            self._main_loop.close()
        self.cluster.close()

    def stop(self, timeout_s: float = 30.0) -> None:
        if self._stopped:
            return
        self._stopped = True
        if self._main_loop is not None:
            async def teardown():
                for task in self._bg_tasks:
                    task.cancel()
                for task in self._bg_tasks:
                    try:
                        await task
                    except (asyncio.CancelledError, Exception):
                        pass
                await self.instance.scheduler.stop()
                if self._server_handle is not None:
                    await self._server_handle.cleanup()
                await self.instance.stop()

            try:
                self._main_loop.run(teardown(), timeout_s)
            finally:
                self._main_loop.close()
        if self._rpc_loop is not None:
            try:
                for srv in (self._instance_srv, self._cluster_srv):
                    if srv is not None:
                        self._rpc_loop.run(srv.stop(), timeout_s)
            finally:
                self._rpc_loop.close()
        if self.replicator is not None:
            self.replicator.close()
        if self.cluster.replica_feed is not None:
            self.cluster.replica_feed.stop()
        if self.cluster.replica_applier is not None:
            self.cluster.replica_applier.close()
        if self.cluster.forward_queue is not None:
            self.cluster.forward_queue.stop()
        reg = getattr(self.cluster.local, "spill_registry", None)
        if reg is not None:
            reg.close()
        self.cluster.close()


def run_rank(cfg: RankConfig) -> RankRuntime:
    """Compose and serve one rank. Crash-recovers from
    ``cfg.snapshot_dir`` + the WAL when a snapshot exists there;
    validates the wiring BEFORE serving; returns a running
    ``RankRuntime``."""
    local = None
    recovered = False
    if cfg.snapshot_dir is not None and (
            pathlib.Path(cfg.snapshot_dir) /
            "sharded_manifest.json").exists():
        # adopt_wal: a serving rank must journal new ingest even when the
        # snapshot (migrated/resharded) carries no wal_dir of its own
        local = recover_distributed(cfg.snapshot_dir,
                                    cfg.cluster.engine.wal_dir,
                                    adopt_wal=True)
        recovered = True
    elif cfg.cluster.engine.wal_dir and sorted(
            pathlib.Path(cfg.cluster.engine.wal_dir).glob("segment-*.log")
            if pathlib.Path(cfg.cluster.engine.wal_dir).exists() else []):
        # no snapshot but a WAL from a previous life: cold recovery is
        # replay-from-empty (recover_distributed handles snapshot=None
        # via the WAL alone only when given a snapshot dir; here the
        # fresh engine replays because DistributedEngine re-opens the
        # WAL and the caller migrates explicitly). Flag it rather than
        # silently double-logging history into the live WAL.
        logger.warning(
            "rank %d: WAL %s exists but no snapshot at %s — starting "
            "FRESH over the existing log (records are preserved; run "
            "recovery explicitly to replay them)", cfg.cluster.rank,
            cfg.cluster.engine.wal_dir, cfg.snapshot_dir)
    cluster = None
    replicator = None
    try:
        cluster = ClusterEngine(cfg.cluster, local=local)
        inst = SiteWhereTpuInstance(cfg.instance, engine=cluster)
        _validate_wiring(cfg, cluster, inst)
        from sitewhere_tpu.parallel.entity_sync import EntityReplicator

        elog = cfg.entity_log_dir
        if elog is None and cfg.cluster.engine.wal_dir:
            wd = pathlib.Path(cfg.cluster.engine.wal_dir)
            elog = str(wd.with_name(wd.name + "-entities"))
        replicator = EntityReplicator(cluster, inst, log_dir=elog)
        replicator.attach()   # replays the journal (SIGKILL recovery)
        if cfg.cluster.n_ranks > 1:
            from sitewhere_tpu.parallel.forward import (ForwardQueue,
                                                        SpillRegistry)

            fdir = cfg.forward_dir
            if fdir is None and cfg.cluster.engine.wal_dir:
                wd = pathlib.Path(cfg.cluster.engine.wal_dir)
                fdir = str(wd.with_name(wd.name + "-forward"))
            if fdir is not None:
                cluster.attach_forwarding(
                    ForwardQueue(
                        cluster, fdir,
                        retry_interval_s=cfg.forward_retry_interval_s,
                        retry_budget_s=cfg.forward_retry_budget_s),
                    SpillRegistry(pathlib.Path(fdir) / "registry"))
        if cfg.cluster.n_ranks > 1 and cfg.replication_factor > 1:
            rdir = cfg.replica_dir
            if rdir is None and cfg.cluster.engine.wal_dir:
                wd = pathlib.Path(cfg.cluster.engine.wal_dir)
                rdir = str(wd.with_name(wd.name + "-replica"))
            if rdir is None:
                logger.warning(
                    "rank %d: replication_factor=%d requested but no WAL/"
                    "replica dir — event-plane replication disabled "
                    "(the feed ships WAL-durable batches; a WAL-less "
                    "rank has nothing durable to ship)",
                    cfg.cluster.rank, cfg.replication_factor)
            else:
                from sitewhere_tpu.parallel.replication import (
                    ReplicaApplier, ReplicaFeed, install_fireover)

                feed = ReplicaFeed(cluster, rdir,
                                   rf=cfg.replication_factor,
                                   heartbeat_s=cfg.replica_heartbeat_s)
                applier = ReplicaApplier(cluster,
                                         rf=cfg.replication_factor,
                                         detect_s=cfg.replica_detect_s)
                cluster.attach_replication(feed, applier)
                # a fenced leader pulls entity state (follower-updated
                # schedule fired marks) before resuming its own firing
                rep = replicator
                feed.on_fenced = lambda: rep.sync_from_peers(True)
                install_fireover(inst.scheduler, cluster)
    except Exception:
        # fail-fast must not leak the constructed engine or journals: a
        # supervisor retrying run_rank in-process would otherwise
        # accumulate open segment handles on every attempt
        if replicator is not None:
            replicator.close()
        eng = cluster.local if cluster is not None else local
        if cluster is not None:
            cluster.close()
        if eng is not None and getattr(eng, "wal", None) is not None:
            eng.wal.close()
        raise
    rt = RankRuntime(cfg, cluster, inst, recovered, replicator=replicator)
    try:
        rt._serve()
    except Exception:
        rt.stop()
        raise
    logger.info("rank %d serving: REST :%s, cluster RPC %s",
                cfg.cluster.rank, rt.rest_port,
                cfg.cluster.peers[cfg.cluster.rank])
    return rt
