"""Tenant management + dataset bootstrap.

The reference's instance-management bootstraps from k8s CRDs: it reads a
``SiteWhereInstance`` + ``InstanceDatasetTemplate`` and runs Groovy dataset
initializers with bootstrap-state tracking in the CRD status
(InstanceBootstrapper.java:79-175); tenants are CRDs spawning per-service
tenant engines. Here tenants are rows in the (natively multi-tenant) engine:
the tenant lane isolates pipelines/state, and dataset templates are Python
callables seeding a tenant with types/areas/users — same capability, flags/
JSON config plane instead of ZooKeeper/CRDs (SURVEY.md §5.6).
"""

from __future__ import annotations

import dataclasses
import secrets
import time
from typing import Callable

from sitewhere_tpu.management.entities import EntityMeta, EntityStore


@dataclasses.dataclass
class Tenant:
    meta: EntityMeta
    name: str
    auth_token: str
    authorized_users: list[str]
    dataset_template: str = "empty"
    bootstrap_state: str = "NotBootstrapped"  # -> Bootstrapping -> Bootstrapped/Failed
    logo_url: str = ""


DatasetTemplate = Callable[["TenantManagement", Tenant], None]


def empty_dataset(tm: "TenantManagement", tenant: Tenant) -> None:
    """No seed data (reference: the 'empty' InstanceDatasetTemplate)."""


def construction_dataset(tm: "TenantManagement", tenant: Tenant) -> None:
    """Seed dataset modeled on the reference's 'construction' demo template:
    device types, an area hierarchy, and a customer."""
    dm = tm.device_management
    if dm is None:
        return
    t = tenant.meta.token
    for token, name in ((f"{t}-excavator", "Excavator"),
                        (f"{t}-crane", "Tower Crane"),
                        (f"{t}-tracker", "Asset Tracker")):
        if token not in dm.device_types:
            dm.create_device_type(token, name)
    if f"{t}-region" not in dm.area_types:
        dm.create_area_type(f"{t}-region", "Region",
                            contained_area_types=[f"{t}-site"])
        dm.create_area_type(f"{t}-site", "Construction Site")
        dm.create_area(f"{t}-southeast", f"{t}-region", "Southeast")
        dm.create_area(f"{t}-peachtree", f"{t}-site", "Peachtree site",
                       parent_token=f"{t}-southeast")
    if f"{t}-org" not in dm.customer_types:
        dm.create_customer_type(f"{t}-org", "Organization")
        dm.create_customer(f"{t}-acme", f"{t}-org", "ACME Construction")


BUILTIN_DATASETS: dict[str, DatasetTemplate] = {
    "empty": empty_dataset,
    "construction": construction_dataset,
}

# tenant configuration templates (reference: Tenants.java
# /templates/configuration backed by TenantConfigurationTemplate CRDs) —
# canned component-graph configs a new tenant can start from, in the
# config.py apply_tenant_config schema
CONFIG_TEMPLATES: list[dict] = [
    {
        "id": "default",
        "name": "Default configuration",
        "description": "In-memory event source with JSON decoder and "
                       "local command delivery.",
        "configuration": {
            "eventSources": [
                {"id": "default-in", "type": "inmemory",
                 "decoder": {"type": "json"},
                 "deduplicator": {"type": "alternate-id"}},
            ],
            "commandRouting": {
                "router": {"type": "single-choice",
                           "destination": "default-local"},
                "destinations": [
                    {"id": "default-local", "type": "local",
                     "encoder": {"type": "json"}},
                ],
            },
        },
    },
    {
        "id": "mqtt",
        "name": "MQTT configuration",
        "description": "MQTT event source (JSON decoder) with MQTT "
                       "command delivery.",
        "configuration": {
            "eventSources": [
                {"id": "mqtt-in", "type": "mqtt",
                 "decoder": {"type": "json"},
                 "configuration": {"host": "127.0.0.1", "port": 1883,
                                   "topic": "sitewhere/input/#"}},
            ],
            "commandRouting": {
                "router": {"type": "single-choice",
                           "destination": "mqtt-out"},
                "destinations": [
                    {"id": "mqtt-out", "type": "mqtt",
                     "encoder": {"type": "json"},
                     "configuration": {"host": "127.0.0.1", "port": 1883}},
                ],
            },
        },
    },
]


class TenantManagement:
    """Tenant CRUD + bootstrap orchestration."""

    def __init__(self, engine, device_management=None):
        self.engine = engine
        self.device_management = device_management
        self.tenants: EntityStore[Tenant] = EntityStore("tenant")
        self.datasets = dict(BUILTIN_DATASETS)

    def create_tenant(self, token: str, name: str,
                      authorized_users: list[str] | None = None,
                      dataset_template: str = "empty",
                      auth_token: str | None = None) -> Tenant:
        if dataset_template not in self.datasets:
            raise ValueError(f"unknown dataset template {dataset_template!r}")
        tenant = self.tenants.create(
            token,
            lambda m: Tenant(
                meta=m, name=name,
                auth_token=auth_token or secrets.token_urlsafe(16),
                authorized_users=authorized_users or [],
                dataset_template=dataset_template,
            ),
        )
        # register the tenant lane in the engine interner
        self.engine.tenants.intern(token)
        self.bootstrap(tenant)
        return tenant

    def bootstrap(self, tenant: Tenant) -> None:
        """Run the dataset initializer with bootstrap-state tracking
        (InstanceBootstrapper.java:87-104 semantics)."""
        tenant.bootstrap_state = "Bootstrapping"
        try:
            self.datasets[tenant.dataset_template](self, tenant)
            tenant.bootstrap_state = "Bootstrapped"
        except Exception:
            tenant.bootstrap_state = "Failed"
            raise
        finally:
            # the state above mutated the entity directly; a no-op store
            # update stamps updated_ms and fires on_change so replicas
            # see the FINAL bootstrap state, not the created default
            self.tenants.update(tenant.meta.token, lambda t: None)

    def authorize_user(self, tenant_token: str, username: str) -> Tenant:
        def apply(t: Tenant) -> None:
            if username not in t.authorized_users:
                t.authorized_users.append(username)

        return self.tenants.update(tenant_token, apply)

    def user_can_access(self, tenant_token: str, username: str,
                        is_admin: bool) -> bool:
        tenant = self.tenants.try_get(tenant_token)
        if tenant is None:
            return False
        return is_admin or not tenant.authorized_users or (
            username in tenant.authorized_users
        )
