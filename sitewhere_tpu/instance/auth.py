"""Users, roles, passwords, and JWT — the instance-management auth stack.

The reference delegates users/roles to Apache Syncope with retry-wrapped
connections (SyncopeUserManagement.java:83-119) and mints JWTs in
web/auth/controllers/JwtService.java:35-66 (basic-auth -> JWT flow via
BasicAuthForJwt + JwtAuthForApi filters). Here users are first-class:
PBKDF2-SHA256 password hashing, role-based granted authorities, and a
dependency-free HS256 JWT implementation with expiry + claims.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import json
import os
import threading
import time


# --- JWT (HS256) -------------------------------------------------------------


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class JwtError(Exception):
    pass


class JwtService:
    """Mint + verify HS256 JWTs (JwtService.java analog)."""

    def __init__(self, secret: bytes | None = None,
                 expiration_s: int = 60 * 60 * 24, issuer: str = "sitewhere-tpu"):
        self.secret = secret if secret is not None else os.urandom(32)
        self.expiration_s = expiration_s
        self.issuer = issuer

    def generate(self, username: str, authorities: list[str],
                 tenant: str | None = None) -> str:
        now = int(time.time())
        payload = {
            "sub": username,
            "auth": authorities,
            "iss": self.issuer,
            "iat": now,
            "exp": now + self.expiration_s,
        }
        if tenant is not None:
            payload["tenant"] = tenant
        header = {"alg": "HS256", "typ": "JWT"}
        signing_input = f"{_b64url(json.dumps(header).encode())}.{_b64url(json.dumps(payload).encode())}"
        sig = hmac.new(self.secret, signing_input.encode(), hashlib.sha256).digest()
        return f"{signing_input}.{_b64url(sig)}"

    def validate(self, token: str) -> dict:
        try:
            header_b64, payload_b64, sig_b64 = token.split(".")
        except ValueError as e:
            raise JwtError("malformed token") from e
        signing_input = f"{header_b64}.{payload_b64}".encode()
        expect = hmac.new(self.secret, signing_input, hashlib.sha256).digest()
        try:
            sig = _b64url_decode(sig_b64)
        except (ValueError, TypeError) as e:
            raise JwtError("malformed signature") from e
        if not hmac.compare_digest(expect, sig):
            raise JwtError("invalid signature")
        try:
            header = json.loads(_b64url_decode(header_b64))
            payload = json.loads(_b64url_decode(payload_b64))
        except (ValueError, UnicodeDecodeError) as e:
            raise JwtError("malformed claims") from e
        if header.get("alg") != "HS256":
            raise JwtError(f"unsupported algorithm {header.get('alg')!r}")
        if payload.get("exp", 0) < time.time():
            raise JwtError("token expired")
        return payload


# --- passwords ---------------------------------------------------------------


def hash_password(password: str, iterations: int = 100_000) -> str:
    salt = os.urandom(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, iterations)
    return f"pbkdf2${iterations}${_b64url(salt)}${_b64url(dk)}"


def verify_password(password: str, stored: str) -> bool:
    try:
        _, iters_s, salt_b64, dk_b64 = stored.split("$")
        salt = _b64url_decode(salt_b64)
        expect = _b64url_decode(dk_b64)
        dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, int(iters_s))
        return hmac.compare_digest(dk, expect)
    except (ValueError, TypeError):
        return False


# --- users + roles -----------------------------------------------------------

# granted-authority constants mirroring the reference's authority catalog
AUTH_ADMIN = "GRP_ACCESS"
AUTH_ADMINISTER_USERS = "ADMINISTER_USERS"
AUTH_ADMINISTER_TENANTS = "ADMINISTER_TENANTS"
AUTH_VIEW_INFORMATION = "VIEW_SERVER_INFORMATION"

DEFAULT_ROLES = {
    "admin": [AUTH_ADMIN, AUTH_ADMINISTER_USERS, AUTH_ADMINISTER_TENANTS,
              AUTH_VIEW_INFORMATION],
    "user": [AUTH_VIEW_INFORMATION],
}


@dataclasses.dataclass
class User:
    username: str
    hashed_password: str
    first_name: str = ""
    last_name: str = ""
    email: str = ""
    roles: list[str] = dataclasses.field(default_factory=lambda: ["user"])
    enabled: bool = True
    created_ms: float = 0.0
    last_login_ms: float | None = None


class AuthenticationError(Exception):
    pass


class UserManagement:
    """User CRUD + authentication (SyncopeUserManagement capability,
    embedded). Role -> authority expansion mirrors the reference's granted-
    authority model."""

    def __init__(self):
        self._lock = threading.Lock()
        self.users: dict[str, User] = {}
        self.roles: dict[str, list[str]] = dict(DEFAULT_ROLES)
        # fires ("upsert"|"delete", "user"|"role", key, obj) after each
        # mutation, outside the lock — the cluster replicator's tap.
        # Ships the User with its HASHED password only (state-based
        # replication never journals or transmits a plaintext password).
        self.on_change = None

    def _notify(self, action: str, kind: str, key: str, obj) -> None:
        cb = self.on_change
        if cb is not None:
            cb(action, kind, key, obj)

    def create_user(self, username: str, password: str, roles: list[str] | None = None,
                    **kw) -> User:
        with self._lock:
            if username in self.users:
                raise ValueError(f"user {username!r} already exists")
            for role in roles or ["user"]:
                if role not in self.roles:
                    raise ValueError(f"unknown role {role!r}")
            user = User(username=username, hashed_password=hash_password(password),
                        roles=roles or ["user"], created_ms=time.time() * 1000, **kw)
            self.users[username] = user
        self._notify("upsert", "user", username, user)
        return user

    def authenticate(self, username: str, password: str) -> User:
        user = self.users.get(username)
        if user is None or not user.enabled:
            raise AuthenticationError("unknown or disabled user")
        if not verify_password(password, user.hashed_password):
            raise AuthenticationError("bad credentials")
        user.last_login_ms = time.time() * 1000
        return user

    def authorities_for(self, user: User) -> list[str]:
        out: list[str] = []
        for role in user.roles:
            for auth in self.roles.get(role, []):
                if auth not in out:
                    out.append(auth)
        return out

    def update_user(self, username: str, password: str | None = None,
                    roles: list[str] | None = None, enabled: bool | None = None,
                    **kw) -> User:
        with self._lock:
            user = self.users.get(username)
            if user is None:
                raise KeyError(f"user {username!r} not found")
            if password is not None:
                user.hashed_password = hash_password(password)
            if roles is not None:
                unknown = [r for r in roles if r not in self.roles]
                if unknown:
                    raise ValueError(f"unknown roles: {unknown}")
                user.roles = roles
            if enabled is not None:
                user.enabled = enabled
            for k, v in kw.items():
                setattr(user, k, v)
        self._notify("upsert", "user", username, user)
        return user

    def add_roles(self, username: str, roles: list[str]) -> User:
        """Append roles (reference: Users.java @PUT /{username}/roles ->
        SyncopeUserManagement.addRoles)."""
        with self._lock:
            user = self.users.get(username)
            if user is None:
                raise KeyError(f"user {username!r} not found")
            unknown = [r for r in roles if r not in self.roles]
            if unknown:
                raise ValueError(f"unknown roles: {unknown}")
            for r in roles:
                if r not in user.roles:
                    user.roles.append(r)
        self._notify("upsert", "user", username, user)
        return user

    def remove_roles(self, username: str, roles: list[str]) -> User:
        """Remove roles (reference: Users.java @DELETE /{username}/roles)."""
        with self._lock:
            user = self.users.get(username)
            if user is None:
                raise KeyError(f"user {username!r} not found")
            user.roles = [r for r in user.roles if r not in set(roles)]
        self._notify("upsert", "user", username, user)
        return user

    def delete_user(self, username: str) -> bool:
        with self._lock:
            existed = self.users.pop(username, None) is not None
        if existed:
            self._notify("delete", "user", username, None)
        return existed

    def create_role(self, role: str, authorities: list[str]) -> None:
        with self._lock:
            self.roles[role] = list(authorities)
        self._notify("upsert", "role", role, list(authorities))

    # ---- replication surface (no hook: peers must not re-broadcast) ----
    def apply_replicated_user(self, username: str, user: "User | None") -> None:
        with self._lock:
            if user is None:
                self.users.pop(username, None)
            else:
                self.users[username] = user

    def apply_replicated_role(self, role: str,
                              authorities: "list[str] | None") -> None:
        with self._lock:
            if authorities is None:
                self.roles.pop(role, None)
            else:
                self.roles[role] = list(authorities)
