"""SiteWhereTpuInstance: the composition root — one object wiring every
service the reference deploys as 15 microservices (SURVEY.md §2 inventory):
engine (ingest pipeline + device state + event store), device/asset
management, command delivery, outbound connectors, batch operations,
scheduling, labels, streams, event search, users/tenants/JWT, and the REST
gateway (web/rest.py). The reference's per-service k8s topology collapses
into one TPU-resident engine plus host services sharing it.
"""

from __future__ import annotations

import dataclasses

from sitewhere_tpu.commands.routing import CommandRegistry, SingleChoiceCommandRouter
from sitewhere_tpu.commands.service import CommandDeliveryService
from sitewhere_tpu.connectors.base import ConnectorHost, OutboundConnector
from sitewhere_tpu.connectors.impl import SearchIndexConnector
from sitewhere_tpu.engine import Engine, EngineConfig
from sitewhere_tpu.ingest.sources import EventSourcesManager, InboundEventSource
from sitewhere_tpu.ingest.wire_edge import WireEdge, WireEdgeConfig
from sitewhere_tpu.instance.auth import JwtService, UserManagement
from sitewhere_tpu.instance.tenants import TenantManagement
from sitewhere_tpu.labels.manager import LabelGeneratorManager
from sitewhere_tpu.management.assets import AssetManagement
from sitewhere_tpu.management.batch import (
    BatchCommandInvocationHandler,
    BatchOperationManager,
)
from sitewhere_tpu.management.device_management import DeviceManagement
from sitewhere_tpu.management.schedule import (
    ScheduleManager,
    batch_command_by_criteria_executor,
    command_invocation_executor,
)
from sitewhere_tpu.management.streams import DeviceStreamManager
from sitewhere_tpu.search.index import EventSearchIndex, SearchProviderManager
from sitewhere_tpu.utils.lifecycle import LifecycleComponent


@dataclasses.dataclass
class InstanceConfig:
    instance_id: str = "sitewhere-tpu"
    engine: EngineConfig = dataclasses.field(default_factory=EngineConfig)
    jwt_expiration_s: int = 60 * 60 * 24
    admin_username: str = "admin"
    admin_password: str = "password"
    index_events: bool = True
    script_root: str | None = None   # versioned tenant-script store dir;
                                     # None -> per-instance temp dir
    conservation_audit_s: float = 5.0  # background conservation-audit
                                       # cadence (ISSUE 14); the thread
                                       # runs only between start() and
                                       # stop(). 0 disables the thread —
                                       # GET /api/instance/conservation
                                       # still audits on demand
    wire_edge: "WireEdgeConfig | None" = None
                                       # persistent-connection listeners
                                       # (ISSUE 20): MQTT/SWP/websocket
                                       # sockets feeding staging arenas.
                                       # None = request-response only


class SiteWhereTpuInstance(LifecycleComponent):
    def __init__(self, config: InstanceConfig | None = None, engine=None):
        """``engine`` may be a pre-built engine — in particular a
        DistributedEngine, so the whole product surface (REST, outbound
        feeds, command delivery, management) serves from the sharded mesh
        state instead of the single-node engine."""
        super().__init__("sitewhere-tpu-instance")
        self.config = config or InstanceConfig()
        self.engine = engine if engine is not None else Engine(self.config.engine)

        # ingest edge: device-initiated stream commands peel off to the
        # stream service (reference routes them through the device command
        # path, DeviceStreamManager.java:36-80); everything else hits the
        # engine's staging path
        self.event_sources = EventSourcesManager(
            on_event_request=self._route_device_request,
            on_registration_request=self.engine.process,
        )
        self.add_child(self.event_sources)

        # persistent-connection wire edge (ISSUE 20): socket listeners
        # feeding staging arenas. The event-sources manager inherits the
        # edge's first batcher, so CoAP/socket/polling receivers with a
        # batchable decoder ride the SAME arrival windows as the live
        # MQTT/SWP connections. Note batched sources bypass the stream-
        # command peel-off (_route_device_request) — sources that need it
        # must keep a host-side deduplicator or a non-batchable decoder.
        self.wire_edge: WireEdge | None = None
        if self.config.wire_edge is not None:
            self.wire_edge = WireEdge(self.engine, self.config.wire_edge)
            self.event_sources.batcher = self.wire_edge.batchers[0]

        # management services
        self.device_management = DeviceManagement(self.engine)
        self.assets = AssetManagement()
        self.streams = DeviceStreamManager()
        self.labels = LabelGeneratorManager()

        # downlink
        self.command_registry = CommandRegistry()
        self.commands = CommandDeliveryService(
            self.engine, SingleChoiceCommandRouter("default"),
            self.command_registry,
        )
        self.add_child(self.commands)
        # cluster-backed engines route invocations to the owning rank's
        # service (see ClusterEngine.route_invocation); the hook gives
        # the rank's RPC server a path to OUR pending set
        attach_cmd = getattr(self.engine, "attach_command_service", None)
        if attach_cmd is not None:
            attach_cmd(self.commands)

        # batch + scheduling
        self.batch = BatchOperationManager()
        self.batch.register_handler(BatchCommandInvocationHandler(self.commands))
        self.scheduler = ScheduleManager()
        # schedule fires record spans on the engine's tracer (ISSUE 10)
        self.scheduler.tracer = getattr(self.engine, "tracer", None)
        self.scheduler.register_executor(
            "CommandInvocation", command_invocation_executor(self.commands)
        )
        self.scheduler.register_executor(
            "BatchCommandByCriteria",
            batch_command_by_criteria_executor(self.device_management, self.batch),
        )

        # search
        self.search = SearchProviderManager()
        self.search_index = EventSearchIndex()
        self.search.add_provider("embedded", self.search_index)
        # a cluster-backed engine fans search out over every rank's index
        # (all replicas feeding one Solr, reference-style): the cluster
        # provider REPLACES "embedded" so REST stays a pure provider
        # lookup; plain engines keep the single-index provider
        attach = getattr(self.engine, "attach_search_index", None)
        if attach is not None:
            from sitewhere_tpu.parallel.cluster import ClusterSearchProvider

            attach(self.search_index)
            self.search.add_provider(
                "embedded", ClusterSearchProvider(self.engine,
                                                  self.search_index))
        self.connector_hosts: list[ConnectorHost] = []
        if self.config.index_events:
            self.add_connector(SearchIndexConnector("search-index", self.search_index))

        # geofencing: zone entry/exit alerts over the location feed
        from sitewhere_tpu.outbound.zones import ZoneMonitor

        self.zone_monitor = ZoneMonitor(self.engine, self.device_management)
        self.add_child(self.zone_monitor)

        # streaming rules / continuous rollups (ISSUE 13; the Siddhi-tier
        # analog): inert until a rule set is installed via REST/RPC, the
        # tenant config's "streamingRules" section, or a watched file
        from sitewhere_tpu.rules import RulesManager

        self.rules = RulesManager(self.engine)

        # event conservation audit plane (ISSUE 14): always-on invariant
        # checking while the instance runs. Constructed here (so REST
        # and the debug bundle can serve its posture immediately) but
        # the thread only spins between start() and stop().
        from sitewhere_tpu.utils.conservation import ConservationAuditor

        self.conservation_auditor = ConservationAuditor(
            self.engine, rules_manager=self.rules,
            interval_s=self.config.conservation_audit_s or 5.0)

        # device-initiated stream commands -> stream store + downlink acks
        from sitewhere_tpu.management.streams import DeviceStreamService

        self.stream_service = DeviceStreamService(self.streams, self.commands)

        # analytics (service-tpu-analytics analog) — live when the engine
        # carries HBM telemetry windows
        self.analytics = None
        if self.config.engine.analytics_devices > 0:
            from sitewhere_tpu.models.service import AnalyticsService

            self.analytics = AnalyticsService(self.engine)

        # fleet-scale historical analytics (ISSUE 19): archive->device
        # batched scoring jobs. Host-side manager is always constructed
        # (jax-free module; jobs fail fast without an archive) so the
        # REST/RPC job surface, the swtpu_analytics_* scrape series, and
        # the analytics-windows conservation stage exist on every
        # instance; it reuses the live service's model when one is up.
        from sitewhere_tpu.models.analytics import AnalyticsManager

        self.analytics_jobs = AnalyticsManager(self.engine,
                                               service=self.analytics)

        # versioned tenant scripts (Instance.java scripting REST family);
        # activation rewrites active.py, which scripted components bind
        # through the hot-reloading ScriptManager
        import tempfile

        from sitewhere_tpu.utils.scripting import (
            DEFAULT_MANAGER,
            ScriptManagement,
        )

        self._scripts_tmpdir = None
        if self.config.script_root is None:
            # ephemeral store for embedded instances — removed on stop(),
            # and by GC/interpreter-exit for instances that never run the
            # lifecycle (tests, short-lived embedding)
            import shutil
            import weakref

            self._scripts_tmpdir = tempfile.mkdtemp(prefix="swtpu-scripts-")
            self._scripts_finalizer = weakref.finalize(
                self, shutil.rmtree, self._scripts_tmpdir,
                ignore_errors=True)
        self.scripts = ScriptManagement(
            self.config.script_root or self._scripts_tmpdir,
            manager=DEFAULT_MANAGER)

        # auth + tenants
        self.users = UserManagement()
        self.users.create_user(self.config.admin_username,
                               self.config.admin_password, roles=["admin"])
        self.jwt = JwtService(expiration_s=self.config.jwt_expiration_s,
                              issuer=self.config.instance_id)
        self.tenants = TenantManagement(self.engine, self.device_management)
        self.tenants.create_tenant("default", "Default Tenant")

        # per-tenant applied component graphs (config.py hot-reload state):
        # tenant -> {"config": dict, "summary": dict}
        self.tenant_configs: dict[str, dict] = {}

        # extra readiness fields served on the public health route
        # (run_rank fills in rank/peers/ports once the rank can serve)
        self.health_extra: dict = {}

    async def on_start(self) -> None:
        if self.config.conservation_audit_s:
            self.conservation_auditor.start()
        if self.wire_edge is not None:
            await self.wire_edge.start()

    async def on_stop(self) -> None:
        # children (event sources) have already stopped; draining the
        # edge last flushes the shared arrival windows they fed
        if self.wire_edge is not None:
            await self.wire_edge.stop()
        self.conservation_auditor.stop()
        if self._scripts_tmpdir is not None:
            import shutil

            shutil.rmtree(self._scripts_tmpdir, ignore_errors=True)
            self._scripts_tmpdir = None

    # --- wiring helpers ---------------------------------------------------
    def add_source(self, source: InboundEventSource) -> InboundEventSource:
        return self.event_sources.add_source(source)

    def _route_device_request(self, req) -> None:
        """Ingest dispatch: stream commands to the stream service,
        everything else to the engine."""
        if self.stream_service.handles(req):
            self.stream_service.handle_request(req)
        else:
            self.engine.process(req)

    def add_connector(self, connector: OutboundConnector,
                      start_from_latest: bool = False) -> ConnectorHost:
        host = ConnectorHost(self.engine, connector,
                             start_from_latest=start_from_latest)
        self.connector_hosts.append(host)
        self.add_child(host)
        return host

    async def pump_outbound(self) -> int:
        """Drive command delivery + all connector hosts once (embedded mode;
        under the REST server these run as background tasks)."""
        n = await self.commands.pump()
        n += await self.zone_monitor.pump()
        for host in self.connector_hosts:
            n += await host.pump()
        return n

    def info(self) -> dict:
        return {
            "instanceId": self.config.instance_id,
            "version": __import__("sitewhere_tpu").__version__,
            "devices": len(self.engine.devices),
            "tenants": len(self.tenants.tenants),
            "metrics": self.engine.metrics(),
            "components": self.describe(),
        }
