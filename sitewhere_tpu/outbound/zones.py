"""Zone monitor: geofence evaluation over the location-event feed.

The reference persists zones (polygon bounds per area; Zones REST
controller, RdbZone) as its geofences but leaves evaluation to external
rule engines. Here evaluation is built in: a feed consumer batches the
newly persisted LOCATION events, tests every point against every zone in
one on-device ray-casting pass (ops/geofence.py), diffs each device's
zone membership against its previous set, and injects zone.entered /
zone.exited alerts back into the pipeline — downstream consumers (device
state, connectors, command delivery) see them like any device alert,
exactly how the analytics anomaly alerts flow.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.core.types import AlertLevel, EventType
from sitewhere_tpu.ingest.requests import DecodedRequest, RequestType
from sitewhere_tpu.ops.geofence import pack_zones, points_in_zones
from sitewhere_tpu.outbound.feed import FeedConsumer
from sitewhere_tpu.utils.lifecycle import LifecycleComponent


class ZoneMonitor(LifecycleComponent):
    """Watches location events and raises zone entry/exit alerts."""

    def __init__(self, engine, device_management,
                 alert_level: AlertLevel = AlertLevel.WARNING,
                 max_vertices: int = 16):
        super().__init__("zone-monitor")
        self.engine = engine
        self.dm = device_management
        self.alert_level = alert_level
        self.max_vertices = max_vertices
        self.consumer = engine.make_feed_consumer("zone-monitor",
                                                  start_from_latest=True)
        # device_id -> frozenset of zone tokens currently containing it
        self.membership: dict[int, frozenset[str]] = {}
        self._zone_tokens: list[str] = []
        self._verts = None
        self._valid = None
        self._zone_version = -1

    def _refresh_zones(self) -> None:
        """Rebuild the packed zone arrays when the zone store changed
        (token set, identity, OR bounds — delete+recreate and in-place
        bounds edits must both invalidate the cache)."""
        zones = self.dm.zones.all()
        version = tuple(sorted(
            (z.meta.token, z.meta.id, tuple(map(tuple, z.bounds)))
            for z in zones))
        if version == self._zone_version:
            return
        self._zone_version = version
        usable = []
        tokens = []
        for z in zones:
            if len(z.bounds) > self.max_vertices:
                # defense in depth (create_zone validates too): one bad zone
                # must never poison the shared outbound pump
                import logging

                logging.getLogger(__name__).warning(
                    "zone %s has %d vertices > capacity %d; skipping",
                    z.meta.token, len(z.bounds), self.max_vertices)
                continue
            usable.append(list(z.bounds))
            tokens.append(z.meta.token)
        self._zone_tokens = tokens
        verts, valid = pack_zones(usable, self.max_vertices)
        self._verts = jnp.asarray(verts)
        self._valid = jnp.asarray(valid)

    async def pump(self) -> int:
        """Evaluate newly persisted location events; returns alerts raised."""
        self._refresh_zones()
        events = self.consumer.poll()
        locs = [e for e in events
                if e.etype is EventType.LOCATION and e.latitude is not None]
        raised = 0
        if locs:
            if self._zone_tokens:
                # pad the point batch to a power-of-two bucket: the kernel
                # is jitted, and a fresh trace per distinct batch size would
                # stall the pump (static shapes, like every kernel here)
                n = len(locs)
                cap = max(8, 1 << (n - 1).bit_length())
                pts = np.zeros((cap, 2), np.float32)
                pts[:n] = [[e.latitude, e.longitude] for e in locs]
                inside = np.asarray(points_in_zones(
                    jnp.asarray(pts), self._verts, self._valid))[:n]
            else:
                inside = np.zeros((len(locs), 0), bool)
            # latest location per device wins within the batch
            latest: dict[int, int] = {}
            for i, e in enumerate(locs):
                latest[e.device_id] = i
            for did, i in latest.items():
                now_in = frozenset(
                    tok for z, tok in enumerate(self._zone_tokens)
                    if inside[i, z])
                before = self.membership.get(did, frozenset())
                if now_in == before:
                    continue
                self.membership[did] = now_in
                token = locs[i].device_token
                for ztok in sorted(now_in - before):
                    self._alert(token, "zone.entered", ztok)
                    raised += 1
                for ztok in sorted(before - now_in):
                    self._alert(token, "zone.exited", ztok)
                    raised += 1
        if events:
            self.consumer.commit(events)
        if raised:
            self.engine.flush_async()
        return raised

    def _alert(self, device_token: str, kind: str, zone_token: str) -> None:
        self.engine.process(DecodedRequest(
            type=RequestType.DEVICE_ALERT,
            device_token=device_token,
            alert_type=f"{kind}:{zone_token}",
            alert_level=self.alert_level,
            alert_message=f"{kind} {zone_token}",
        ))
