"""Outbound event feed: per-consumer cursor over the persisted event store.

Plays the role of the reference's outbound-events / outbound-command-
invocations topics plus consumer groups (OutboundPayloadEnrichmentLogic
enriches and produces, KafkaOutboundConnectorHost consumes with its own
group offset; SURVEY.md §2.3/§2.7). Each ``FeedConsumer`` owns a committed
offset into the engine's event store; ``poll()`` returns newly persisted,
context-enriched events as host records. Offsets commit after the handler
batch succeeds — at-least-once, exactly like the reference's async offset
commits (KafkaOutboundConnectorHost.java:156-163).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from sitewhere_tpu.core.types import NULL_ID, EventType
from sitewhere_tpu.ops.readback import read_range


@dataclasses.dataclass
class OutboundEvent:
    """Host-side enriched event record (GProcessedEventPayload analog)."""

    event_id: int          # absolute store position (unique, ordered)
    etype: EventType
    device_token: str
    device_id: int
    assignment_id: int
    tenant: str
    area_id: int
    asset_id: int
    ts_ms: int
    received_ms: int
    measurements: dict[str, float]
    values: list[float]
    aux0: int
    aux1: int
    customer_id: int = NULL_ID
    # set only for LOCATION events that carried coordinates (vmask lane 0);
    # a null-coord location event leaves these None — never null island
    latitude: float | None = None
    longitude: float | None = None

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "eventId": self.event_id,
            "type": self.etype.name,
            "deviceToken": self.device_token,
            "assignmentId": self.assignment_id,
            "tenant": self.tenant,
            "areaId": self.area_id,
            "assetId": self.asset_id,
            "eventDateMs": self.ts_ms,
            "receivedDateMs": self.received_ms,
            "measurements": self.measurements,
            "values": self.values,
        }


class FeedConsumer:
    """One consumer group over the engine's event store.

    With tenant arenas each arena is an independent sub-ring (its own
    write order), so the consumer keeps one committed offset per arena —
    the per-partition consumer-group offsets of the reference, with the
    arena as the partition. Event ids encode (arena, position) as
    ``position * arenas + arena``; with one arena (the default) ids are
    plain positions, unchanged."""

    def __init__(self, engine, group_id: str, max_batch: int = 1024,
                 start_from_latest: bool = False):
        from sitewhere_tpu.ops.readback import arena_cursor

        self.engine = engine
        self.group_id = group_id
        self.max_batch = max_batch
        store = engine.state.store
        self.arenas = store.arenas
        self.offsets = [
            arena_cursor(store, a) if start_from_latest else 0
            for a in range(self.arenas)
        ]
        self.lag_lost = 0  # events overwritten before we consumed them

    @property
    def offset(self) -> int:
        """Total committed events across arenas (monotone)."""
        return sum(self.offsets)

    def poll(self) -> list[OutboundEvent]:
        """Fetch newly persisted events past the committed offsets (does not
        commit — call ``commit(events)`` after successful processing)."""
        # the WHOLE poll holds the engine lock: pipeline state is DONATED
        # through every step, so a store reference captured outside the
        # lock dies ("Array has been deleted") the moment a concurrent
        # flush dispatches — and a ring that wrapped between the head read
        # and the range read would serve new rows under old positions.
        # Polls are control-plane (connector pumping); ingest holds the
        # lock only per dispatch, so the serialization is acceptable.
        with self.engine.lock:
            if self.engine._pending_outs:
                self.engine.drain()
            return self._poll_locked()

    def _poll_locked(self) -> list[OutboundEvent]:
        """Poll body; caller MUST hold the engine lock (protects the
        donated store AND the archive index, which _spool/_expire mutate
        and whose segment files they unlink)."""
        from sitewhere_tpu.ops.readback import arena_cursor

        store = self.engine.state.store
        acap = store.arena_capacity
        archive = getattr(self.engine, "archive", None)
        lane_names = self._lane_names()   # once per poll, not per chunk
        out: list[OutboundEvent] = []
        for a in range(self.arenas):
            head = arena_cursor(store, a)
            if head <= self.offsets[a]:
                continue
            # ring overwrite: oldest retained position is head - arena cap.
            # A lagging consumer REPLAYS evicted rows from the archive tier
            # (Kafka-consumer at-least-once: falling behind means reading
            # older log segments, not losing events). Like the ring read,
            # replay does NOT advance the committed offset — redelivery
            # until commit(); only unrecoverable gaps (rows absent from the
            # archive too) advance the offset and count as lag_lost.
            oldest = max(0, head - acap)
            budget = self.max_batch
            if archive is None and self.offsets[a] < oldest:
                self.lag_lost += oldest - self.offsets[a]
                self.offsets[a] = oldest
            pos = self.offsets[a]
            while archive is not None and pos < oldest and budget > 0:
                sl, n = archive.read_rows(a, pos,
                                          min(oldest - pos, budget))
                if n == 0:
                    # recorded-loss/expired gap: skip ONLY to the next
                    # archived segment (or the ring) — and only when
                    # nothing replayed-but-uncommitted precedes the gap,
                    # else the offset advance would drop those events on
                    # a pre-commit crash
                    if pos != self.offsets[a]:
                        break   # deliver pre-gap events first
                    nxt = archive.next_start(a, pos)
                    nxt = oldest if nxt is None else min(nxt, oldest)
                    self.lag_lost += nxt - pos
                    self.offsets[a] = nxt
                    pos = nxt
                    continue
                out.extend(self._enrich(sl, pos, n, a, lane_names))
                pos += n
                budget -= n
            if pos < oldest:
                continue   # batch full mid-replay; resumes next poll
            count = min(head - pos, budget)
            if count <= 0:
                continue
            sl = read_range(store, np.int32(pos % acap), count, arena=a)
            out.extend(self._enrich(sl, pos, count, a, lane_names))
        return out

    def commit(self, events: list[OutboundEvent]) -> None:
        for ev in events:
            a = ev.event_id % self.arenas
            pos = ev.event_id // self.arenas
            self.offsets[a] = max(self.offsets[a], pos + 1)

    def _lane_names(self) -> dict[int, str]:
        """channel -> representative name (first interned name per lane)."""
        eng = self.engine
        lane_names: dict[int, str] = {}
        for name, nid in eng.channel_map.names.items():
            lane_names.setdefault(nid % eng.config.channels, name)
        return lane_names

    def _enrich(self, sl, base: int, count: int, arena: int = 0,
                lane_names: dict[int, str] | None = None
                ) -> list[OutboundEvent]:
        eng = self.engine
        if lane_names is None:
            lane_names = self._lane_names()
        etype = np.asarray(sl.etype[:count])
        device = np.asarray(sl.device[:count])
        assignment = np.asarray(sl.assignment[:count])
        tenant = np.asarray(sl.tenant[:count])
        area = np.asarray(sl.area[:count])
        customer = np.asarray(sl.customer[:count])
        asset = np.asarray(sl.asset[:count])
        ts = np.asarray(sl.ts_ms[:count])
        recv = np.asarray(sl.received_ms[:count])
        values = np.asarray(sl.values[:count])
        vmask = np.asarray(sl.vmask[:count])
        aux = np.asarray(sl.aux[:count])
        valid = np.asarray(sl.valid[:count])

        out = []
        for i in range(count):
            if not valid[i]:
                continue
            info = eng.devices.get(int(device[i]))
            et = EventType(int(etype[i]))
            meas = {}
            lat = lon = None
            if et is EventType.MEASUREMENT:
                for ch in np.nonzero(vmask[i])[0]:
                    meas[lane_names.get(int(ch), f"ch{ch}")] = float(values[i, ch])
            elif et is EventType.LOCATION and vmask[i, 0]:
                lat, lon = float(values[i, 0]), float(values[i, 1])
            out.append(
                OutboundEvent(
                    event_id=(base + i) * self.arenas + arena,
                    etype=et,
                    device_token=info.token if info else f"#{int(device[i])}",
                    device_id=int(device[i]),
                    assignment_id=int(assignment[i]),
                    tenant=(
                        eng.tenants.token(int(tenant[i]))
                        if int(tenant[i]) != NULL_ID else "default"
                    ),
                    area_id=int(area[i]),
                    customer_id=int(customer[i]),
                    asset_id=int(asset[i]),
                    ts_ms=int(ts[i]),
                    received_ms=int(recv[i]),
                    measurements=meas,
                    values=[float(v) for v in values[i]],
                    aux0=int(aux[i, 0]),
                    aux1=int(aux[i, 1]),
                    latitude=lat,
                    longitude=lon,
                )
            )
        return out
