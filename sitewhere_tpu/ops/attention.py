"""Blockwise (flash) attention for long telemetry windows — Pallas TPU kernel
plus a jnp oracle.

The reference has no attention anywhere (SURVEY.md §5.7: long-context is a new
TPU-first design axis, not a ported one). This op is the compute core of the
long-window analytics models (models/transformer.py): telemetry windows grow
to tens of thousands of timesteps per device, so attention must be blockwise
(never materialize the [S, S] score matrix in HBM) and, across chips,
sequence-parallel (parallel/ring_attention.py reuses the same streaming-softmax
update this kernel applies per block).

TPU mapping:
  * scores are computed tile-by-tile in VMEM with the MXU doing the
    [block_q, D] @ [D, block_k] and [block_q, block_k] @ [block_k, D]
    matmuls in bfloat16/float32;
  * the softmax runs in streaming form (running row-max m, normalizer l,
    unnormalized accumulator acc) so only O(block_q * D) state lives across
    key blocks — the flash-attention recurrence;
  * grid = (batch*heads, q-blocks, k-blocks) with the k axis innermost and
    sequential ("arbitrary"), accumulating into VMEM scratch.

The jnp reference is the oracle for tests and the fallback on non-TPU
backends (interpret mode covers the kernel itself in CI).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sitewhere_tpu import compat as _compat

_NEG_INF = -1e30


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jax.Array:
    """Plain multi-head attention oracle.

    q, k, v: [B, S, H, D] -> [B, S, H, D]. Softmax in float32.
    """
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / float(d) ** 0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = jnp.arange(sq)[:, None]
        col = jnp.arange(sk)[None, :]
        s = jnp.where(col > row, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc, *,
                  sm_scale, causal, block_q, block_k, num_kb):
    """One (bh, qi, ki) grid step: fold key block ki into the running softmax
    state for query block qi. Scratch persists across the sequential k axis."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # Causal: key blocks entirely above the diagonal contribute nothing —
    # skip their matmuls (halves the causal FLOPs).
    live = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [block_q, D]
        k = k_ref[0].astype(jnp.float32)          # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale                               # [block_q, block_k]

        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(col > row, _NEG_INF, s)

        m_prev = m_sc[:, 0]                        # [block_q]
        l_prev = l_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                          # [block_q, D]
        acc_sc[:] = acc_sc[:] * alpha[:, None] + pv
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[:, None], l_sc.shape)

    @pl.when(ki == num_kb - 1)
    def _emit():
        # Fully-masked rows (padding) have l == 0; emit 0 for them.
        l = l_sc[:, 0]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[:] / safe[:, None]).astype(o_ref.dtype)


def _pick_block(s: int, preferred: int) -> int:
    for b in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if b <= s and s % b == 0:
            return b
    return s


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k",
                              "force_pallas")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    block_q: int = 512,
    block_k: int = 512,
    force_pallas: bool = False,
) -> jax.Array:
    """Blockwise attention, [B, S, H, D] -> [B, S, H, D].

    Runs the Pallas kernel on TPU (interpret mode when forced on CPU for
    tests); jnp oracle elsewhere. D is padded to a lane-friendly multiple of
    128 inside the kernel and sliced back.
    """
    on_tpu = jax.default_backend() == "tpu"
    if not (on_tpu or force_pallas):
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)

    b, s, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / float(d) ** 0.5
    bq = _pick_block(s, block_q)
    bk = _pick_block(s, block_k)

    dp = -d % 128
    if dp:
        pad = ((0, 0), (0, 0), (0, 0), (0, dp))
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    dd = d + dp

    # [B, S, H, D] -> [B*H, S, D]
    def bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, dd)

    qf, kf, vf = bh(q), bh(k), bh(v)
    num_kb = s // bk
    grid = (b * h, s // bq, num_kb)

    kernel = functools.partial(
        _flash_kernel, sm_scale=scale, causal=causal,
        block_q=bq, block_k=bk, num_kb=num_kb,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, dd), lambda bh_, qi, ki: (bh_, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dd), lambda bh_, qi, ki: (bh_, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dd), lambda bh_, qi, ki: (bh_, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, dd), lambda bh_, qi, ki: (bh_, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, s, dd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, dd), jnp.float32),
        ],
        compiler_params=_compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=not on_tpu,
    )(qf, kf, vf)

    out = out.reshape(b, h, s, dd)[..., :d]
    return jnp.swapaxes(out, 1, 2)
