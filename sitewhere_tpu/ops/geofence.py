"""Zone geofencing: vectorized point-in-polygon on device.

The reference stores zones as lat/lon polygon bounds on areas
(service-device-management/.../Zones controller + RdbZone entity;
SURVEY.md §2.5) — the platform's geofences. The reference repo itself
never evaluates them (evaluation lived in downstream rule engines); here
containment is a first-class batched kernel: every location event in a
batch is tested against every zone in one [N x Z x V] ray-casting pass —
MXU-free but fully vectorized, no per-event host loops.

Zone storage is padded to a static vertex capacity V by REPEATING the
first vertex: the wrap edge then degenerates to a zero-length segment
that contributes no crossings, so polygons of any size share one shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pack_zones(polygons: list[list[tuple[float, float]]],
               max_vertices: int = 16) -> tuple[np.ndarray, np.ndarray]:
    """[(lat, lon), ...] polygons -> (verts [Z, V, 2] float32, valid [Z]).
    Polygons beyond ``max_vertices`` raise; empty list packs a single
    invalid row so downstream shapes stay static."""
    z = max(1, len(polygons))
    verts = np.zeros((z, max_vertices, 2), np.float32)
    valid = np.zeros(z, bool)
    for i, poly in enumerate(polygons):
        if len(poly) < 3:
            raise ValueError(f"zone {i}: a polygon needs >= 3 vertices")
        if len(poly) > max_vertices:
            raise ValueError(
                f"zone {i}: {len(poly)} vertices > capacity {max_vertices}")
        arr = np.asarray(poly, np.float32)
        verts[i, :len(poly)] = arr
        verts[i, len(poly):] = arr[0]      # pad = first vertex (degenerate)
        valid[i] = True
    return verts, valid


@jax.jit
def points_in_zones(points: jax.Array, verts: jax.Array,
                    zone_valid: jax.Array) -> jax.Array:
    """points [N, 2] (lat, lon) x zones [Z, V, 2] -> bool [N, Z].

    Even-odd ray casting; the ray runs in +lon. Division-free edge test so
    degenerate (padded) edges are exact no-ops.
    """
    a = verts                                   # [Z, V, 2]
    b = jnp.roll(verts, -1, axis=1)             # [Z, V, 2] next vertex
    py = points[:, None, None, 0]               # lat  [N, 1, 1]
    px = points[:, None, None, 1]               # lon  [N, 1, 1]
    ay, ax = a[None, :, :, 0], a[None, :, :, 1]   # [1, Z, V]
    by, bx = b[None, :, :, 0], b[None, :, :, 1]

    straddles = (ay > py) != (by > py)
    # px < ax + (py - ay) * (bx - ax) / (by - ay), multiplied through by
    # (by - ay) with sign-aware flip:
    lhs = (px - ax) * (by - ay)
    rhs = (bx - ax) * (py - ay)
    crosses = straddles & jnp.where(by > ay, lhs < rhs, lhs > rhs)
    inside = jnp.sum(crosses, axis=2) % 2 == 1    # [N, Z]
    return inside & zone_valid[None, :]


# devicewatch (ISSUE 11): standalone containment calls (zone REST
# checks, tests) report compiles under the geofence family; calls
# inlined into the pipeline step trace pass through untouched.
from sitewhere_tpu.utils.devicewatch import watched_jit  # noqa: E402

points_in_zones = watched_jit(points_in_zones, family="geofence")
