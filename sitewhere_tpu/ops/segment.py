"""Sort/segment primitives used by the batched pipeline kernels.

These replace the reference's per-key Kafka Streams grouping
(``groupByKey().windowedBy(...).aggregate(...)`` in
service-device-state/.../kafka/DeviceStatePipeline.java:80-88) with
fully-vectorized XLA patterns: lexicographic sorts via ``lax.sort`` with
multiple keys, run-length ranks computed with cumulative max/min scans, and
"argmax scatter" (find the winning event per key without data-dependent
control flow). Everything is static-shape and jit-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

INT32_MIN = jnp.iinfo(jnp.int32).min
INT32_MAX = jnp.iinfo(jnp.int32).max


def lex_argsort(keys: list[jax.Array]) -> tuple[list[jax.Array], jax.Array]:
    """Stable lexicographic argsort of equal-length 1-D keys (ascending,
    keys[0] primary). Returns (sorted_keys, permutation); apply ``perm`` to
    gather arbitrary (possibly multi-dimensional) payload rows."""
    n = keys[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    out = lax.sort(list(keys) + [iota], num_keys=len(keys), is_stable=True)
    return list(out[: len(keys)]), out[-1]


def segment_ranks(sorted_ids: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Given segment ids already sorted ascending, return
    ``(rank_from_start, rank_from_end)`` within each run of equal ids.

    rank_from_end == 0 marks the last (e.g. most recent, if secondary-sorted
    by time) element of each segment.
    """
    n = sorted_ids.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    is_start = jnp.concatenate([jnp.ones((1,), jnp.bool_), sorted_ids[1:] != sorted_ids[:-1]])
    # index of the start of each run, propagated forward
    start_idx = lax.associative_scan(jnp.maximum, jnp.where(is_start, idx, INT32_MIN))
    rank_from_start = idx - start_idx
    is_end = jnp.concatenate([sorted_ids[1:] != sorted_ids[:-1], jnp.ones((1,), jnp.bool_)])
    # index of the end of each run, propagated backward
    end_idx = lax.associative_scan(jnp.minimum, jnp.where(is_end, idx, INT32_MAX), reverse=True)
    rank_from_end = end_idx - idx
    return rank_from_start, rank_from_end


def scatter_argmax_mask(
    seg: jax.Array,
    key1: jax.Array,
    key2: jax.Array,
    valid: jax.Array,
    num_segments: int,
) -> jax.Array:
    """Return a bool mask selecting, for every segment id, the single element
    with the lexicographically largest ``(key1, key2)`` among ``valid`` rows.

    ``key2`` must be unique per row within a segment (e.g. batch sequence
    number) so the winner is unique. Three scatters + two gathers; no sort.
    """
    seg_c = jnp.where(valid, seg, num_segments)  # invalid rows -> dropped slot
    k1 = jnp.where(valid, key1, INT32_MIN)
    max1 = jnp.full((num_segments,), INT32_MIN, key1.dtype).at[seg_c].max(k1, mode="drop")
    on_max1 = valid & (key1 == max1.at[seg_c].get(mode="fill", fill_value=INT32_MIN))
    k2 = jnp.where(on_max1, key2, INT32_MIN)
    max2 = jnp.full((num_segments,), INT32_MIN, key2.dtype).at[seg_c].max(k2, mode="drop")
    winner = on_max1 & (key2 == max2.at[seg_c].get(mode="fill", fill_value=INT32_MIN))
    return winner


def stable_partition_topk(perm: jax.Array, match_sorted: jax.Array,
                          total: jax.Array, limit: int) -> jax.Array:
    """First ``limit`` entries of the stable partition of ``perm`` by
    ``match_sorted``: matching entries keep their ``perm`` order and come
    first, non-matching entries (in ``perm`` order) fill the remainder.

    This is the O(N) per-query half of the shared-scan batched query: when
    ``perm`` is one ordering sort shared by every query in a batch, the
    result equals ``lex_argsort([~match, order_key])[:limit]`` — the
    stable lexicographic sort the single-query path runs — without paying
    a per-query O(N log N) sort. ``total`` must equal ``sum(match_sorted)``
    (the caller already needs it for result counting). Two cumulative sums
    and one no-conflict scatter; destinations past ``limit`` drop."""
    m = match_sorted
    match_rank = jnp.cumsum(m.astype(jnp.int32)) - 1
    non_rank = jnp.cumsum((~m).astype(jnp.int32)) - 1
    dest = jnp.where(m, match_rank, total + non_rank)
    return jnp.zeros((limit,), perm.dtype).at[dest].set(perm, mode="drop")


def compact_valid_front(valid: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Stable permutation moving ``valid`` rows to the front.

    Returns (n_valid, perm). Used to densify assignment-expanded events before
    the ring-buffer append (ops/persist.py) so padding never costs capacity.
    """
    _, perm = lex_argsort([(~valid).astype(jnp.int32)])
    n_valid = jnp.sum(valid.astype(jnp.int32))
    return n_valid, perm
