"""Device-side event store queries: filtered scan + top-k by time.

The reference's event queries (listDeviceEvents / searchDeviceEvents REST
paths backed by InfluxDB/Cassandra per-tenant queries) become a masked scan
over the HBM ring with an on-device sort — the whole store is filtered in
one XLA program and only the top-``limit`` rows travel to the host.

:func:`query_store_batch` is the shared-scan variant (Crescando/SharedDB
scan sharing): Q predicate sets evaluate in ONE pass over the store. The
ordering sort is query-independent — newest-first with index tie-break —
so the batch runs it once and each query reduces to an O(N) masked scan
plus an O(N) stable-partition top-k (ops/segment.stable_partition_topk)
instead of Q independent O(N log N) sorts. Results are byte-identical to
Q sequential :func:`query_store` calls, tie-breaking included.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.core.store import EventStore
from sitewhere_tpu.core.types import NULL_ID
from sitewhere_tpu.ops.segment import lex_argsort, stable_partition_topk


class QueryResult(NamedTuple):
    n: jax.Array        # int32[] matches (capped at limit)
    total: jax.Array    # int32[] total matches in store
    etype: jax.Array    # int32[limit]
    device: jax.Array
    assignment: jax.Array
    tenant: jax.Array
    area: jax.Array
    customer: jax.Array
    ts_ms: jax.Array
    received_ms: jax.Array
    values: jax.Array   # float32[limit, C]
    vmask: jax.Array
    aux: jax.Array


class QueryParams(NamedTuple):
    """One predicate set per lane (int32[Q] each; ``NULL_ID`` = any).
    ``t0``/``t1`` are the inclusive event-time bounds — callers pass the
    full int32 range for an unbounded side."""

    device: jax.Array
    etype: jax.Array
    tenant: jax.Array
    t0: jax.Array
    t1: jax.Array
    assignment: jax.Array
    aux0: jax.Array
    aux1: jax.Array
    area: jax.Array
    customer: jax.Array


N_QUERY_PARAMS = len(QueryParams._fields)


def bucket_limit(limit: int) -> int:
    """Power-of-two bucket for the static ``limit`` argument — bounds the
    compile cache at one program per bucket instead of one per distinct
    ``pageSize`` (callers slice the result back to the exact page)."""
    return 1 << max(0, int(limit) - 1).bit_length()


def host_filter_mask(cols: dict, *, device=None, etype=None, tenant=None,
                     assignment=None, aux0=None, aux1=None, area=None,
                     customer=None, since_ms=None,
                     until_ms=None) -> np.ndarray:
    """Host-side (numpy) evaluation of ONE query predicate set over a
    columnar row block — the archive-tier mirror of the masks
    :func:`query_store` builds on device, kept here so the two tiers'
    predicate semantics can never drift apart. ``cols`` maps ring column
    names to arrays (``aux`` is the 2-d lane column); ``None`` = any,
    matching the NULL_ID convention of :class:`QueryParams`. Validity and
    eviction caps are the CALLER's concern — this is only the predicate
    conjunction."""
    n = len(cols["ts_ms"])
    m = np.ones(n, bool)
    if device is not None:
        m &= cols["device"] == device
    if etype is not None:
        m &= cols["etype"] == etype
    if tenant is not None:
        m &= cols["tenant"] == tenant
    if assignment is not None:
        m &= cols["assignment"] == assignment
    if aux0 is not None:
        m &= cols["aux"][:, 0] == aux0
    if aux1 is not None:
        m &= cols["aux"][:, 1] == aux1
    if area is not None:
        m &= cols["area"] == area
    if customer is not None:
        m &= cols["customer"] == customer
    ts = cols["ts_ms"]
    if since_ms is not None:
        m &= ts >= since_ms
    if until_ms is not None:
        m &= ts <= until_ms
    return m


MAX_PAGE_SIZE = 1000


def clamp_page_size(value, default: int = 100) -> int:
    """THE pageSize clamp ([1, MAX_PAGE_SIZE]) shared by every external
    surface (REST gateway, RPC server) — it caps :func:`bucket_limit` at
    1024, so a wire-supplied page size can never mint an unbounded set of
    compiled query programs. Lives next to the bucketing it protects so
    the surfaces can't drift apart."""
    if value is None:
        value = default
    return max(1, min(int(value), MAX_PAGE_SIZE))


@functools.partial(jax.jit, static_argnames=("limit",))
def query_store_batch(store: EventStore, params: QueryParams,
                      limit: int = 100) -> QueryResult:
    """Evaluate Q predicate sets in one pass over the ring (leading Q dim
    on every result field). One shared newest-first ordering sort; per
    query only the O(N) mask + stable-partition top-k. Byte-identical to
    Q sequential :func:`query_store` calls at the same ``limit``."""
    limit = min(limit, store.capacity)   # match query_store's perm[:limit]
    neg_ts = -jnp.maximum(store.ts_ms, jnp.iinfo(jnp.int32).min + 1)
    # ONE ordering sort shared by every query: stable ascending on -ts
    # keeps index-ascending ties, so a stable partition by each query's
    # match mask reproduces lex_argsort([~match, -ts]) exactly
    _, perm = lex_argsort([neg_ts])

    def one(p: QueryParams) -> QueryResult:
        m = store.valid
        m &= (p.device == NULL_ID) | (store.device == p.device)
        m &= (p.etype == NULL_ID) | (store.etype == p.etype)
        m &= (p.tenant == NULL_ID) | (store.tenant == p.tenant)
        m &= (p.assignment == NULL_ID) | (store.assignment == p.assignment)
        m &= (p.aux0 == NULL_ID) | (store.aux[:, 0] == p.aux0)
        m &= (p.aux1 == NULL_ID) | (store.aux[:, 1] == p.aux1)
        m &= (p.area == NULL_ID) | (store.area == p.area)
        m &= (p.customer == NULL_ID) | (store.customer == p.customer)
        m &= (store.ts_ms >= p.t0) & (store.ts_ms <= p.t1)
        total = jnp.sum(m.astype(jnp.int32))
        top = stable_partition_topk(perm, m[perm], total, limit)
        return QueryResult(
            n=jnp.minimum(total, limit),
            total=total,
            etype=store.etype[top],
            device=store.device[top],
            assignment=store.assignment[top],
            tenant=store.tenant[top],
            area=store.area[top],
            customer=store.customer[top],
            ts_ms=store.ts_ms[top],
            received_ms=store.received_ms[top],
            values=store.values[top],
            vmask=store.vmask[top],
            aux=store.aux[top],
        )

    return jax.vmap(one)(params)


@functools.partial(jax.jit, static_argnames=("limit",))
def query_store(
    store: EventStore,
    device: jax.Array,   # int32[] filter (NULL_ID = any)
    etype: jax.Array,    # int32[] filter (NULL_ID = any)
    tenant: jax.Array,   # int32[] filter (NULL_ID = any)
    t0: jax.Array,       # int32[] inclusive lower ts bound
    t1: jax.Array,       # int32[] inclusive upper ts bound
    limit: int = 100,
    assignment: jax.Array | None = None,  # int32[] filter (NULL_ID = any)
    aux0: jax.Array | None = None,        # int32[] filter on aux[:, 0]
    aux1: jax.Array | None = None,        # int32[] filter on aux[:, 1]
    area: jax.Array | None = None,        # int32[] filter (NULL_ID = any)
    customer: jax.Array | None = None,    # int32[] filter (NULL_ID = any)
) -> QueryResult:
    """Newest-first filtered query over the whole ring."""
    m = store.valid
    m &= (device == NULL_ID) | (store.device == device)
    m &= (etype == NULL_ID) | (store.etype == etype)
    m &= (tenant == NULL_ID) | (store.tenant == tenant)
    if assignment is not None:
        m &= (assignment == NULL_ID) | (store.assignment == assignment)
    if aux0 is not None:
        m &= (aux0 == NULL_ID) | (store.aux[:, 0] == aux0)
    if aux1 is not None:
        m &= (aux1 == NULL_ID) | (store.aux[:, 1] == aux1)
    if area is not None:
        m &= (area == NULL_ID) | (store.area == area)
    if customer is not None:
        m &= (customer == NULL_ID) | (store.customer == customer)
    m &= (store.ts_ms >= t0) & (store.ts_ms <= t1)
    total = jnp.sum(m.astype(jnp.int32))
    # sort newest first: key = (-match, -ts)
    neg_ts = -jnp.maximum(store.ts_ms, jnp.iinfo(jnp.int32).min + 1)
    _, perm = lex_argsort([(~m).astype(jnp.int32), neg_ts])
    top = perm[:limit]
    n = jnp.minimum(total, limit)
    return QueryResult(
        n=n,
        total=total,
        etype=store.etype[top],
        device=store.device[top],
        assignment=store.assignment[top],
        tenant=store.tenant[top],
        area=store.area[top],
        customer=store.customer[top],
        ts_ms=store.ts_ms[top],
        received_ms=store.received_ms[top],
        values=store.values[top],
        vmask=store.vmask[top],
        aux=store.aux[top],
    )


def merge_shard_pages(pages: QueryResult, limit: int) -> QueryResult:
    """Merge per-shard top-``limit`` pages into the global page (host
    side, numpy). ``pages`` is a :class:`QueryResult` of HOST arrays with
    a leading shard axis (``ts_ms`` is ``[S, limit]``; ``n``/``total``
    are ``[S]``). The merge key is ``(-ts, shard, in-page rank)`` —
    newest first, shard-ascending then rank-ascending on ts ties. Within
    one shard the page already carries the single-chip store-index
    tie-order, so the merged page is byte-identical to the single-chip
    engine whenever ts ties do not span shards (the sharded tie
    contract; see README "Multi-chip SPMD store"). Per-shard top-k is
    sufficient: any global top-``limit`` row is inside its own shard's
    top-``limit``."""
    n = np.asarray(pages.n).astype(np.int64)            # [S]
    ts_all = np.asarray(pages.ts_ms)
    page_len = ts_all.shape[1]
    s_idx, i_idx = np.nonzero(
        np.arange(page_len)[None, :] < n[:, None])
    order = np.lexsort(
        (i_idx, s_idx,
         -ts_all[s_idx, i_idx].astype(np.int64)))[: int(limit)]
    gs, gi = s_idx[order], i_idx[order]
    k = len(order)
    total = int(np.asarray(pages.total).sum())

    def gather(col):
        col = np.asarray(col)
        out = np.zeros((int(limit),) + col.shape[2:], col.dtype)
        out[:k] = col[gs, gi]
        return out

    return QueryResult(
        n=np.int32(min(total, int(limit))), total=np.int32(total),
        etype=gather(pages.etype), device=gather(pages.device),
        assignment=gather(pages.assignment), tenant=gather(pages.tenant),
        area=gather(pages.area), customer=gather(pages.customer),
        ts_ms=gather(pages.ts_ms), received_ms=gather(pages.received_ms),
        values=gather(pages.values), vmask=gather(pages.vmask),
        aux=gather(pages.aux))


# devicewatch (ISSUE 11): both query kernels report compiles/shape keys
# under the query families. Passthrough shims — dispatch, ``.lower``
# (the QueryBatcher's AOT seam, which records its own exact compile
# timings per (Q bucket, limit bucket)), and in-jit inlining (the
# sharded engine's _stacked_query) all behave exactly as before.
from sitewhere_tpu.utils.devicewatch import watched_jit  # noqa: E402

query_store_batch = watched_jit(query_store_batch, family="query.batch",
                                static_argnames=("limit",))
query_store = watched_jit(query_store, family="query.scan",
                          static_argnames=("limit",))
