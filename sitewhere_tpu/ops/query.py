"""Device-side event store queries: filtered scan + top-k by time.

The reference's event queries (listDeviceEvents / searchDeviceEvents REST
paths backed by InfluxDB/Cassandra per-tenant queries) become a masked scan
over the HBM ring with an on-device sort — the whole store is filtered in
one XLA program and only the top-``limit`` rows travel to the host.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.store import EventStore
from sitewhere_tpu.core.types import NULL_ID
from sitewhere_tpu.ops.segment import lex_argsort


class QueryResult(NamedTuple):
    n: jax.Array        # int32[] matches (capped at limit)
    total: jax.Array    # int32[] total matches in store
    etype: jax.Array    # int32[limit]
    device: jax.Array
    assignment: jax.Array
    tenant: jax.Array
    area: jax.Array
    customer: jax.Array
    ts_ms: jax.Array
    received_ms: jax.Array
    values: jax.Array   # float32[limit, C]
    vmask: jax.Array
    aux: jax.Array


@functools.partial(jax.jit, static_argnames=("limit",))
def query_store(
    store: EventStore,
    device: jax.Array,   # int32[] filter (NULL_ID = any)
    etype: jax.Array,    # int32[] filter (NULL_ID = any)
    tenant: jax.Array,   # int32[] filter (NULL_ID = any)
    t0: jax.Array,       # int32[] inclusive lower ts bound
    t1: jax.Array,       # int32[] inclusive upper ts bound
    limit: int = 100,
    assignment: jax.Array | None = None,  # int32[] filter (NULL_ID = any)
    aux0: jax.Array | None = None,        # int32[] filter on aux[:, 0]
    aux1: jax.Array | None = None,        # int32[] filter on aux[:, 1]
    area: jax.Array | None = None,        # int32[] filter (NULL_ID = any)
    customer: jax.Array | None = None,    # int32[] filter (NULL_ID = any)
) -> QueryResult:
    """Newest-first filtered query over the whole ring."""
    m = store.valid
    m &= (device == NULL_ID) | (store.device == device)
    m &= (etype == NULL_ID) | (store.etype == etype)
    m &= (tenant == NULL_ID) | (store.tenant == tenant)
    if assignment is not None:
        m &= (assignment == NULL_ID) | (store.assignment == assignment)
    if aux0 is not None:
        m &= (aux0 == NULL_ID) | (store.aux[:, 0] == aux0)
    if aux1 is not None:
        m &= (aux1 == NULL_ID) | (store.aux[:, 1] == aux1)
    if area is not None:
        m &= (area == NULL_ID) | (store.area == area)
    if customer is not None:
        m &= (customer == NULL_ID) | (store.customer == customer)
    m &= (store.ts_ms >= t0) & (store.ts_ms <= t1)
    total = jnp.sum(m.astype(jnp.int32))
    # sort newest first: key = (-match, -ts)
    neg_ts = -jnp.maximum(store.ts_ms, jnp.iinfo(jnp.int32).min + 1)
    _, perm = lex_argsort([(~m).astype(jnp.int32), neg_ts])
    top = perm[:limit]
    n = jnp.minimum(total, limit)
    return QueryResult(
        n=n,
        total=total,
        etype=store.etype[top],
        device=store.device[top],
        assignment=store.assignment[top],
        tenant=store.tenant[top],
        area=store.area[top],
        customer=store.customer[top],
        ts_ms=store.ts_ms[top],
        received_ms=store.received_ms[top],
        values=store.values[top],
        vmask=store.vmask[top],
        aux=store.aux[top],
    )
