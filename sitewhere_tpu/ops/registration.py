"""Batched device auto-registration.

The reference routes events for unknown devices to an unregistered-device
topic; service-device-registration consumes it and get-or-creates the device
with a default device type / customer / area, ensures an assignment, and acks
(registration/DeviceRegistrationManager.java:44-164, single-thread executor at
line 66). Here registration is a batched kernel over the miss-set produced by
ops/lookup.py: unknown tokens are deduplicated in-batch, allocated dense
device + assignment rows from device-resident counters, and written into the
registry tables in one shot — the host mirrors the allocation deterministically
(same order, same ids) from the returned new-token list.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.registry import RegistryTables
from sitewhere_tpu.core.types import NULL_ID, DeviceAssignmentStatus
from sitewhere_tpu.ops.segment import INT32_MAX, compact_valid_front


class RegistrationResult(NamedTuple):
    registry: RegistryTables
    next_device: jax.Array       # int32[] updated allocation counter
    next_assignment: jax.Array   # int32[]
    n_registered: jax.Array      # int32[] new devices this batch
    # compacted [B] list of newly registered token ids (NULL_ID padded) so the
    # host can mirror metadata + fire RegistrationAck system commands
    new_tokens: jax.Array        # int32[B]
    overflow: jax.Array          # bool[] capacity exhausted (dead-letter)


def register_misses(
    reg: RegistryTables,
    next_device: jax.Array,
    next_assignment: jax.Array,
    token_id: jax.Array,    # int32[B]
    tenant_id: jax.Array,   # int32[B]
    miss: jax.Array,        # bool[B] unregistered-device rows from lookup
    default_type: jax.Array,      # int32[] default device type id
    default_area: jax.Array,      # int32[]
    default_customer: jax.Array,  # int32[]
) -> RegistrationResult:
    """Register every distinct missed token: device row + ACTIVE assignment."""
    b = token_id.shape[0]
    t = reg.token_capacity
    n = reg.device_capacity
    g = reg.assignment_capacity

    safe_tok = jnp.clip(token_id, 0, t - 1)
    known = reg.token_to_device[safe_tok] != NULL_ID
    want = miss & ~known & (token_id >= 0) & (token_id < t)

    # dedup within batch: first occurrence of each token wins
    seq = jnp.arange(b, dtype=jnp.int32)
    tok_w = jnp.where(want, token_id, t)
    first = jnp.full((t,), INT32_MAX, jnp.int32).at[tok_w].min(seq, mode="drop")
    winner = want & (seq == first.at[safe_tok].get(mode="fill", fill_value=INT32_MAX))

    # dense rank among winners -> allocated ids
    rank = jnp.cumsum(winner.astype(jnp.int32)) - 1
    n_new = jnp.sum(winner.astype(jnp.int32))
    new_dev = next_device + rank
    new_asn = next_assignment + rank
    fits = winner & (new_dev < n) & (new_asn < g)
    overflow = n_new > jnp.sum(fits.astype(jnp.int32))

    dev_w = jnp.where(fits, new_dev, n)
    asn_w = jnp.where(fits, new_asn, g)
    tok_ww = jnp.where(fits, token_id, t)

    registry = dataclasses.replace(
        reg,
        token_to_device=reg.token_to_device.at[tok_ww].set(new_dev, mode="drop"),
        device_active=reg.device_active.at[dev_w].set(True, mode="drop"),
        device_type=reg.device_type.at[dev_w].set(default_type, mode="drop"),
        device_tenant=reg.device_tenant.at[dev_w].set(tenant_id, mode="drop"),
        device_area=reg.device_area.at[dev_w].set(default_area, mode="drop"),
        device_customer=reg.device_customer.at[dev_w].set(default_customer, mode="drop"),
        device_assignments=reg.device_assignments.at[dev_w, 0].set(new_asn, mode="drop"),
        assignment_active=reg.assignment_active.at[asn_w].set(True, mode="drop"),
        assignment_status=reg.assignment_status.at[asn_w].set(
            jnp.int32(DeviceAssignmentStatus.ACTIVE), mode="drop"
        ),
        assignment_device=reg.assignment_device.at[asn_w].set(new_dev, mode="drop"),
        assignment_area=reg.assignment_area.at[asn_w].set(default_area, mode="drop"),
        assignment_customer=reg.assignment_customer.at[asn_w].set(default_customer, mode="drop"),
    )

    n_fit = jnp.sum(fits.astype(jnp.int32))
    _, perm = compact_valid_front(fits)
    new_tokens = jnp.where(jnp.arange(b) < n_fit, token_id[perm], NULL_ID)

    return RegistrationResult(
        registry=registry,
        next_device=next_device + n_fit,
        next_assignment=next_assignment + n_fit,
        n_registered=n_fit,
        new_tokens=new_tokens,
        overflow=overflow,
    )
