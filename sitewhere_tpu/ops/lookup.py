"""Batched device lookup + assignment expansion.

Replaces the reference's inbound-processing hot loop — a blocking gRPC
``getDeviceByToken`` per message followed by an active-assignments RPC and a
flatMap to one payload per assignment
(service-inbound-processing/.../kafka/DecodedEventsPipeline.java:87-115,
DeviceLookupMapper.java:50-93, DeviceAssignmentsLookupMapper /
PreprocessedEventMapper) — with two gathers over device-resident registry
tables. The not-found branch (DecodedEventsPipeline.java:96-106, which feeds
the unregistered-device-events topic) becomes the returned ``miss`` mask.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.registry import MAX_ACTIVE_ASSIGNMENTS, RegistryTables
from sitewhere_tpu.core.types import NULL_ID


class LookupResult(NamedTuple):
    device: jax.Array       # int32[B] dense device id (NULL_ID on miss)
    found: jax.Array        # bool[B]  valid event and device registered+active
    miss: jax.Array         # bool[B]  valid event but unregistered/inactive
    tenant_ok: jax.Array    # bool[B]  event tenant matches device tenant
    assignments: jax.Array  # int32[B, A] active assignment ids (NULL_ID pads)
    n_assignments: jax.Array  # int32[B]


def lookup_devices(
    reg: RegistryTables,
    token_id: jax.Array,
    tenant_id: jax.Array,
    valid: jax.Array,
) -> LookupResult:
    """Vectorized device/assignment lookup for one event batch."""
    # out-of-range token ids must miss, not alias into clipped slots
    in_range = (token_id >= 0) & (token_id < reg.token_capacity)
    safe_tok = jnp.clip(token_id, 0, reg.token_capacity - 1)
    device = jnp.where(valid & in_range, reg.token_to_device[safe_tok], NULL_ID)
    has_row = device != NULL_ID
    safe_dev = jnp.clip(device, 0, reg.device_capacity - 1)
    active = jnp.where(has_row, reg.device_active[safe_dev], False)
    dev_tenant = jnp.where(has_row, reg.device_tenant[safe_dev], NULL_ID)
    tenant_ok = has_row & ((tenant_id == NULL_ID) | (dev_tenant == tenant_id))
    found = valid & has_row & active & tenant_ok
    miss = valid & ~found
    assignments = jnp.where(
        found[:, None], reg.device_assignments[safe_dev], NULL_ID
    )
    # only ACTIVE assignment slots expand into events
    safe_asn = jnp.clip(assignments, 0, reg.assignment_capacity - 1)
    asn_live = (assignments != NULL_ID) & reg.assignment_active[safe_asn]
    assignments = jnp.where(asn_live, assignments, NULL_ID)
    n_assignments = jnp.sum(asn_live.astype(jnp.int32), axis=1)
    return LookupResult(
        device=jnp.where(found, device, NULL_ID),
        found=found,
        miss=miss,
        tenant_ok=tenant_ok,
        assignments=assignments,
        n_assignments=n_assignments,
    )


class ExpandedEvents(NamedTuple):
    """Per-assignment expansion of an event batch, flattened to B*A rows —
    the TPU analog of PreprocessedEventMapper's one-payload-per-assignment
    flatMap."""

    valid: jax.Array       # bool[B*A]
    device: jax.Array      # int32[B*A]
    assignment: jax.Array  # int32[B*A]
    area: jax.Array        # int32[B*A]
    customer: jax.Array    # int32[B*A]
    asset: jax.Array       # int32[B*A]
    source_row: jax.Array  # int32[B*A] row in the original batch


def expand_assignments(reg: RegistryTables, res: LookupResult) -> ExpandedEvents:
    b, a = res.assignments.shape
    asn = res.assignments.reshape(-1)
    live = asn != NULL_ID
    safe = jnp.clip(asn, 0, reg.assignment_capacity - 1)
    device = jnp.repeat(res.device, a)
    source_row = jnp.repeat(jnp.arange(b, dtype=jnp.int32), a)
    return ExpandedEvents(
        valid=live,
        device=jnp.where(live, device, NULL_ID),
        assignment=jnp.where(live, asn, NULL_ID),
        area=jnp.where(live, reg.assignment_area[safe], NULL_ID),
        customer=jnp.where(live, reg.assignment_customer[safe], NULL_ID),
        asset=jnp.where(live, reg.assignment_asset[safe], NULL_ID),
        source_row=source_row,
    )


__all__ = [
    "LookupResult",
    "ExpandedEvents",
    "lookup_devices",
    "expand_assignments",
    "MAX_ACTIVE_ASSIGNMENTS",
]
