"""Fused per-device window feature extraction (Pallas TPU kernel).

Computes analytics features over the HBM-resident telemetry windows
(models/windows.py, [M, W, C] float32): per (device, channel) mean, std,
min, max, last value, and first-to-last delta — the feature front-end for
anomaly scoring and drift detection in the tpu-analytics service, and the
input normalization pass for models/anomaly.py.

The Pallas kernel makes this ONE pass over HBM per tile (six reductions
fused in VMEM, single read of the window data), where the naive jnp
version materializes multiple reduction intermediates. The reference has
no equivalent: it re-queries time-series DBs for any analysis. A jnp
reference implementation is used on non-TPU backends and as the test
oracle.

Feature layout (axis -1): [mean, std, min, max, last, delta].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NUM_FEATURES = 6


def window_features_reference(windows: jax.Array) -> jax.Array:
    """jnp oracle: [M, W, C] -> [M, C, NUM_FEATURES]."""
    mean = jnp.mean(windows, axis=1)
    std = jnp.std(windows, axis=1)
    mn = jnp.min(windows, axis=1)
    mx = jnp.max(windows, axis=1)
    last = windows[:, -1, :]
    delta = windows[:, -1, :] - windows[:, 0, :]
    return jnp.stack([mean, std, mn, mx, last, delta], axis=-1)


def _features_kernel(win_ref, out_ref):
    """One tile: win [TM, C, W] -> out [TM, C, F].

    The window axis W sits on the TPU lane dimension (width 128-friendly),
    so reductions run across lanes and the narrow channel axis (typically 8)
    lives on sublanes — the [.., W, C] layout would pad C to 128 lanes and
    blow VMEM 16x."""
    w = win_ref[:]                       # [TM, C, W]
    n = w.shape[2]
    mean = jnp.mean(w, axis=2)           # [TM, C]
    # population std to match jnp.std
    var = jnp.mean(jnp.square(w), axis=2) - jnp.square(mean)
    std = jnp.sqrt(jnp.maximum(var, 0.0))
    mn = jnp.min(w, axis=2)
    mx = jnp.max(w, axis=2)
    last = w[:, :, n - 1]
    delta = last - w[:, :, 0]
    out_ref[:] = jnp.stack([mean, std, mn, mx, last, delta], axis=-1)


@functools.partial(jax.jit, static_argnames=("tile_m", "force_pallas"))
def window_features(windows: jax.Array, tile_m: int = 256,
                    force_pallas: bool = False) -> jax.Array:
    """[M, W, C] -> [M, C, NUM_FEATURES]. Uses the Pallas kernel on TPU
    (or when forced, e.g. interpret-mode tests); jnp elsewhere."""
    m, w, c = windows.shape
    on_tpu = jax.default_backend() == "tpu"
    if not (on_tpu or force_pallas):
        return window_features_reference(windows)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tile = min(tile_m, m)
    if m % tile:
        pad = tile - m % tile
        windows = jnp.pad(windows, ((0, pad), (0, 0), (0, 0)))
        mp = m + pad
    else:
        mp = m
    wt = jnp.swapaxes(windows.astype(jnp.float32), 1, 2)  # [M, C, W]
    out = pl.pallas_call(
        _features_kernel,
        grid=(mp // tile,),
        in_specs=[pl.BlockSpec((tile, c, w), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((tile, c, NUM_FEATURES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((mp, c, NUM_FEATURES), jnp.float32),
        interpret=not on_tpu,
    )(wt)
    return out[:m]


# devicewatch (ISSUE 11): the analytics feature extractor (Pallas on
# TPU) reports compiles under its own family — a window-shape churn in
# the anomaly service shows up here, not as silent recompile stalls.
from sitewhere_tpu.utils.devicewatch import watched_jit  # noqa: E402

window_features = watched_jit(window_features, family="window_features",
                              static_argnames=("tile_m", "force_pallas"))


def normalize_windows(windows: jax.Array, features: jax.Array,
                      eps: float = 1e-6) -> jax.Array:
    """Standardize windows with the extracted per-channel mean/std — the
    input conditioning for the anomaly models."""
    mean = features[:, :, 0][:, None, :]
    std = features[:, :, 1][:, None, :]
    return (windows - mean) / (std + eps)
