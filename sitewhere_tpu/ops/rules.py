"""Fused streaming-rule + continuous-rollup kernels (the CEP tier).

The reference ships Siddhi 3.1.2 as its complex-event-processing layer:
standing queries over the event stream (thresholds, windowed aggregates,
sequences, absence patterns) evaluated per event in a JVM loop. Here a
rule SET lowers into device-resident parameter tables + carried state
arrays that ride INSIDE the already-running fused ingest step — a
standing rule is a predicate that never leaves the batch (the
``ops/query.query_store_batch`` shared-scan argument, applied to rules).

Cost discipline: the ingest overhead gate is ≤3% of the fused step, so
the kernel avoids the two expensive vector idioms on both backends —
**no scatters and no associative scans on the rules path**. One stable
two-key sort per group scope orders the batch into (group, time) runs;
everything else is cumulative-max/cumsum prefixes, ``searchsorted``
run maps, and gathers:

  * per-group run bounds come from ``searchsorted`` over the sorted
    group column (groups are ascending, so each group's run is an
    interval);
  * "most recent selected row at-or-before me" (the sequence A-mark,
    the absence previous-match, first-fire-of-key detection) is a
    GLOBAL ``lax.cummax`` over selected row indices, guarded by the
    run/window start index — valid because within a run the sort makes
    timestamps ascending;
  * segmented count/sum prefixes are a global ``cumsum`` minus its
    value at the segment head (exact for ints; exact for float sums of
    exactly-representable values — the parity gates use binary halves);
  * pending fires are looked up by rank via ``searchsorted`` over the
    global new-key cumsum — up to K distinct fired keys per (rule,
    group) per batch land in the pending ring, oldest dropped and
    counted.

The static ``layout`` (kind/scope/agg/ops per rule) is pytree METADATA:
the compiled program specializes per rule kind — a parameter tweak
(thresholds, windows, channels) is a plain array swap with zero
recompiles, while a structural change recompiles under the declared
swap's devicewatch allowance.

Determinism contract (the replay/standby parity oracle rides on it):
every update and fire decision is a pure function of the EVENT STREAM
(event-time ``ts_ms``, values, group ids) — never the host clock, never
``received_ms`` — and is **batch-partition invariant**: splitting the
same stream into different batch boundaries yields the same carried
state and the same fire KEY set. Window (agg, op) combinations are
restricted to monotone pairs at model-validation time, so "the window
crossed" is observable at any batch end under the same window key;
threshold rules lower to extremum windows and fire on the crossing
event itself; absence fires are keyed by the ``last_seen`` timestamp
that opened the silence. Fire keys (window id / silence-opening
timestamp) are the device half of the ``rule+group+window`` dedup
discipline; the host half (rules/manager.py) turns them into alert
alternate-ids.

Known boundary: sequence pairing and absence silence detection assume
per-group EVENT-TIME order matches arrival order (true of real device
streams and preserved verbatim by WAL replay). A late event — one
arriving after the global watermark already passed its group's
deadline — can make an absence key partition-dependent: the trailing
check may fire a silence that the late arrival would have closed. Such
fires are still deduped within any one partition; operators ingesting
heavily out-of-order streams should size ``deadlineMs`` above their
lateness bound (the standard CEP allowed-lateness discipline).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from sitewhere_tpu.core.types import NULL_ID
from sitewhere_tpu.ops.segment import INT32_MIN, lex_argsort

# rule kinds (KIND_THRESHOLD lowers to KIND_WINDOW in the model — see
# module docstring — so the kernel only knows three)
KIND_WINDOW = 0
KIND_SEQUENCE = 1
KIND_ABSENCE = 2

# group scopes
SCOPE_DEVICE = 0
SCOPE_AREA = 1
SCOPE_TENANT = 2

# comparison ops
OP_GT = 0
OP_GE = 1
OP_LT = 2
OP_LE = 3
NO_PRED = -1

# window aggregates
AGG_COUNT = 0
AGG_SUM = 1
AGG_MIN = 2
AGG_MAX = 3

F32_INF = jnp.float32(jnp.inf)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RuleBlock:
    """R rules over G group slots. ``layout`` is STATIC structure (the
    program specializes on it); the table columns are runtime PARAMETERS
    (editable without a shape change — a threshold tweak hot-swaps with
    zero recompiles); state columns are the carried accumulators donated
    through every step with the rest of PipelineState."""

    # static per-rule structure: ((kind, scope, agg, op_a, op_b), ...)
    layout: tuple = dataclasses.field(metadata=dict(static=True))

    # ------------------------------------------------ parameters, [R]
    active: jax.Array     # bool[R]
    etype: jax.Array      # int32[R] event-type filter (NULL_ID = any)
    tenant: jax.Array     # int32[R] tenant filter (NULL_ID = any)
    ch_a: jax.Array       # int32[R] predicate-A value channel
    val_a: jax.Array      # float32[R]
    ch_b: jax.Array       # int32[R] predicate-B channel (sequence /
    val_b: jax.Array      # float32[R]   window contributing filter)
    window_ms: jax.Array  # int32[R] window / pair horizon / deadline

    # ------------------------------------------------ carried state
    wm: jax.Array         # int32[] event-time watermark (max ts seen)
    acc_wid: jax.Array    # int32[R, G] window id being accumulated
    acc_cnt: jax.Array    # int32[R, G] (count/sum windows)
    acc_sum: jax.Array    # float32[R, G]
    mark_ts: jax.Array    # int32[R, G] seq: last pred-A ts; absence:
    #                       last matching ts (INT32_MIN = never)
    fired_key: jax.Array  # int32[R, G] newest fired key (dedup guard)
    # pending-fire ring per (rule, group): up to K un-harvested fires
    # survive between polls; overflow drops the OLDEST (counted in
    # ``missed`` — the oldest are the ones a previous owner most likely
    # already emitted)
    pend_key: jax.Array   # int32[R, G, K]
    pend_val: jax.Array   # float32[R, G, K]
    pend_w: jax.Array     # int32[R, G] total fires written (ring cursor)
    pend_h: jax.Array     # int32[R, G] fires harvested
    fires: jax.Array      # int32[] distinct keys fired (partition-inv.)
    missed: jax.Array     # int32[] fires dropped (ring overflow)
    late: jax.Array       # int32[] events older than their window carry
    oob: jax.Array        # int32[] matches whose group id >= G

    @property
    def n_rules(self) -> int:
        return len(self.layout)

    @property
    def groups(self) -> int:
        return self.acc_wid.shape[1]

    @property
    def pend_depth(self) -> int:
        return self.pend_key.shape[2]

    @staticmethod
    def zeros(table: dict, layout: tuple, groups: int,
              pending: int = 4) -> "RuleBlock":
        """Fresh state for a lowered parameter table (``table`` maps the
        parameter field names to numpy arrays of length R == len(layout));
        ``layout`` is the static per-rule (kind, scope, agg, op_a, op_b)
        structure."""
        r = len(layout)
        g = int(groups)
        k = max(1, int(pending))
        i32 = jnp.int32
        return RuleBlock(
            layout=tuple(tuple(int(x) for x in row) for row in layout),
            active=jnp.asarray(table["active"], jnp.bool_),
            **{kk: jnp.asarray(table[kk], i32)
               for kk in ("etype", "tenant", "ch_a", "ch_b",
                          "window_ms")},
            val_a=jnp.asarray(table["val_a"], jnp.float32),
            val_b=jnp.asarray(table["val_b"], jnp.float32),
            wm=jnp.asarray(INT32_MIN, i32),
            acc_wid=jnp.full((r, g), INT32_MIN, i32),
            acc_cnt=jnp.zeros((r, g), i32),
            acc_sum=jnp.zeros((r, g), jnp.float32),
            mark_ts=jnp.full((r, g), INT32_MIN, i32),
            fired_key=jnp.full((r, g), INT32_MIN, i32),
            pend_key=jnp.full((r, g, k), INT32_MIN, i32),
            pend_val=jnp.zeros((r, g, k), jnp.float32),
            pend_w=jnp.zeros((r, g), i32),
            pend_h=jnp.zeros((r, g), i32),
            fires=jnp.zeros((), i32),
            missed=jnp.zeros((), i32),
            late=jnp.zeros((), i32),
            oob=jnp.zeros((), i32),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RollupBlock:
    """P continuous rollups, each a [G, NB] ring of tumbling time-window
    aggregates of one value channel per device/area/tenant group,
    maintained incrementally in-step and served by the query path. Stat
    lanes pack two-wide so each ring update is three scatter passes
    total (newest-window-id, add(count, sum), max(max, -min))."""

    channel: jax.Array    # int32[P]
    scope: jax.Array      # int32[P] SCOPE_*
    etype: jax.Array      # int32[P] (NULL_ID = any)
    window_ms: jax.Array  # int32[P]
    wid: jax.Array        # int32[P, G, NB] window id held by each slot
    adds: jax.Array       # float32[P, G, NB, 2] (count, sum) — counts
    #                       are exact in f32 below 2^24
    exts: jax.Array       # float32[P, G, NB, 2] (max, -min)
    late: jax.Array       # int32[] events older than their slot's window

    # ---- named views (the read surface the manager/tests consume)
    @property
    def cnt(self):
        return self.adds[..., 0].astype(jnp.int32)

    @property
    def vsum(self):
        return self.adds[..., 1]

    @property
    def vmax(self):
        return self.exts[..., 0]

    @property
    def vmin(self):
        return -self.exts[..., 1]

    @property
    def n_rollups(self) -> int:
        return self.channel.shape[0]

    @property
    def groups(self) -> int:
        return self.wid.shape[1]

    @property
    def buckets(self) -> int:
        return self.wid.shape[2]

    @staticmethod
    def zeros(table: dict, groups: int, buckets: int) -> "RollupBlock":
        p = len(table["channel"])
        g, nb = int(groups), int(buckets)
        i32 = jnp.int32
        return RollupBlock(
            **{k: jnp.asarray(table[k], i32)
               for k in ("channel", "scope", "etype", "window_ms")},
            wid=jnp.full((p, g, nb), INT32_MIN, i32),
            adds=jnp.zeros((p, g, nb, 2), jnp.float32),
            exts=jnp.full((p, g, nb, 2), -F32_INF),
            late=jnp.zeros((), i32),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RulesState:
    """The CEP tier's slice of PipelineState (``state.rules``)."""

    rules: RuleBlock | None = None
    rollups: RollupBlock | None = None


# --------------------------------------------------------------------------
# kernel helpers
# --------------------------------------------------------------------------

def _cmp_static(v, op: int, ref):
    """Comparison with a STATIC op code (specialized at trace time)."""
    if op == OP_GT:
        return v > ref
    if op == OP_GE:
        return v >= ref
    if op == OP_LT:
        return v < ref
    return v <= ref


def _chans(batch, ch):
    """Per-rule value channels gathered in ONE pass: [B, R] values and
    populated-masks for a traced channel-index vector."""
    return jnp.take(batch.values, ch, axis=1), jnp.take(batch.vmask, ch,
                                                        axis=1)


def _last_at_or_before(sel, iota, guard_start):
    """For each row, the index of the newest SELECTED row strictly
    before it within its segment (INT32-style -1 when none): a global
    running max over selected indices, shifted one row and guarded by
    the segment-start index. Valid because rows are (group, ts)-sorted,
    so "newest index" == "newest timestamp"."""
    last = lax.cummax(jnp.where(sel, iota, -1))
    prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), last[:-1]])
    return jnp.where(prev >= guard_start, prev, -1)


class _ScopeView:
    """One (group, ts)-sorted view of the batch, shared by every rule of
    a scope: permutation, sorted group/ts columns, run-start indices and
    per-group run bounds (``searchsorted`` over the ascending groups)."""

    __slots__ = ("perm", "g_s", "ts_s", "live", "seg_start", "start_idx",
                 "lo", "ends", "has", "iota")

    def __init__(self, gcol, ts, groups):
        b = gcol.shape[0]
        (self.g_s, self.ts_s), self.perm = lex_argsort([gcol, ts])
        self.live = self.g_s < groups
        self.iota = jnp.arange(b, dtype=jnp.int32)
        self.seg_start = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), self.g_s[1:] != self.g_s[:-1]])
        self.start_idx = lax.cummax(
            jnp.where(self.seg_start, self.iota, -1))
        gid = jnp.arange(groups, dtype=jnp.int32)
        self.lo = jnp.searchsorted(self.g_s, gid, side="left"
                                   ).astype(jnp.int32)
        self.ends = (jnp.searchsorted(self.g_s, gid, side="right")
                     .astype(jnp.int32) - 1)
        self.has = self.ends >= self.lo


def _ring_push_multi(pend_key, pend_val, pend_w, pend_h, fired_key,
                     sv: _ScopeView, new_key, key_e, val_e):
    """Push every distinct fired key (per group, run order, newest-K
    kept) into the [G, K] pending ring — rank lookups via searchsorted
    over the global new-key cumsum; no scatters. Returns updated ring +
    cursors + fired_key and the (fires, missed) deltas."""
    g, k = pend_key.shape
    nk = new_key.astype(jnp.int32)
    c_glob = jnp.cumsum(nk)
    lo_safe = jnp.where(sv.has, sv.lo, 0)
    end_safe = jnp.where(sv.has, sv.ends, 0)
    base = jnp.where(sv.has, c_glob[lo_safe] - nk[lo_safe], 0)
    c_g = jnp.where(sv.has, c_glob[end_safe] - base, 0)       # [G]
    kept = jnp.minimum(c_g, k)
    # ranks (1-based within the run's new-key rows) of the kept fires
    jj = jnp.arange(k, dtype=jnp.int32)[None, :]              # [1, K]
    want = jj < kept[:, None]
    target = base[:, None] + (c_g - kept)[:, None] + jj + 1
    rows = jnp.searchsorted(c_glob, jnp.where(want, target, -1),
                            side="left").astype(jnp.int32)
    rows = jnp.clip(rows, 0, new_key.shape[0] - 1)
    keys_gk = key_e[rows]
    vals_gk = val_e[rows]
    slot = (pend_w[:, None] + jj) % k
    onehot = slot[:, :, None] == jnp.arange(k)[None, None, :]  # [G,K,K]
    write = want[:, :, None] & onehot
    pend_key = jnp.where(jnp.any(write, 1),
                         jnp.sum(jnp.where(write, keys_gk[:, :, None], 0),
                                 axis=1),
                         pend_key)
    pend_val = jnp.where(jnp.any(write, 1),
                         jnp.sum(jnp.where(write, vals_gk[:, :, None],
                                           0.0), axis=1),
                         pend_val)
    pending_before = jnp.clip(pend_w - pend_h, 0, k)
    missed = (jnp.sum(jnp.maximum(0, pending_before + kept - k))
              + jnp.sum(c_g - kept))
    pend_w = pend_w + c_g
    last_key = jnp.where(c_g > 0, keys_gk[jnp.arange(g), kept - 1],
                         INT32_MIN)
    fired_key = jnp.maximum(fired_key, last_key)
    return (pend_key, pend_val, pend_w, fired_key,
            jnp.sum(c_g), missed)


def _pend_push_one(pend_key, pend_val, pend_w, pend_h, fire, key, val):
    """Append at most one fire per group (the absence trailing check)."""
    k = pend_key.shape[1]
    slot = pend_w % k
    onehot = slot[:, None] == jnp.arange(k)[None, :]
    write = fire[:, None] & onehot
    overflow = fire & (pend_w - pend_h >= k)
    return (jnp.where(write, key[:, None], pend_key),
            jnp.where(write, val[:, None], pend_val),
            pend_w + fire.astype(jnp.int32),
            jnp.sum(overflow.astype(jnp.int32)))


def _rules_block_update(rb: RuleBlock, batch, dev, area,
                        base_valid) -> RuleBlock:
    g = rb.groups
    ts = batch.ts_ms
    wm_new = jnp.maximum(
        rb.wm, jnp.max(jnp.where(batch.valid, ts, INT32_MIN)))
    gcols = {SCOPE_DEVICE: dev, SCOPE_AREA: area,
             SCOPE_TENANT: batch.tenant_id}
    views: dict[int, _ScopeView] = {}
    new_state = {f: [] for f in ("acc_wid", "acc_cnt", "acc_sum",
                                 "mark_ts", "fired_key", "pend_key",
                                 "pend_val", "pend_w")}
    fires_n = jnp.zeros((), jnp.int32)
    missed_n = jnp.zeros((), jnp.int32)
    late_n = jnp.zeros((), jnp.int32)
    oob_n = jnp.zeros((), jnp.int32)
    va_all, vma_all = _chans(batch, rb.ch_a)          # [B, R]
    vb_all, vmb_all = _chans(batch, rb.ch_b)

    for r, (kind, scope, agg, op_a, op_b) in enumerate(rb.layout):
        sv = views.get(scope)
        if sv is None:
            gc = gcols[scope]
            key = jnp.where(base_valid & (gc >= 0) & (gc < g), gc, g)
            sv = views[scope] = _ScopeView(key, ts, g)
        win = jnp.maximum(rb.window_ms[r], 1)
        et_ok = (rb.etype[r] == NULL_ID) | (batch.etype == rb.etype[r])
        tn_ok = ((rb.tenant[r] == NULL_ID)
                 | (batch.tenant_id == rb.tenant[r]))
        ev_ok = base_valid & et_ok & tn_ok & rb.active[r]
        v_a, vm_a = va_all[:, r], vma_all[:, r]
        # out-of-capacity groups: count matches that fell off the table
        oob_raw = ev_ok & vm_a & ((gcols[scope] < 0)
                                  | (gcols[scope] >= g))
        oob_n += jnp.sum(oob_raw.astype(jnp.int32))

        ts_s = sv.ts_s
        g_safe = jnp.minimum(sv.g_s, g - 1)
        fired_row = jnp.where(sv.live, rb.fired_key[r][g_safe],
                              jnp.iinfo(jnp.int32).max)

        acc_wid_r, acc_cnt_r, acc_sum_r = (rb.acc_wid[r], rb.acc_cnt[r],
                                           rb.acc_sum[r])
        mark_r = rb.mark_ts[r]
        fired_r = rb.fired_key[r]

        if kind == KIND_WINDOW:
            m = ev_ok & vm_a
            if op_b != NO_PRED:   # contributing-event filter
                m &= vmb_all[:, r] & _cmp_static(vb_all[:, r], op_b,
                                                 rb.val_b[r])
            m_s = m[sv.perm] & sv.live
            v_s = v_a[sv.perm]
            wid = ts_s // win
            prev_wid = jnp.concatenate([wid[:1] - 1, wid[:-1]])
            wstart = sv.seg_start | (wid != prev_wid)
            wstart_idx = lax.cummax(jnp.where(wstart, sv.iota, -1))
            cw = jnp.where(sv.live, acc_wid_r[g_safe], INT32_MIN)
            join = (cw > INT32_MIN) & (wid == cw)
            late_n += jnp.sum((m_s & (wid < cw)).astype(jnp.int32))
            eff = m_s & (wid >= cw)
            if agg in (AGG_COUNT, AGG_SUM):
                x = (jnp.where(eff, 1, 0).astype(jnp.int32)
                     if agg == AGG_COUNT else jnp.where(eff, v_s, 0.0))
                cx = jnp.cumsum(x)
                seg = cx - (cx[wstart_idx] - x[wstart_idx])  # inclusive
                carry = jnp.where(
                    join,
                    (acc_cnt_r[g_safe] if agg == AGG_COUNT
                     else acc_sum_r[g_safe]),
                    jnp.zeros((), x.dtype))
                tot = seg + carry
                totf = tot.astype(jnp.float32)
                fire = (eff & _cmp_static(totf, op_a, rb.val_a[r])
                        & (wid > fired_row))
                # first fire of a window: the exclusive total had not
                # crossed (carry-crossed windows fired a batch ago and
                # are blocked by the dedup guard)
                new_key = fire & ~_cmp_static(
                    (tot - x).astype(jnp.float32), op_a, rb.val_a[r])
                key_e, val_e = wid, totf
                # run-end accumulator (totals of the newest window)
                end_safe = jnp.where(sv.has, sv.ends, 0)
                wid_end = wid[end_safe]
                upd = sv.has & (wid_end >= jnp.where(
                    acc_wid_r > INT32_MIN, acc_wid_r, INT32_MIN))
                tot_end = tot[end_safe]
                if agg == AGG_COUNT:
                    acc_cnt_r = jnp.where(upd, tot_end, acc_cnt_r)
                else:
                    acc_sum_r = jnp.where(upd, tot_end, acc_sum_r)
                acc_wid_r = jnp.where(upd, wid_end, acc_wid_r)
            else:
                # extremum windows (thresholds lower here): the running
                # max/min crosses exactly when some EVENT crosses, so
                # fires are per-event with no accumulator at all
                cross = eff & _cmp_static(v_s, op_a, rb.val_a[r])
                fire = cross & (wid > fired_row)
                prior = _last_at_or_before(cross, sv.iota, wstart_idx)
                new_key = fire & (prior < 0)
                key_e, val_e = wid, v_s
                end_safe = jnp.where(sv.has, sv.ends, 0)
                wid_end = wid[end_safe]
                upd = sv.has & (wid_end >= acc_wid_r)
                acc_wid_r = jnp.where(upd, wid_end, acc_wid_r)
        elif kind == KIND_SEQUENCE:
            m_a = (ev_ok & vm_a
                   & _cmp_static(v_a, op_a, rb.val_a[r]))[sv.perm] \
                & sv.live
            m_b = (ev_ok & vmb_all[:, r]
                   & _cmp_static(vb_all[:, r], op_b,
                                 rb.val_b[r]))[sv.perm] & sv.live
            prev_a = _last_at_or_before(m_a, sv.iota, sv.start_idx)
            a_ts = jnp.where(prev_a >= 0,
                             ts_s[jnp.maximum(prev_a, 0)],
                             jnp.where(sv.live, mark_r[g_safe],
                                       INT32_MIN))
            fire = (m_b & (a_ts > INT32_MIN) & (ts_s >= a_ts)
                    & (ts_s - a_ts <= win))
            key_e = ts_s // win
            fire &= key_e > fired_row
            val_e = (ts_s - a_ts).astype(jnp.float32)
            prev_f = _last_at_or_before(fire, sv.iota, sv.start_idx)
            new_key = fire & ((prev_f < 0)
                              | (key_e[jnp.maximum(prev_f, 0)] != key_e))
        else:  # KIND_ABSENCE
            m_a = (ev_ok & vm_a
                   & _cmp_static(v_a, op_a, rb.val_a[r]))[sv.perm] \
                & sv.live
            prev_m = _last_at_or_before(m_a, sv.iota, sv.start_idx)
            prev_ts = jnp.where(prev_m >= 0,
                                ts_s[jnp.maximum(prev_m, 0)],
                                jnp.where(sv.live, mark_r[g_safe],
                                          INT32_MIN))
            # a match after a silence longer than the deadline fires,
            # keyed by the silence-opening timestamp
            fire = (m_a & (prev_ts > INT32_MIN)
                    & (ts_s - prev_ts > win))
            key_e = prev_ts
            fire &= key_e > fired_row
            val_e = (ts_s - prev_ts).astype(jnp.float32)
            prev_f = _last_at_or_before(fire, sv.iota, sv.start_idx)
            new_key = fire & ((prev_f < 0)
                              | (key_e[jnp.maximum(prev_f, 0)] != key_e))

        if kind in (KIND_SEQUENCE, KIND_ABSENCE):
            # mark = newest pred-A/matching timestamp (run-end gather)
            last_sel = lax.cummax(jnp.where(m_a, sv.iota, -1))
            end_safe = jnp.where(sv.has, sv.ends, 0)
            le = last_sel[end_safe]
            in_run = sv.has & (le >= sv.lo)
            mark_r = jnp.where(in_run,
                               jnp.maximum(mark_r,
                                           ts_s[jnp.maximum(le, 0)]),
                               mark_r)

        (pk, pv, pw, fired_r, f_n, m_n) = _ring_push_multi(
            rb.pend_key[r], rb.pend_val[r], rb.pend_w[r], rb.pend_h[r],
            fired_r, sv, new_key, key_e, val_e)
        fires_n += f_n
        missed_n += m_n

        if kind == KIND_ABSENCE:
            # trailing: the watermark passed last_seen + deadline with
            # no new match (at most one per group per batch)
            trail = (rb.active[r] & (mark_r > INT32_MIN)
                     & (wm_new - mark_r > win) & (mark_r > fired_r))
            pk, pv, pw, over = _pend_push_one(
                pk, pv, pw, rb.pend_h[r], trail, mark_r,
                (wm_new - mark_r).astype(jnp.float32))
            fired_r = jnp.where(trail, mark_r, fired_r)
            fires_n += jnp.sum(trail.astype(jnp.int32))
            missed_n += over

        new_state["acc_wid"].append(acc_wid_r)
        new_state["acc_cnt"].append(acc_cnt_r)
        new_state["acc_sum"].append(acc_sum_r)
        new_state["mark_ts"].append(mark_r)
        new_state["fired_key"].append(fired_r)
        new_state["pend_key"].append(pk)
        new_state["pend_val"].append(pv)
        new_state["pend_w"].append(pw)

    return dataclasses.replace(
        rb, wm=wm_new,
        **{f: jnp.stack(v) for f, v in new_state.items()},
        fires=rb.fires + fires_n,
        missed=rb.missed + missed_n,
        late=rb.late + late_n,
        oob=rb.oob + oob_n)


def _rollup_block_update(ro: RollupBlock, batch, groups3,
                         base_valid) -> RollupBlock:
    p, g, nb = ro.wid.shape
    b = batch.capacity
    ts = batch.ts_ms

    et_ok = ((ro.etype[None, :] == NULL_ID)
             | (batch.etype[:, None] == ro.etype[None, :]))
    v = jnp.take(batch.values, ro.channel, axis=1)        # [B, P]
    vm = jnp.take(batch.vmask, ro.channel, axis=1)
    g_bp = groups3[ro.scope].T                            # [B, P]
    rel = (base_valid[:, None] & et_ok & vm & (g_bp >= 0) & (g_bp < g))
    win = jnp.maximum(ro.window_ms, 1)[None, :]
    wid = ts[:, None] // win
    slot = wid % nb
    p_bp = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32)[None, :], (b, p))
    # sentinel on the leading index drops irrelevant points
    pi = jnp.where(rel, p_bp, p)
    gi = jnp.minimum(jnp.maximum(g_bp, 0), g - 1)
    # pass 1: the newest window id per touched slot wins the slot
    wid_new = ro.wid.at[pi, gi, slot].max(wid, mode="drop")
    stale = wid_new != ro.wid
    adds0 = jnp.where(stale[..., None], 0.0, ro.adds)
    exts0 = jnp.where(stale[..., None], -F32_INF, ro.exts)
    # pass 2/3: events carrying the slot's (new) window id contribute;
    # older ones are late (counted, never mixed into a newer window)
    contrib = rel & (wid == wid_new.at[pi, gi, slot].get(
        mode="fill", fill_value=INT32_MIN))
    pc = jnp.where(contrib, p_bp, p)
    ones = jnp.ones_like(v)
    return dataclasses.replace(
        ro,
        wid=wid_new,
        adds=adds0.at[pc, gi, slot].add(
            jnp.stack([ones, v], axis=-1), mode="drop"),
        exts=exts0.at[pc, gi, slot].max(
            jnp.stack([v, -v], axis=-1), mode="drop"),
        late=ro.late + jnp.sum((rel & ~contrib).astype(jnp.int32)))


def rules_update(rs: RulesState, batch, dev, found, registry) -> RulesState:
    """One batch through the CEP tier: called INSIDE ``pipeline_step`` on
    the post-lookup view (``dev``/``found`` from ops/lookup), so rules and
    rollups see exactly the rows that persist. Pure event-time function —
    see the module docstring's determinism contract."""
    if rs.rules is None and rs.rollups is None:
        return rs
    base_valid = batch.valid & found
    n_dev = registry.device_area.shape[0]
    dev_safe = jnp.clip(dev, 0, n_dev - 1)
    area = jnp.where(found, registry.device_area[dev_safe], NULL_ID)

    rules = rs.rules
    if rules is not None:
        rules = _rules_block_update(rules, batch, dev, area, base_valid)

    rollups = rs.rollups
    if rollups is not None:
        groups3 = jnp.stack([dev, area, batch.tenant_id])  # [3, B]
        rollups = _rollup_block_update(rollups, batch, groups3,
                                       base_valid)
    return RulesState(rules=rules, rollups=rollups)


def harvest_fires(rules_state: RulesState):
    """Drain the pending-fire rings (pure; the engine jits this with
    state donation under the ``rules.harvest`` devicewatch family).
    Returns ``(new_rules_state, pend_key, pend_val, pend_w, pend_h)`` —
    the harvest cursor advances to the write cursor; the host
    reconstructs each group's ``min(w - h, K)`` newest entries from the
    ring (oldest-first at slots ``(w - n .. w - 1) % K``)."""
    rb = rules_state.rules
    if rb is None:
        z = jnp.zeros((0, 0))
        return rules_state, z, z, z, z
    cleared = dataclasses.replace(rb, pend_h=rb.pend_w)
    return (dataclasses.replace(rules_state, rules=cleared),
            rb.pend_key, rb.pend_val, rb.pend_w, rb.pend_h)


def merge_shard_harvests(pend_key, pend_val, pend_w, pend_h,
                         layout, device_cap):
    """Fold an SPMD engine's per-shard harvest (stacked ``[S, R, G, K]``
    rings and ``[S, R, G]`` cursors from a vmapped :func:`harvest_fires`)
    into the single-chip decode layout, SCOPE-aware per rule:

    * device scope — group ids are shard-LOCAL device ids, and a device
      lives on exactly one shard, so shard ``s``'s ring for local group
      ``g`` lands whole at global group ``s * device_cap + g`` (the
      engine's shard-qualified device-id space, so the host fire decode's
      ``devices.get(g)`` resolves unchanged);
    * area/tenant scope — group ids are GLOBAL interner ids replicated on
      every shard, so the per-shard rings for the same group fold into
      one ring: entries merge key-ascending (event-time-deterministic
      keys), newest ``K`` kept, cursors rebuilt to the ring contract
      (``n = min(w - h, K)`` newest, oldest-first at ``(w-n .. w-1) % K``).

    Host arrays in, host arrays out (numpy); output group axis is
    ``max(S * device_cap, G)``."""
    import numpy as np

    pk = np.asarray(pend_key)                   # [S, R, G, K]
    pv = np.asarray(pend_val)
    pw = np.asarray(pend_w)                     # [S, R, G]
    ph = np.asarray(pend_h)
    s_n, r_n, g_n, depth = pk.shape
    g_out = max(s_n * device_cap, g_n)
    mk = np.zeros((r_n, g_out, depth), pk.dtype)
    mv = np.zeros((r_n, g_out, depth), pv.dtype)
    mw = np.zeros((r_n, g_out), pw.dtype)
    mh = np.zeros((r_n, g_out), ph.dtype)

    def pending(s, r, g):
        """(key, val) pairs of shard s's un-harvested ring, oldest-first."""
        n = min(int(pw[s, r, g] - ph[s, r, g]), depth)
        w = int(pw[s, r, g])
        return [(int(pk[s, r, g, (w - n + j) % depth]),
                 float(pv[s, r, g, (w - n + j) % depth]))
                for j in range(n)]

    for r, (kind, scope, *_rest) in enumerate(layout):
        if scope == SCOPE_DEVICE:
            # whole-ring relocation: local device g -> s*device_cap + g
            span = min(g_n, device_cap)
            for s in range(s_n):
                lo = s * device_cap
                mk[r, lo:lo + span] = pk[s, r, :span]
                mv[r, lo:lo + span] = pv[s, r, :span]
                mw[r, lo:lo + span] = pw[s, r, :span]
                mh[r, lo:lo + span] = ph[s, r, :span]
        else:
            for g in range(g_n):
                entries = [e for s in range(s_n) for e in pending(s, r, g)]
                if not entries:
                    continue
                entries.sort(key=lambda e: e[0])
                total = len(entries)
                keep = entries[-depth:]
                w = total
                for j, (k, v) in enumerate(keep):
                    slot = (w - len(keep) + j) % depth
                    mk[r, g, slot] = k
                    mv[r, g, slot] = v
                mw[r, g] = w
                mh[r, g] = w - len(keep)
    return mk, mv, mw, mh
