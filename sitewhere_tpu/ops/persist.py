"""Batched event persistence into the HBM ring store.

Replaces the reference's per-event time-series writes
(service-event-management/.../kafka/EventPersistenceMapper.java:61-120 →
InfluxDbDeviceEventManagement.java:63-161 point builds) with one compaction
sort + one masked scatter per batch. Invalid (padding / unexpanded) rows are
compacted to the back and scattered out-of-bounds with ``mode='drop'`` so
they cost no ring capacity.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.store import EventStore
from sitewhere_tpu.ops.segment import compact_valid_front


class PersistResult(NamedTuple):
    store: EventStore
    appended: jax.Array  # int32[] events written this batch


def append_events(
    store: EventStore,
    valid: jax.Array,       # bool[E]
    etype: jax.Array,       # int32[E]
    device: jax.Array,      # int32[E]
    assignment: jax.Array,  # int32[E]
    tenant: jax.Array,      # int32[E]
    area: jax.Array,        # int32[E]
    asset: jax.Array,       # int32[E]
    ts_ms: jax.Array,       # int32[E]
    received_ms: jax.Array, # int32[E]
    values: jax.Array,      # float32[E, C]
    vmask: jax.Array,       # bool[E, C]
    aux: jax.Array,         # int32[E, AUX]
) -> PersistResult:
    """Append up to E events at the ring cursor. E may exceed remaining ring
    space; the ring wraps (oldest rows overwritten), mirroring retention-policy
    expiry in the reference's InfluxDB backend (INFLUX_RETENTION_POLICY
    override, InfluxDbDeviceEventManagement.java)."""
    s = store.capacity
    e = valid.shape[0]
    # With e <= s the positions (cursor+rank) % s are distinct, so the single
    # scatter below is well-defined. A batch larger than the whole ring would
    # alias slots inside one scatter (order-undefined in XLA); sizes are
    # static, so reject that configuration at trace time.
    if e > s:
        raise ValueError(
            f"expanded batch ({e} rows) exceeds event-store capacity ({s}); "
            "allocate store_capacity >= batch_capacity * MAX_ACTIVE_ASSIGNMENTS"
        )

    # Stable-compact valid rows to the front so padding never lands in the ring.
    n, perm = compact_valid_front(valid)
    c_valid = valid[perm]
    c_etype = etype[perm]
    c_device = device[perm]
    c_assignment = assignment[perm]
    c_tenant = tenant[perm]
    c_area = area[perm]
    c_asset = asset[perm]
    c_ts = ts_ms[perm]
    c_recv = received_ms[perm]
    c_values = values[perm]
    c_vmask = vmask[perm]
    c_aux = aux[perm]
    rank = jnp.arange(e, dtype=jnp.int32)
    pos = jnp.where(c_valid, (store.cursor + rank) % s, s)  # s = out of bounds -> dropped

    new = EventStore(
        cursor=(store.cursor + n) % jnp.int32(s),
        epoch=store.epoch + (store.cursor + n) // jnp.int32(s),
        etype=store.etype.at[pos].set(c_etype, mode="drop"),
        device=store.device.at[pos].set(c_device, mode="drop"),
        assignment=store.assignment.at[pos].set(c_assignment, mode="drop"),
        tenant=store.tenant.at[pos].set(c_tenant, mode="drop"),
        area=store.area.at[pos].set(c_area, mode="drop"),
        asset=store.asset.at[pos].set(c_asset, mode="drop"),
        ts_ms=store.ts_ms.at[pos].set(c_ts, mode="drop"),
        received_ms=store.received_ms.at[pos].set(c_recv, mode="drop"),
        values=store.values.at[pos].set(c_values, mode="drop"),
        vmask=store.vmask.at[pos].set(c_vmask, mode="drop"),
        aux=store.aux.at[pos].set(c_aux, mode="drop"),
        valid=store.valid.at[pos].set(True, mode="drop"),
    )
    return PersistResult(store=new, appended=n)
