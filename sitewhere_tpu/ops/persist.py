"""Batched event persistence into the HBM ring store.

Replaces the reference's per-event time-series writes
(service-event-management/.../kafka/EventPersistenceMapper.java:61-120 →
InfluxDbDeviceEventManagement.java:63-161 point builds) with one compaction
sort + one masked scatter per batch. Invalid (padding / unexpanded) rows are
compacted to the back and scattered out-of-bounds with ``mode='drop'`` so
they cost no ring capacity.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.store import EventStore
from sitewhere_tpu.ops.segment import lex_argsort, segment_ranks


class PersistResult(NamedTuple):
    store: EventStore
    appended: jax.Array  # int32[] events written this batch


def append_events(
    store: EventStore,
    valid: jax.Array,       # bool[E]
    etype: jax.Array,       # int32[E]
    device: jax.Array,      # int32[E]
    assignment: jax.Array,  # int32[E]
    tenant: jax.Array,      # int32[E]
    area: jax.Array,        # int32[E]
    customer: jax.Array,    # int32[E]
    asset: jax.Array,       # int32[E]
    ts_ms: jax.Array,       # int32[E]
    received_ms: jax.Array, # int32[E]
    values: jax.Array,      # float32[E, C]
    vmask: jax.Array,       # bool[E, C]
    aux: jax.Array,         # int32[E, AUX]
) -> PersistResult:
    """Append up to E events at each arena's ring cursor. Rows route to
    arena ``tenant % A`` (A=1: the single shared ring). E may exceed an
    arena's remaining space; that arena wraps (oldest rows overwritten),
    mirroring retention-policy expiry in the reference's InfluxDB backend
    (INFLUX_RETENTION_POLICY override, InfluxDbDeviceEventManagement.java).
    With multiple arenas this is the hard per-tenant retention guarantee:
    a burst only wraps its own arena."""
    s = store.capacity
    a_n = store.arenas
    acap = store.arena_capacity
    e = valid.shape[0]
    # With e <= acap the positions within one arena are distinct, so the
    # single scatter below is well-defined. A batch larger than one arena
    # could alias slots inside one scatter (order-undefined in XLA); sizes
    # are static, so reject that configuration at trace time.
    if e > acap:
        raise ValueError(
            f"expanded batch ({e} rows) exceeds per-arena event-store "
            f"capacity ({acap}); allocate store_capacity >= "
            "batch_capacity * MAX_ACTIVE_ASSIGNMENTS * arenas"
        )

    # Route each valid row to its tenant's arena, group rows by arena
    # (stable: batch order preserved within an arena), rank within group.
    arena = jnp.where(valid & (tenant >= 0), tenant % a_n,
                      jnp.where(valid, 0, a_n))   # a_n = padding sentinel
    sorted_keys, perm = lex_argsort([arena])
    s_arena = sorted_keys[0]
    rank, _ = segment_ranks(s_arena)
    c_valid = valid[perm]
    c_etype = etype[perm]
    c_device = device[perm]
    c_assignment = assignment[perm]
    c_tenant = tenant[perm]
    c_area = area[perm]
    c_customer = customer[perm]
    c_asset = asset[perm]
    c_ts = ts_ms[perm]
    c_recv = received_ms[perm]
    c_values = values[perm]
    c_vmask = vmask[perm]
    c_aux = aux[perm]
    arena_safe = jnp.clip(s_arena, 0, a_n - 1)
    cur = store.cursor[arena_safe]
    pos = jnp.where(s_arena < a_n,
                    arena_safe * acap + (cur + rank) % acap,
                    s)   # s = out of bounds -> dropped
    # per-arena appended counts: one-hot sum (sentinel rows drop out)
    counts = jnp.sum(
        (s_arena[:, None] == jnp.arange(a_n)[None, :]).astype(jnp.int32),
        axis=0)
    n = jnp.sum(c_valid.astype(jnp.int32))

    new = EventStore(
        cursor=(store.cursor + counts) % jnp.int32(acap),
        epoch=store.epoch + (store.cursor + counts) // jnp.int32(acap),
        etype=store.etype.at[pos].set(c_etype, mode="drop"),
        device=store.device.at[pos].set(c_device, mode="drop"),
        assignment=store.assignment.at[pos].set(c_assignment, mode="drop"),
        tenant=store.tenant.at[pos].set(c_tenant, mode="drop"),
        area=store.area.at[pos].set(c_area, mode="drop"),
        customer=store.customer.at[pos].set(c_customer, mode="drop"),
        asset=store.asset.at[pos].set(c_asset, mode="drop"),
        ts_ms=store.ts_ms.at[pos].set(c_ts, mode="drop"),
        received_ms=store.received_ms.at[pos].set(c_recv, mode="drop"),
        values=store.values.at[pos].set(c_values, mode="drop"),
        vmask=store.vmask.at[pos].set(c_vmask, mode="drop"),
        aux=store.aux.at[pos].set(c_aux, mode="drop"),
        valid=store.valid.at[pos].set(True, mode="drop"),
    )
    return PersistResult(store=new, appended=n)
