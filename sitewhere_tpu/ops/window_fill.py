"""Vectorized archive->window fill for historical analytics (ISSUE 19).

Rebuilds per-device telemetry windows [M, W, C] from a flat batch of
archived measurement rows — the device-side half of the batched
archive->device scoring pipeline (models/analytics.py). The live-window
path (models/windows.py) appends each ingest batch into per-device rings;
here an entire streamed round of historical rows lands in one shot, so
the op sorts rows by (device slot, ts, seq), ranks them within each
device run, keeps only the newest W per device, and scatters them into
the snapshot layout the scoring stack consumes: newest row at index W-1,
zeros padding the front of underfilled windows — exactly the shape
``snapshot_windows`` yields for a live ring, so ``_score_windows``
(models/service.py) runs unchanged over either source.

Keeping only the newest W rows per device (``rank >= count - W``) is
what makes the scatter deterministic: every surviving row owns a UNIQUE
(device, slot) destination, so no two rows race for a slot — the
duplicate-destination nondeterminism a naive modular ring scatter would
reintroduce. No per-device Python loops anywhere; everything is one
static-shape program (fixed N and M per analytics round -> zero
retraces, watched under its own devicewatch family).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from sitewhere_tpu.ops.segment import lex_argsort, segment_ranks


@functools.partial(jax.jit, static_argnames=("m", "w"))
def fill_windows(
    dev_slot: jax.Array,   # int32[N] dense batch-device slot, -1 = drop
    ts: jax.Array,         # int32[N] event time (window order key)
    seq: jax.Array,        # int32[N] tie-break (absolute archive pos)
    values: jax.Array,     # float32[N, C]
    vmask: jax.Array,      # bool[N, C] valid channel lanes
    *, m: int, w: int,
) -> tuple[jax.Array, jax.Array]:
    """-> (data float32[m, w, C] snapshot-form, filled int32[m] total
    matching rows per slot — may exceed ``w``; older rows spill off)."""
    vals = jnp.where(vmask, values, 0.0)
    take = (dev_slot >= 0) & (dev_slot < m)
    dev_key = jnp.where(take, dev_slot, m)
    sorted_keys, perm = lex_argsort([dev_key, ts, seq])
    s_dev = sorted_keys[0]
    s_vals = vals[perm]
    rank, _ = segment_ranks(s_dev)
    live = s_dev < m
    counts = jnp.zeros((m,), jnp.int32).at[
        jnp.where(live, s_dev, m)].add(live.astype(jnp.int32), mode="drop")
    cnt_row = counts.at[jnp.where(live, s_dev, m)].get(
        mode="fill", fill_value=0)
    slot = rank + w - cnt_row          # right-align: newest lands at w-1
    keep = live & (slot >= 0)          # only the newest w rows per device
    d_w = jnp.where(keep, s_dev, m)
    c = values.shape[1]
    data = jnp.zeros((m, w, c), jnp.float32).at[d_w, slot].set(
        s_vals, mode="drop")
    return data, counts


# devicewatch (ISSUE 11 discipline): the analytics fill runs at fixed
# (N, M, W) per job round — any shape churn is a bug and shows up under
# this family instead of as silent recompile stalls.
from sitewhere_tpu.utils.devicewatch import watched_jit  # noqa: E402

fill_windows = watched_jit(fill_windows, family="window_fill",
                           static_argnames=("m", "w"))
