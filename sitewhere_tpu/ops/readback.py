"""Host readback of event-store ranges — the outbound-topic consumer primitive.

In the reference, everything downstream of persistence (device-state,
outbound connectors, command delivery) consumes Kafka topics fed by the
persistence triggers (KafkaEventPersistenceTriggers.java:36-129). Here those
consumers read ranges of the HBM ring store by absolute cursor — the same
at-least-once, offset-committed contract as a Kafka consumer group, without
the broker. ``read_range`` slices [start, start+count) (wrapping) into a
host-visible struct; each consumer tracks its own committed offset
(outbound/feed.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.store import EventStore


class StoreSlice(NamedTuple):
    etype: jax.Array
    device: jax.Array
    assignment: jax.Array
    tenant: jax.Array
    area: jax.Array
    asset: jax.Array
    ts_ms: jax.Array
    received_ms: jax.Array
    values: jax.Array
    vmask: jax.Array
    aux: jax.Array
    valid: jax.Array


@functools.partial(jax.jit, static_argnames=("count",))
def read_range(store: EventStore, start: jax.Array, count: int) -> StoreSlice:
    """Gather ``count`` rows beginning at absolute position ``start % S``."""
    s = store.capacity
    idx = (start + jnp.arange(count, dtype=jnp.int32)) % s
    return StoreSlice(
        etype=store.etype[idx],
        device=store.device[idx],
        assignment=store.assignment[idx],
        tenant=store.tenant[idx],
        area=store.area[idx],
        asset=store.asset[idx],
        ts_ms=store.ts_ms[idx],
        received_ms=store.received_ms[idx],
        values=store.values[idx],
        vmask=store.vmask[idx],
        aux=store.aux[idx],
        valid=store.valid[idx],
    )


def absolute_cursor(store: EventStore) -> int:
    """Total events ever written (epoch * capacity + cursor)."""
    return int(store.epoch) * store.capacity + int(store.cursor)
