"""Host readback of event-store ranges — the outbound-topic consumer primitive.

In the reference, everything downstream of persistence (device-state,
outbound connectors, command delivery) consumes Kafka topics fed by the
persistence triggers (KafkaEventPersistenceTriggers.java:36-129). Here those
consumers read ranges of the HBM ring store by absolute cursor — the same
at-least-once, offset-committed contract as a Kafka consumer group, without
the broker. ``read_range`` slices [start, start+count) (wrapping) into a
host-visible struct; each consumer tracks its own committed offset
(outbound/feed.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.store import EventStore


class StoreSlice(NamedTuple):
    etype: jax.Array
    device: jax.Array
    assignment: jax.Array
    tenant: jax.Array
    area: jax.Array
    customer: jax.Array
    asset: jax.Array
    ts_ms: jax.Array
    received_ms: jax.Array
    values: jax.Array
    vmask: jax.Array
    aux: jax.Array
    valid: jax.Array


@functools.partial(jax.jit, static_argnames=("count", "arena"))
def read_range(store: EventStore, start: jax.Array, count: int,
               arena: int = 0) -> StoreSlice:
    """Gather ``count`` rows of one arena beginning at its arena-local
    position ``start % (S/A)`` (arena 0 of a 1-arena store = the whole
    ring, the classic behavior)."""
    s = store.arena_capacity
    idx = arena * s + (start + jnp.arange(count, dtype=jnp.int32)) % s
    return StoreSlice(
        etype=store.etype[idx],
        device=store.device[idx],
        assignment=store.assignment[idx],
        tenant=store.tenant[idx],
        area=store.area[idx],
        customer=store.customer[idx],
        asset=store.asset[idx],
        ts_ms=store.ts_ms[idx],
        received_ms=store.received_ms[idx],
        values=store.values[idx],
        vmask=store.vmask[idx],
        aux=store.aux[idx],
        valid=store.valid[idx],
    )


# devicewatch (ISSUE 11): the archive spool and feed consumers read the
# ring through this one program — compiles (one per (count, arena,
# store shape)) land under the readback family.
from sitewhere_tpu.utils.devicewatch import watched_jit  # noqa: E402

read_range = watched_jit(read_range, family="readback",
                         static_argnames=("count", "arena"))


def absolute_cursor(store: EventStore) -> int:
    """Total events ever written, summed over arenas — monotone under
    appends, the durable-watermark scalar."""
    import numpy as np

    epochs = np.asarray(jax.device_get(store.epoch)).astype(np.int64)
    cursors = np.asarray(jax.device_get(store.cursor)).astype(np.int64)
    return int(np.sum(epochs * store.arena_capacity + cursors))


def arena_cursor(store: EventStore, arena: int) -> int:
    """One arena's absolute write count (epoch*arena_capacity + cursor)."""
    return (int(store.epoch[arena]) * store.arena_capacity
            + int(store.cursor[arena]))
