"""Windowed device-state aggregation + merge kernel.

This is the TPU replacement for the reference's device-state path ("hot loop
#3", SURVEY.md §3.3): Kafka Streams ``groupByKey -> 5s tumbling window ->
DeviceStateAggregator`` (service-device-state/.../kafka/DeviceStatePipeline.java:80-88,
DeviceStateAggregator.java:29-68) followed by a per-assignment JPA merge that
keeps the latest value plus the 3 most recent events per event class
(persistence/rdb/RdbDeviceStateMergeStrategy.java:41-120).

One call merges one batch/window of events into the HBM-resident
``DeviceStateStore``:
  * recent-event rings (depth R=3, most-recent-first) per class are updated
    with a sort + rank-from-end + masked scatter, then a fixed-size row-wise
    top-R merge against the existing ring — no data-dependent shapes.
  * latest-per-channel measurement values use an argmax-scatter over
    (device, channel) segments — exact even with duplicate timestamps
    (batch sequence breaks ties), robust under at-least-once replay.
  * last-interaction / presence / per-type counters are plain max/add scatters.

Correctness does not depend on batch boundaries aligning with wall-clock
windows: merging two half-windows yields the same state as one full window
(tested against a numpy oracle in tests/test_pipeline.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sitewhere_tpu.core.state import LOC_LANES, RECENT_DEPTH, DeviceStateStore
from sitewhere_tpu.core.types import NUM_EVENT_TYPES, EventType, PresenceState
from sitewhere_tpu.ops.segment import INT32_MAX, INT32_MIN, lex_argsort, segment_ranks

_NEG_SAFE_MIN = INT32_MIN + 1


def _batch_recent_ring(
    n_devices: int,
    take: jax.Array,     # bool[B] rows of this event class
    dev: jax.Array,      # int32[B]
    ts: jax.Array,       # int32[B]
    seq: jax.Array,      # int32[B]
    lanes: list[jax.Array],  # per-row payload lanes to carry into the ring
) -> tuple[jax.Array, jax.Array, list[jax.Array]]:
    """Extract the up-to-R most recent events per device from the batch.

    Returns (ring_valid[N,R], ring_ts[N,R], ring_lanes) with slot 0 = newest.
    """
    r_depth = RECENT_DEPTH
    dev_key = jnp.where(take, dev, n_devices)  # invalid rows sort to the end
    sorted_keys, perm = lex_argsort([dev_key, ts, seq])
    s_devkey = sorted_keys[0]
    s_ts = ts[perm]
    s_lanes = [lane[perm] for lane in lanes]
    _, rank_end = segment_ranks(s_devkey)
    live = (s_devkey < n_devices) & (rank_end < r_depth)
    # rank_end==0 is the newest -> slot 0
    slot = rank_end
    d_w = jnp.where(live, s_devkey, n_devices)  # OOB -> dropped
    ring_valid = jnp.zeros((n_devices, r_depth), jnp.bool_).at[d_w, slot].set(True, mode="drop")
    ring_ts = jnp.full((n_devices, r_depth), INT32_MIN, jnp.int32).at[d_w, slot].set(s_ts, mode="drop")
    ring_lanes = []
    for lane in s_lanes:
        shape = (n_devices, r_depth) + lane.shape[1:]
        ring_lanes.append(jnp.zeros(shape, lane.dtype).at[d_w, slot].set(lane, mode="drop"))
    return ring_valid, ring_ts, ring_lanes


def _merge_rings(
    new_valid: jax.Array, new_ts: jax.Array, new_lanes: list[jax.Array],
    old_valid: jax.Array, old_ts: jax.Array, old_lanes: list[jax.Array],
) -> tuple[jax.Array, jax.Array, list[jax.Array]]:
    """Row-wise top-R merge of batch ring + existing ring (most-recent-first).

    New entries are preferred on timestamp ties (later arrival wins, matching
    the reference merge strategy's replace-on-merge behavior)."""
    r_depth = RECENT_DEPTH
    cat_valid = jnp.concatenate([new_valid, old_valid], axis=1)   # [N, 2R]
    cat_ts = jnp.concatenate([new_ts, old_ts], axis=1)
    # row-wise stable lexicographic sort: invalid last, then ts descending.
    # Two separate keys — packing into one int32 would collide real
    # near-INT32_MIN timestamps with the invalid sentinel.
    idx = jnp.broadcast_to(jnp.arange(cat_ts.shape[1], dtype=jnp.int32), cat_ts.shape)
    _, _, order = jax.lax.sort(
        [(~cat_valid).astype(jnp.int32), -jnp.maximum(cat_ts, _NEG_SAFE_MIN), idx],
        dimension=1, num_keys=2, is_stable=True,
    )
    order = order[:, :r_depth]
    out_valid = jnp.take_along_axis(cat_valid, order, axis=1)
    out_ts = jnp.take_along_axis(cat_ts, order, axis=1)
    out_lanes = []
    for new_lane, old_lane in zip(new_lanes, old_lanes):
        cat = jnp.concatenate([new_lane, old_lane], axis=1)
        idx = order.reshape(order.shape + (1,) * (cat.ndim - 2))
        out_lanes.append(jnp.take_along_axis(cat, jnp.broadcast_to(idx, order.shape + cat.shape[2:]), axis=1))
    return out_valid, out_ts, out_lanes


def merge_batch_state(
    state: DeviceStateStore,
    dev: jax.Array,      # int32[B] dense device id (found events only)
    found: jax.Array,    # bool[B]
    etype: jax.Array,    # int32[B]
    ts_ms: jax.Array,    # int32[B]
    seq: jax.Array,      # int32[B]
    values: jax.Array,   # float32[B, C]
    vmask: jax.Array,    # bool[B, C]
    aux: jax.Array,      # int32[B, AUX]
) -> DeviceStateStore:
    """Merge one batch of looked-up events into the device state store."""
    n = state.device_capacity
    c = values.shape[1]
    dev_safe = jnp.where(found, dev, n)  # OOB -> dropped in scatters

    # --- measurements -----------------------------------------------------
    take_m = found & (etype == EventType.MEASUREMENT)
    m_valid, m_ts, (m_vals, m_mask) = _batch_recent_ring(
        n, take_m, dev, ts_ms, seq, [values, vmask]
    )
    rm_valid, rm_ts, (rm_vals, rm_mask) = _merge_rings(
        m_valid, m_ts, [m_vals, m_mask],
        state.recent_meas_valid, state.recent_meas_ms,
        [state.recent_meas, state.recent_meas_mask],
    )

    # latest value per (device, channel): argmax-scatter with (ts, seq) key
    ch_take = take_m[:, None] & vmask                     # bool[B, C]
    flat_seg = (dev_safe[:, None] * c + jnp.arange(c, dtype=jnp.int32)[None, :])
    flat_seg = jnp.where(ch_take, flat_seg, n * c).reshape(-1)
    flat_ts = jnp.broadcast_to(ts_ms[:, None], ch_take.shape).reshape(-1)
    flat_seq = jnp.broadcast_to(seq[:, None], ch_take.shape).reshape(-1)
    flat_val = values.reshape(-1)
    flat_take = ch_take.reshape(-1)
    k1 = jnp.where(flat_take, flat_ts, INT32_MIN)
    max_ts = jnp.full((n * c,), INT32_MIN, jnp.int32).at[flat_seg].max(k1, mode="drop")
    on_max = flat_take & (flat_ts == max_ts.at[flat_seg].get(mode="fill", fill_value=INT32_MIN))
    k2 = jnp.where(on_max, flat_seq, INT32_MIN)
    max_seq = jnp.full((n * c,), INT32_MIN, jnp.int32).at[flat_seg].max(k2, mode="drop")
    winner = on_max & (flat_seq == max_seq.at[flat_seg].get(mode="fill", fill_value=INT32_MIN))
    w_seg = jnp.where(winner, flat_seg, n * c)
    # only overwrite when the batch value is at least as new as the stored one
    cand_val = jnp.full((n * c,), 0.0, jnp.float32).at[w_seg].set(flat_val, mode="drop")
    cand_ts = jnp.full((n * c,), INT32_MIN, jnp.int32).at[w_seg].set(flat_ts, mode="drop")
    cand_val = cand_val.reshape(n, c)
    cand_ts = cand_ts.reshape(n, c)
    newer = cand_ts >= state.meas_last_ms
    meas_last = jnp.where(newer & (cand_ts > INT32_MIN), cand_val, state.meas_last)
    meas_last_ms = jnp.maximum(state.meas_last_ms, cand_ts)

    # --- locations --------------------------------------------------------
    # vmask lane 0 gates the ring: a LOCATION event decoded without
    # coordinates (null lat/lon) counts in event_counts but must not record
    # a (0, 0) null-island row
    take_l = found & (etype == EventType.LOCATION) & vmask[:, 0]
    l_valid, l_ts, (l_vals,) = _batch_recent_ring(
        n, take_l, dev, ts_ms, seq, [values[:, :LOC_LANES]]
    )
    rl_valid, rl_ts, (rl_vals,) = _merge_rings(
        l_valid, l_ts, [l_vals],
        state.recent_loc_valid, state.recent_loc_ms, [state.recent_loc],
    )

    # --- alerts -----------------------------------------------------------
    take_a = found & (etype == EventType.ALERT)
    a_valid, a_ts, (a_level, a_type) = _batch_recent_ring(
        n, take_a, dev, ts_ms, seq,
        [values[:, 0].astype(jnp.int32), aux[:, 0]],
    )
    ra_valid, ra_ts, (ra_level, ra_type) = _merge_rings(
        a_valid, a_ts, [a_level, a_type],
        state.recent_alert_valid, state.recent_alert_ms,
        [state.recent_alert_level, state.recent_alert_type],
    )

    # --- presence / interaction / counters --------------------------------
    last_inter = state.last_interaction_ms.at[dev_safe].max(
        jnp.where(found, ts_ms, INT32_MIN), mode="drop"
    )
    presence = state.presence.at[dev_safe].set(
        jnp.where(found, jnp.int32(PresenceState.PRESENT), jnp.int32(PresenceState.UNKNOWN)),
        mode="drop",
    )
    et_safe = jnp.clip(etype, 0, NUM_EVENT_TYPES - 1)
    counts = state.event_counts.at[dev_safe, et_safe].add(
        found.astype(jnp.int32), mode="drop"
    )

    return DeviceStateStore(
        last_interaction_ms=last_inter,
        presence=presence,
        meas_last=meas_last,
        meas_last_ms=meas_last_ms,
        recent_meas=rm_vals,
        recent_meas_mask=rm_mask,
        recent_meas_ms=rm_ts,
        recent_meas_valid=rm_valid,
        recent_loc=rl_vals,
        recent_loc_ms=rl_ts,
        recent_loc_valid=rl_valid,
        recent_alert_level=ra_level,
        recent_alert_type=ra_type,
        recent_alert_ms=ra_ts,
        recent_alert_valid=ra_valid,
        event_counts=counts,
    )


def presence_sweep(
    state: DeviceStateStore,
    device_active: jax.Array,  # bool[N] registered devices
    now_ms: jax.Array,
    missing_interval_ms: jax.Array,
) -> tuple[DeviceStateStore, jax.Array]:
    """Mark devices presence-MISSING when last interaction is too old.

    Vectorized analog of DevicePresenceManager's periodic scan
    (service-device-state/.../presence/DevicePresenceManager.java:103-160,
    default missing interval 8h). Returns (state, newly_missing mask) so the
    host can fire presence-missing notifications exactly once per transition
    (the reference's PresenceNotificationStrategies SendOnce semantics)."""
    seen = state.last_interaction_ms > INT32_MIN
    stale = seen & (state.last_interaction_ms < now_ms - missing_interval_ms)
    was_present = state.presence == PresenceState.PRESENT
    newly_missing = device_active & stale & was_present
    presence = jnp.where(
        device_active & stale, jnp.int32(PresenceState.MISSING), state.presence
    )
    import dataclasses

    return dataclasses.replace(state, presence=presence), newly_missing
