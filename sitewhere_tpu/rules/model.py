"""Declarative rule-set model: parse, validate, lower to device tables.

A rule set is a plain JSON document (REST/RPC-postable, file-watchable):

    {
      "name": "default",
      "rules": [
        {"name": "overheat", "kind": "threshold",
         "channel": "engine.temperature", "op": ">", "value": 90,
         "cooldownMs": 1000, "scope": "device",
         "alertType": "overheat", "level": "ERROR"},
        {"name": "hot-burst", "kind": "window", "agg": "count",
         "channel": "engine.temperature", "op": ">=", "value": 5,
         "windowMs": 5000,
         "where": {"channel": "engine.temperature", "op": ">", "value": 90}},
        {"name": "spike-then-drop", "kind": "sequence",
         "first": {"channel": "rpm", "op": ">", "value": 5000},
         "then":  {"channel": "rpm", "op": "<", "value": 100},
         "withinMs": 10000},
        {"name": "went-silent", "kind": "absence",
         "channel": "engine.temperature", "deadlineMs": 60000}
      ],
      "rollups": [
        {"name": "temp-1s", "channel": "engine.temperature",
         "windowMs": 1000, "scope": "device"}
      ]
    }

Validation happens at parse time (loudly — a bad rule set never reaches
the device), lowering at install time against a live engine's interners.
Threshold rules LOWER to window rules over the running extremum — "some
event crossed" == "running max/min crossed" — so the kernel (ops/
rules.py) only knows three kinds. Window (agg, op) combinations are
restricted to the monotone ones; that restriction is what makes fire
detection batch-partition invariant (the replay/standby parity
contract — see ops/rules.py docstring).
"""

from __future__ import annotations

import dataclasses
import json
import pathlib

import numpy as np

from sitewhere_tpu.core.types import AlertLevel, EventType
from sitewhere_tpu.ops.rules import (
    AGG_COUNT,
    AGG_MAX,
    AGG_MIN,
    AGG_SUM,
    KIND_ABSENCE,
    KIND_SEQUENCE,
    KIND_WINDOW,
    OP_GE,
    OP_GT,
    OP_LE,
    OP_LT,
    SCOPE_AREA,
    SCOPE_DEVICE,
    SCOPE_TENANT,
    RollupBlock,
    RuleBlock,
    RulesState,
)
from sitewhere_tpu.core.types import NULL_ID


class RuleSetError(ValueError):
    """Invalid rule-set document; raised at parse/validate time, BEFORE
    any live state is touched (the compile-before-swap discipline)."""


_OPS = {">": OP_GT, ">=": OP_GE, "<": OP_LT, "<=": OP_LE,
        "gt": OP_GT, "ge": OP_GE, "lt": OP_LT, "le": OP_LE}
_AGGS = {"count": AGG_COUNT, "sum": AGG_SUM, "min": AGG_MIN, "max": AGG_MAX}
_SCOPES = {"device": SCOPE_DEVICE, "area": SCOPE_AREA,
           "tenant": SCOPE_TENANT}
_KINDS = ("threshold", "window", "sequence", "absence")
# monotone (agg, op) combinations: once the running aggregate satisfies
# the predicate within a window it stays satisfied, so fire detection is
# independent of where batch boundaries fall
_MONOTONE_OPS = {AGG_COUNT: (OP_GT, OP_GE), AGG_SUM: (OP_GT, OP_GE),
                 AGG_MAX: (OP_GT, OP_GE), AGG_MIN: (OP_LT, OP_LE)}

MAX_RULES = 64
MAX_ROLLUPS = 16
NO_PRED_OP = -1          # sentinel: predicate slot unused


def _pred(spec, ctx: str) -> tuple[str, int, float]:
    if not isinstance(spec, dict):
        raise RuleSetError(f"{ctx}: predicate must be an object")
    ch = spec.get("channel")
    if not ch or not isinstance(ch, str):
        raise RuleSetError(f"{ctx}: predicate requires a 'channel' name")
    op = spec.get("op", "any")
    if op in ("any", "*"):          # "an event on this channel"
        return ch, OP_GE, float("-inf")
    if op not in _OPS:
        raise RuleSetError(f"{ctx}: unknown op {op!r} "
                           f"(known: {sorted(_OPS)})")
    if "value" not in spec:
        raise RuleSetError(f"{ctx}: op {op!r} requires 'value'")
    return ch, _OPS[op], float(spec["value"])


@dataclasses.dataclass(frozen=True)
class RuleMeta:
    """Host-side per-rule metadata the manager needs at emission time."""

    name: str
    kind: str                    # user-facing kind (threshold stays
    #                              'threshold' even though it lowers)
    scope: str
    tenant: str | None
    window_ms: int
    alert_type: str
    level: str                   # AlertLevel name
    lowered_kind: int            # KIND_* actually on device


@dataclasses.dataclass(frozen=True)
class RollupMeta:
    name: str
    channel: str
    scope: str
    window_ms: int


@dataclasses.dataclass(frozen=True)
class RuleSet:
    """A parsed + validated rule-set document."""

    doc: dict
    rules: tuple
    rollups: tuple

    @property
    def name(self) -> str:
        return self.doc.get("name", "default")

    @staticmethod
    def parse(doc: dict | str | pathlib.Path) -> "RuleSet":
        if isinstance(doc, (str, pathlib.Path)):
            doc = json.loads(pathlib.Path(doc).read_text())
        if not isinstance(doc, dict):
            raise RuleSetError("rule set must be a JSON object")
        rules = doc.get("rules", [])
        rollups = doc.get("rollups", [])
        if not isinstance(rules, list) or not isinstance(rollups, list):
            raise RuleSetError("'rules' and 'rollups' must be arrays")
        if len(rules) > MAX_RULES:
            raise RuleSetError(f"{len(rules)} rules > limit {MAX_RULES}")
        if len(rollups) > MAX_ROLLUPS:
            raise RuleSetError(
                f"{len(rollups)} rollups > limit {MAX_ROLLUPS}")
        # document-level capacity overrides validate at PARSE time, so a
        # pre-validating caller (config.reload_tenant_config) can reject
        # a bad document before tearing anything down — lower() re-checks
        # but must never be the first place a doc error surfaces
        for knob in ("groups", "rollupBuckets", "pending"):
            if knob in doc:
                try:
                    val = int(doc[knob])
                except (TypeError, ValueError):
                    raise RuleSetError(
                        f"'{knob}' must be an integer") from None
                if val < 1:
                    raise RuleSetError(f"'{knob}' must be >= 1")
        seen: set[str] = set()
        parsed_rules = []
        for i, spec in enumerate(rules):
            parsed_rules.append(_parse_rule(spec, i, seen))
        parsed_rollups = []
        for i, spec in enumerate(rollups):
            parsed_rollups.append(_parse_rollup(spec, i, seen))
        if not parsed_rules and not parsed_rollups:
            raise RuleSetError("rule set defines no rules and no rollups")
        return RuleSet(doc=doc, rules=tuple(parsed_rules),
                       rollups=tuple(parsed_rollups))

    # ---------------------------------------------------------- lowering
    def signature(self) -> tuple:
        """Shape/structure signature: two rule sets with equal
        signatures lower to identical device-array shapes AND identical
        static layouts (a swap between them is a pure parameter update —
        no recompile, carried state preservable). Rollup DEFINITIONS are
        part of it: a changed rollup (channel/scope/window) must get
        fresh rings, never inherit another definition's accumulators."""
        return (len(self.rules), len(self.rollups),
                # window_ms is part of the preserve gate: fire keys and
                # accumulators are denominated in window units, so a
                # window change must reset carried state, never inherit
                # keys computed in the old units
                tuple((r["lowered_kind"], _SCOPES[r["scope"]], r["agg"],
                       r["op_a"], r["op_b"], r["window_ms"])
                      for r in self.rules),
                tuple((p["name"], p["channel"], p["scope"], p["etype"],
                       p["window_ms"]) for p in self.rollups))

    def identity(self) -> tuple:
        """Positional rule identity; carried state is only preserved
        across a swap when this matches (same rules, tweaked params)."""
        return tuple((r["name"], r["kind"], r["scope"]) for r in self.rules)

    def lower(self, engine) -> tuple[RulesState, list[RuleMeta],
                                     list[RollupMeta]]:
        """Resolve names against the engine's interners and build fresh
        device blocks. Channel names intern (rules may precede traffic);
        install the SAME rule set on every replica of a partition so the
        interner streams stay aligned."""
        groups = int(self.doc.get(
            "groups", getattr(engine.config, "rule_groups", 1024)))
        buckets = int(self.doc.get(
            "rollupBuckets", getattr(engine.config, "rollup_buckets", 32)))
        if groups < 1 or buckets < 1:
            raise RuleSetError("groups/rollupBuckets must be >= 1")

        def ch(name: str) -> int:
            return engine.channel_map.channel_of(name)

        def tenant_id(name) -> int:
            return engine.tenants.intern(name) if name else NULL_ID

        meta: list[RuleMeta] = []
        layout: list[tuple] = []
        cols: dict[str, list] = {k: [] for k in (
            "active", "etype", "tenant", "ch_a", "val_a", "ch_b",
            "val_b", "window_ms")}
        for r in self.rules:
            # static structure (the compiled program specializes per
            # rule kind/scope/agg/op; changing these is a declared swap)
            layout.append((r["lowered_kind"], _SCOPES[r["scope"]],
                           r["agg"], r["op_a"], r["op_b"]))
            cols["active"].append(True)
            cols["etype"].append(r["etype"])
            cols["tenant"].append(tenant_id(r["tenant"]))
            cols["ch_a"].append(ch(r["ch_a"]))
            cols["val_a"].append(r["val_a"])
            cols["ch_b"].append(ch(r["ch_b"]) if r["ch_b"] else 0)
            cols["val_b"].append(r["val_b"])
            cols["window_ms"].append(r["window_ms"])
            meta.append(RuleMeta(
                name=r["name"], kind=r["kind"], scope=r["scope"],
                tenant=r["tenant"], window_ms=r["window_ms"],
                alert_type=r["alert_type"], level=r["level"],
                lowered_kind=r["lowered_kind"]))
        rb = None
        if self.rules:
            table = {k: np.asarray(v) for k, v in cols.items()}
            table["val_a"] = np.asarray(cols["val_a"], np.float32)
            table["val_b"] = np.asarray(cols["val_b"], np.float32)
            pending = int(self.doc.get(
                "pending", getattr(engine.config, "rule_pending", 4)))
            rb = RuleBlock.zeros(table, tuple(layout), groups, pending)

        ro = None
        ro_meta: list[RollupMeta] = []
        if self.rollups:
            rt = {k: [] for k in ("channel", "scope", "etype", "window_ms")}
            for p in self.rollups:
                rt["channel"].append(ch(p["channel"]))
                rt["scope"].append(_SCOPES[p["scope"]])
                rt["etype"].append(p["etype"])
                rt["window_ms"].append(p["window_ms"])
                ro_meta.append(RollupMeta(
                    name=p["name"], channel=p["channel"], scope=p["scope"],
                    window_ms=p["window_ms"]))
            ro = RollupBlock.zeros(
                {k: np.asarray(v) for k, v in rt.items()}, groups, buckets)
        return RulesState(rules=rb, rollups=ro), meta, ro_meta


def _etype_of(spec, ctx: str) -> int:
    raw = spec.get("etype", "MEASUREMENT")
    if raw in (None, "any", "*"):
        return NULL_ID
    try:
        return int(EventType[raw] if isinstance(raw, str) else
                   EventType(raw))
    except (KeyError, ValueError):
        raise RuleSetError(f"{ctx}: unknown etype {raw!r}") from None


def _scope_of(spec, ctx: str) -> str:
    scope = spec.get("scope", "device")
    if scope not in _SCOPES:
        raise RuleSetError(f"{ctx}: unknown scope {scope!r} "
                           f"(known: {sorted(_SCOPES)})")
    return scope


def _window_of(spec, key: str, ctx: str, default=None) -> int:
    raw = spec.get(key, default)
    if raw is None:
        raise RuleSetError(f"{ctx}: '{key}' is required")
    w = int(raw)
    if w < 1:
        raise RuleSetError(f"{ctx}: '{key}' must be >= 1 ms")
    return w


def _parse_rule(spec, i: int, seen: set) -> dict:
    if not isinstance(spec, dict):
        raise RuleSetError(f"rule[{i}]: must be an object")
    name = spec.get("name")
    if not name or not isinstance(name, str) or ":" in name:
        raise RuleSetError(f"rule[{i}]: requires a 'name' without ':'")
    if name in seen:
        raise RuleSetError(f"rule[{i}]: duplicate name {name!r}")
    seen.add(name)
    kind = spec.get("kind")
    if kind not in _KINDS:
        raise RuleSetError(
            f"rule {name!r}: unknown kind {kind!r} (known: {_KINDS})")
    ctx = f"rule {name!r}"
    scope = _scope_of(spec, ctx)
    level = str(spec.get("level", "WARNING")).upper()
    if level not in AlertLevel.__members__:
        raise RuleSetError(f"{ctx}: unknown level {level!r}")
    out = {
        "name": name, "kind": kind, "scope": scope,
        "etype": _etype_of(spec, ctx),
        "tenant": spec.get("tenant"),
        "alert_type": str(spec.get("alertType", name)),
        "level": level,
        "ch_b": None, "op_b": NO_PRED_OP, "val_b": 0.0,
        "agg": AGG_MAX,
    }
    if kind == "threshold":
        chn, op, val = _pred(spec, ctx)
        if op not in (OP_GT, OP_GE, OP_LT, OP_LE):
            raise RuleSetError(f"{ctx}: threshold requires a comparison op")
        out.update(
            lowered_kind=KIND_WINDOW, ch_a=chn, op_a=op, val_a=val,
            # "some event crossed" == "running extremum crossed"
            agg=AGG_MAX if op in (OP_GT, OP_GE) else AGG_MIN,
            window_ms=_window_of(spec, "cooldownMs", ctx, default=1000))
    elif kind == "window":
        agg = spec.get("agg")
        if agg not in _AGGS:
            raise RuleSetError(f"{ctx}: unknown agg {agg!r} "
                               f"(known: {sorted(_AGGS)})")
        agg_c = _AGGS[agg]
        chn, op, val = _pred(spec, ctx)
        if op not in _MONOTONE_OPS[agg_c]:
            good = [k for k, v in _OPS.items()
                    if v in _MONOTONE_OPS[agg_c] and len(k) <= 2]
            raise RuleSetError(
                f"{ctx}: agg {agg!r} only supports monotone ops {good} "
                "(batch-partition-invariant fire detection)")
        out.update(lowered_kind=KIND_WINDOW, ch_a=chn, op_a=op, val_a=val,
                   agg=agg_c,
                   window_ms=_window_of(spec, "windowMs", ctx))
        if "where" in spec:
            wb, wop, wval = _pred(spec["where"], f"{ctx} where")
            out.update(ch_b=wb, op_b=wop, val_b=wval)
    elif kind == "sequence":
        ch_a, op_a, val_a = _pred(spec.get("first"), f"{ctx} first")
        ch_b, op_b, val_b = _pred(spec.get("then"), f"{ctx} then")
        out.update(lowered_kind=KIND_SEQUENCE,
                   ch_a=ch_a, op_a=op_a, val_a=val_a,
                   ch_b=ch_b, op_b=op_b, val_b=val_b,
                   window_ms=_window_of(spec, "withinMs", ctx))
    else:  # absence
        chn, op, val = _pred(spec, ctx)
        out.update(lowered_kind=KIND_ABSENCE, ch_a=chn, op_a=op,
                   val_a=val,
                   window_ms=_window_of(spec, "deadlineMs", ctx))
    return out


def _parse_rollup(spec, i: int, seen: set) -> dict:
    if not isinstance(spec, dict):
        raise RuleSetError(f"rollup[{i}]: must be an object")
    name = spec.get("name")
    if not name or not isinstance(name, str):
        raise RuleSetError(f"rollup[{i}]: requires a 'name'")
    if name in seen:
        raise RuleSetError(f"rollup[{i}]: duplicate name {name!r}")
    seen.add(name)
    ctx = f"rollup {name!r}"
    channel = spec.get("channel")
    if not channel or not isinstance(channel, str):
        raise RuleSetError(f"{ctx}: requires a 'channel' name")
    return {"name": name, "channel": channel,
            "scope": _scope_of(spec, ctx),
            "etype": _etype_of(spec, ctx),
            "window_ms": _window_of(spec, "windowMs", ctx)}
