"""Host runtime for the streaming-rules tier.

The manager owns the ACTIVE rule set for one engine and enforces three
disciplines the tentpole names:

* **compile-before-swap** — a candidate rule set is parsed, validated,
  lowered, and (when its shapes differ from the live set's) AOT-compiled
  for the engine's hot dispatch program BEFORE the live set is touched.
  A bad document raises out of ``load()``/``check_reload()`` with the
  old set still serving; the compile itself runs OFF the engine lock, so
  ingest keeps dispatching the old program until the new one is ready.
  The devicewatch budget is granted one shape per swap — exactly the
  ``set_geofence_zones`` allowance discipline.

* **dedup-keyed emission** — a fire's identity is
  ``swr:<rule>:<group>:<key>`` (rule+group+window). Alerts go out as
  ordinary DeviceAlert JSON envelopes through ``ingest_json_batch`` —
  WAL-carried, replication-visible, archived, queryable — with the key
  as the event's ``alternateId``. Because every replayed/applied alert
  re-interns its alternate id, the engine's event-id interner doubles as
  the durable key registry: ``resync_emitted()`` scans it so replay and
  standby promotion re-emit exactly the fires the previous owner never
  got out, and nothing twice.

* **leader-only emission** — a standby runs the same rule set over the
  same stream (identical carried state by the kernel's determinism
  contract) but with ``active=False`` its pending fires are never
  harvested; promotion flips ``active`` and the next poll drains
  everything the old owner left, suppressed against the replayed keys.
"""

from __future__ import annotations

import json
import logging
import pathlib
import threading
from types import SimpleNamespace

import numpy as np

from sitewhere_tpu.ops.rules import KIND_ABSENCE
from sitewhere_tpu.rules.model import RuleSet, RuleSetError

logger = logging.getLogger(__name__)

ALERT_KEY_PREFIX = "swr:"


class RulesManager:
    """Rule-set lifecycle + alert emission for one engine."""

    def __init__(self, engine, active: bool = True):
        from sitewhere_tpu.utils.metrics import rules_metrics

        self.engine = engine
        self.active = active          # leader emits; standbys observe
        self.ruleset: RuleSet | None = None
        self.meta: list = []
        self.rollup_meta: list = []
        self._mu = threading.Lock()   # manager bookkeeping only; engine
        #                               state swaps take the engine lock
        self._emitted: set[str] = set()
        self._scan_pos = 0            # event-id interner resync cursor
        self._path: pathlib.Path | None = None
        self._mtime: float | None = None
        self.swaps = 0
        self.reload_errors = 0
        self.alerts_emitted = 0
        self.alerts_suppressed = 0
        # conservation accounting (ISSUE 14): every harvested fire must
        # land in exactly one sink — emitted, dedup-suppressed, or
        # skipped (stale meta row / unresolvable group token); the
        # audit plane checks harvested == emitted + suppressed + skipped
        self.fires_harvested = 0
        self.harvest_skipped = 0
        # rollup ring -> archive spill (ISSUE 19; the PR-12 leftover):
        # closed [P, G, NB] windows age out to columnar segments under
        # <archive>/rollups so months-long dashboards read the archive,
        # not the ring
        self._rollup_arch = None
        self.rollup_windows_spilled = 0
        self.rollup_spill_calls = 0
        self._inst = rules_metrics()

    # ----------------------------------------------------------- install
    def load(self, doc, *, precompile: bool = True) -> dict:
        """Validate + lower + install a rule set. Raises RuleSetError on
        a bad document WITHOUT touching the live set. When the new set
        has the same shape signature and positional identity as the live
        one, carried state (window accumulators, sequence marks, absence
        deadlines, fired keys) is preserved — a parameter tweak hot-swaps
        with zero recompiles and zero state loss."""
        ruleset = doc if isinstance(doc, RuleSet) else RuleSet.parse(doc)
        eng = self.engine
        state, meta, ro_meta = ruleset.lower(eng)
        preserve = (self.ruleset is not None
                    and ruleset.signature() == self.ruleset.signature()
                    and ruleset.identity() == self.ruleset.identity())
        precompiled = None
        if precompile and not preserve:
            # shape change: AOT-compile the hot dispatch program for the
            # candidate shape OFF the engine lock (ingest keeps serving
            # the old program until this returns)
            precompiled = eng.precompile_rules(state)
        eng.set_rules(state, precompiled=precompiled,
                      preserve_state=preserve)
        with self._mu:
            self.ruleset = ruleset
            self.meta = meta
            self.rollup_meta = ro_meta
            self.swaps += 1
        self._inst["swaps"].inc()
        summary = {"name": ruleset.name, "rules": len(meta),
                   "rollups": len(ro_meta), "preservedState": preserve,
                   "precompiled": precompiled is not None}
        logger.info("rule set %r installed: %s", ruleset.name, summary)
        return summary

    def clear(self) -> None:
        """Remove the active rule set (the running program recompiles
        without the rules subtree under a granted allowance)."""
        self.engine.set_rules(None)
        with self._mu:
            self.ruleset = None
            self.meta = []
            self.rollup_meta = []

    # -------------------------------------------------------- hot reload
    def watch_file(self, path) -> dict:
        """Load ``path`` now and arm mtime-based hot reload for it."""
        p = pathlib.Path(path)
        summary = self.load(json.loads(p.read_text()))
        with self._mu:
            self._path = p
            self._mtime = p.stat().st_mtime
        return summary

    def check_reload(self) -> bool:
        """Reload the watched file if its mtime changed (the scripting/
        config-reload plumbing's discipline: mtime only advances after a
        SUCCESSFUL swap, so a torn write retries on the next tick; a bad
        document is rejected loudly and the active set keeps serving).
        Returns True when a reload ran."""
        with self._mu:
            path, mtime = self._path, self._mtime
        if path is None:
            return False
        try:
            now_mtime = path.stat().st_mtime
        except OSError:
            return False
        if mtime is not None and now_mtime == mtime:
            return False
        try:
            self.load(json.loads(path.read_text()))
        except (RuleSetError, ValueError, OSError) as e:
            self.reload_errors += 1
            self._inst["reload_errors"].inc()
            logger.error("rule-set reload of %s rejected (keeping the "
                         "active set): %s", path, e)
            raise
        with self._mu:
            self._mtime = now_mtime
        return True

    # ---------------------------------------------------------- emission
    def resync_emitted(self) -> int:
        """Register every rule-alert dedup key the engine has ever seen
        (its event-id interner is append-only and survives snapshot
        restore, WAL replay, and standby apply — the durable half of the
        rule+group+window dedup discipline). Incremental: scans only
        tokens interned since the last call."""
        ids = self.engine.event_ids
        n = len(ids)
        added = 0
        with self._mu:
            for i in range(self._scan_pos, n):
                tok = ids.token(i)
                if tok.startswith(ALERT_KEY_PREFIX):
                    if tok not in self._emitted:
                        self._emitted.add(tok)
                        added += 1
            self._scan_pos = n
        return added

    def promote(self) -> int:
        """Standby -> owner: enable emission and resync the dedup keys
        from the applied stream. The next ``poll()`` emits exactly the
        fires the old owner never shipped."""
        self.active = True
        return self.resync_emitted()

    def poll(self, flush: bool = False) -> list[dict]:
        """Harvest pending fires and emit their alert events through the
        normal ingest pipeline. Inactive (standby) managers only resync;
        their pending fires stay on device for promotion. Returns the
        alerts emitted."""
        eng = self.engine
        if flush:
            eng.flush()
        self.resync_emitted()
        if not self.active:
            return []
        out = eng.poll_rule_fires()
        if out is None:
            return []
        pend_key, pend_val, pend_w, pend_h = (np.asarray(x) for x in out)
        pending = pend_w - pend_h
        if not (pending > 0).any():
            return []
        depth = pend_key.shape[2]
        fires: list[tuple[int, int, int, float]] = []
        for r, g in zip(*np.nonzero(pending > 0)):
            n = min(int(pending[r, g]), depth)
            w = int(pend_w[r, g])
            for j in range(n):     # oldest -> newest within the ring
                slot = (w - n + j) % depth
                fires.append((int(r), int(g),
                              int(pend_key[r, g, slot]),
                              float(pend_val[r, g, slot])))
        fires.sort()
        alerts: list[dict] = []
        by_tenant: dict[str, list[bytes]] = {}
        with self._mu:
            meta = list(self.meta)
        # conservation accounting tallies LOCALLY and commits in ONE
        # _mu block after the alert batches ingested: a concurrent
        # audit must read either the pre-poll or the post-poll
        # counters, never a mid-harvest state where harvested has run
        # ahead of its sinks (harvested == emitted + suppressed +
        # skipped is a checked equation)
        skipped = suppressed = 0
        for r, g, key, val in fires:
            if r >= len(meta):
                skipped += 1       # stale pend row from a narrower set
                continue
            m = meta[r]
            group_tok = self._group_token(m.scope, g)
            if group_tok is None:
                skipped += 1
                continue
            dedup = f"{ALERT_KEY_PREFIX}{m.name}:{group_tok}:{key}"
            with self._mu:
                if dedup in self._emitted:
                    suppressed += 1
                    self._inst["suppressed"].inc()
                    continue
                self._emitted.add(dedup)
            alerts.append(self._format_alert(m, group_tok, g, key, val,
                                             dedup, by_tenant))
        for tenant, payloads in by_tenant.items():
            eng.ingest_json_batch(payloads, tenant)
        with self._mu:
            self.fires_harvested += len(fires)
            self.harvest_skipped += skipped
            self.alerts_suppressed += suppressed
            self.alerts_emitted += len(alerts)
        if alerts:
            self._inst["alerts"].inc(len(alerts))
            eng.host_counters["rule_alerts"] = \
                eng.host_counters.get("rule_alerts", 0) + len(alerts)
        return alerts

    def _group_token(self, scope: str, g: int) -> str | None:
        eng = self.engine
        if scope == "device":
            info = eng.devices.get(g)
            return info.token if info is not None else None
        interner = eng.areas if scope == "area" else eng.tenants
        return interner.token(g) if 0 <= g < len(interner) else None

    def _format_alert(self, m, group_tok: str, g: int, key: int,
                      val: float, dedup: str, by_tenant: dict) -> dict:
        eng = self.engine
        # deterministic event time from the fire key (never the clock):
        # window rules -> window start; absence -> deadline expiry
        rel = (key + m.window_ms if m.lowered_kind == KIND_ABSENCE
               else key * m.window_ms)
        abs_ms = int(eng.epoch.base_unix_s * 1000) + rel
        if m.scope == "device":
            token, tenant = group_tok, eng.devices[g].tenant
        else:
            # area/tenant-grouped fires attach to a per-tenant emitter
            # device (registered through the admin path, so the
            # registration is WAL-carried and standby-visible too)
            tenant = group_tok if m.scope == "tenant" else (
                m.tenant or "default")
            token = f"swrules-{tenant}"
            if eng.tokens.lookup(token) < 0 or \
                    eng.token_device.get(eng.tokens.lookup(token)) is None:
                eng.register_device(token, tenant=tenant)
        envelope = {
            "deviceToken": token, "type": "DeviceAlert", "tenant": tenant,
            "request": {
                "type": m.alert_type, "level": m.level.capitalize(),
                "message": f"rule {m.name} fired for {m.scope} "
                           f"{group_tok}",
                "eventDate": abs_ms, "alternateId": dedup,
            },
        }
        by_tenant.setdefault(tenant, []).append(
            json.dumps(envelope, sort_keys=True).encode())
        return {"rule": m.name, "kind": m.kind, "scope": m.scope,
                "group": group_tok, "key": key, "value": val,
                "alternateId": dedup, "deviceToken": token,
                "tenant": tenant, "eventDateMs": abs_ms,
                "level": m.level, "alertType": m.alert_type}

    # ------------------------------------------------------------- reads
    def status(self) -> dict:
        eng = self.engine
        counters = eng.rule_counters()
        with self._mu:
            rs = self.ruleset
            out = {
                "ruleSet": rs.name if rs else None,
                "rules": [dataclass_dict(m) for m in self.meta],
                "rollups": [dataclass_dict(m) for m in self.rollup_meta],
                "active": self.active,
                "swaps": self.swaps,
                "reloadErrors": self.reload_errors,
                "alertsEmitted": self.alerts_emitted,
                "alertsSuppressed": self.alerts_suppressed,
                "dedupKeys": len(self._emitted),
                "watchedFile": str(self._path) if self._path else None,
            }
        out.update(counters)
        return out

    def read_rollup(self, name: str, group: str | None = None,
                    limit: int = 100) -> dict:
        """Serve one rollup's materialized windows (newest-first). With
        ``group`` only that device/area/tenant's ring is read; without,
        up to ``limit`` non-empty (group, window) buckets are listed."""
        eng = self.engine
        with self._mu:
            metas = list(self.rollup_meta)
        p = next((i for i, m in enumerate(metas) if m.name == name), None)
        if p is None:
            raise KeyError(f"rollup {name!r} not found")
        m = metas[p]
        with eng.lock:
            eng._sync_mirrors()
            rs = eng.state.rules
            if rs is None or rs.rollups is None:
                # a concurrent clear() raced this read: the meta said
                # the rollup existed, the device state says otherwise
                return {"rollup": name, "windowMs": m.window_ms,
                        "scope": m.scope, "channel": m.channel,
                        "buckets": []}
            arrs = eng._rollup_tables(p, m.scope)
            gid = None
            if group is not None:
                gid = self._group_id(m.scope, group)
                if gid is None or not (0 <= gid < arrs[0].shape[0]):
                    return {"rollup": name, "windowMs": m.window_ms,
                            "scope": m.scope, "buckets": []}
        wid, cnt, vsum, vmin, vmax = (np.asarray(a) for a in arrs)
        if gid is not None:
            rows = [(gid, b) for b in np.nonzero(cnt[gid] > 0)[0]]
        else:
            gs, bs = np.nonzero(cnt > 0)
            rows = list(zip(gs, bs))
        rows.sort(key=lambda gb: (-int(wid[gb[0], gb[1]]), gb[0]))
        buckets = []
        for g, b in rows[:limit]:
            buckets.append({
                "group": self._group_token(m.scope, int(g)) or int(g),
                "windowStartMs": int(wid[g, b]) * m.window_ms,
                "count": int(cnt[g, b]),
                "sum": float(vsum[g, b]),
                "min": float(vmin[g, b]),
                "max": float(vmax[g, b]),
            })
        return {"rollup": name, "windowMs": m.window_ms, "scope": m.scope,
                "channel": m.channel, "buckets": buckets}

    def _group_id(self, scope: str, token: str) -> int | None:
        eng = self.engine
        if scope == "device":
            tid = eng.tokens.lookup(token)
            return eng.token_device.get(tid) if tid >= 0 else None
        interner = eng.areas if scope == "area" else eng.tenants
        gid = interner.lookup(token)
        return gid if gid >= 0 else None

    # ----------------------------------------------------- rollup spill
    def rollup_archive(self):
        """The rollup retention tier: a second :class:`EventArchive`
        under ``<archive dir>/rollups`` (lazy; partition = rollup index,
        compression follows the engine knob). ``None`` without a main
        archive — spill is then a no-op and dashboards read the ring
        only."""
        eng = self.engine
        arch = getattr(eng, "archive", None)
        if arch is None:
            return None
        if self._rollup_arch is None:
            from sitewhere_tpu.utils.archive import EventArchive
            self._rollup_arch = EventArchive(
                arch.dir / "rollups", segment_rows=arch.segment_rows,
                cache_segments=2, compress=arch.compress)
        return self._rollup_arch

    def spill_rollups(self, lag: int = 1) -> dict:
        """Age CLOSED rollup windows out of the device-resident
        ``[P, G, NB]`` rings into the rollup archive. A window is closed
        once the rollup's newest live window id exceeds it by ``lag``
        (still-accumulating windows never spill). Idempotent: the spill
        watermark per rollup is recovered from the segments' ``aux0``
        (= window id) zone maps, so re-spooling after restart re-writes
        nothing. Row mapping — one archive row per non-empty closed
        (group, window): device=group id, assignment=rollup index,
        ts_ms=window start (relative ms, the ``windowStartMs`` domain),
        received_ms=window end, values lanes=[count, sum, min, max],
        aux=[window id, bucket]."""
        eng = self.engine
        ra = self.rollup_archive()
        out = {"spilled": 0, "rollups": 0}
        if ra is None:
            return out
        with self._mu:
            metas = list(self.rollup_meta)
            self.rollup_spill_calls += 1
        c = int(eng.config.channels)
        nlan = min(4, c)
        for p, m in enumerate(metas):
            with eng.lock:
                eng._sync_mirrors()
                rs = eng.state.rules
                if rs is None or rs.rollups is None:
                    break
                arrs = eng._rollup_tables(p, m.scope)
            wid, cnt, vsum, vmin, vmax = (np.asarray(a) for a in arrs)
            live = cnt > 0
            if not live.any():
                continue
            newest = int(wid[live].max())
            mark = max((s.stats["z"]["aux0"][1] for s in ra.segments
                        if s.part == p and s.stats
                        and "aux0" in s.stats.get("z", {})), default=-1)
            gs, bs = np.nonzero(live & (wid <= newest - lag)
                                & (wid > mark))
            if not gs.size:
                continue
            w_sel = wid[gs, bs]
            order = np.lexsort((gs, w_sel))
            gs, bs, w_sel = gs[order], bs[order], w_sel[order]
            n = gs.size
            vals = np.zeros((n, c), np.float32)
            stats_rows = np.stack([cnt[gs, bs], vsum[gs, bs],
                                   vmin[gs, bs], vmax[gs, bs]],
                                  axis=1)
            vals[:, :nlan] = stats_rows[:, :nlan]
            vmask = np.zeros((n, c), bool)
            vmask[:, :nlan] = True
            tenant = np.zeros(n, np.int64)
            if m.scope == "tenant":
                tenant[:] = gs
            elif m.scope == "device":
                for i, g in enumerate(gs):      # cold path, small n
                    info = eng.devices.get(int(g))
                    if info is not None:
                        tenant[i] = max(eng.tenants.lookup(info.tenant), 0)
            sl = SimpleNamespace(
                etype=np.zeros(n, np.int64),    # MEASUREMENT
                device=gs.astype(np.int64),
                assignment=np.full(n, p, np.int64),
                tenant=tenant,
                area=gs.astype(np.int64) if m.scope == "area"
                else np.full(n, -1, np.int64),
                customer=np.full(n, -1, np.int64),
                asset=np.full(n, -1, np.int64),
                ts_ms=w_sel.astype(np.int64) * m.window_ms,
                received_ms=(w_sel.astype(np.int64) + 1) * m.window_ms,
                values=vals, vmask=vmask,
                aux=np.stack([w_sel.astype(np.int64),
                              bs.astype(np.int64)], axis=1),
                valid=np.ones(n, bool))
            ra.append_segment(p, ra.spilled(p), sl)
            out["spilled"] += n
            out["rollups"] += 1
        with self._mu:
            self.rollup_windows_spilled += out["spilled"]
        if out["spilled"]:
            eng.host_counters["rollup_windows_spilled"] = \
                eng.host_counters.get("rollup_windows_spilled", 0) \
                + out["spilled"]
        return out

    def read_rollup_history(self, name: str, group: str | None = None,
                            since_ms: int | None = None,
                            until_ms: int | None = None,
                            limit: int = 100) -> dict:
        """Months-long dashboard read: serve one rollup's SPILLED windows
        from the rollup archive through the normal pushdown query path
        (zone maps prune by time, blooms by group) — the ring only ever
        holds the hot tail, :meth:`read_rollup` serves that."""
        eng = self.engine
        with self._mu:
            metas = list(self.rollup_meta)
        p = next((i for i, m in enumerate(metas) if m.name == name), None)
        if p is None:
            raise KeyError(f"rollup {name!r} not found")
        m = metas[p]
        base = {"rollup": name, "windowMs": m.window_ms, "scope": m.scope,
                "channel": m.channel, "buckets": []}
        ra = self.rollup_archive()
        if ra is None:
            return base
        gid = None
        if group is not None:
            gid = self._group_id(m.scope, group)
            if gid is None:
                return base
        _total, rows = ra.query(assignment=p, device=gid,
                                since_ms=since_ms, until_ms=until_ms,
                                limit=limit)
        nlan = min(4, int(eng.config.channels))
        for r in rows:
            v = np.asarray(r["values"], np.float64)
            stats = [float(v[i]) if i < nlan else 0.0 for i in range(4)]
            base["buckets"].append({
                "group": self._group_token(m.scope, int(r["device"]))
                or int(r["device"]),
                "windowStartMs": int(r["ts_ms"]),
                "count": int(stats[0]), "sum": stats[1],
                "min": stats[2], "max": stats[3],
            })
        return base


def dataclass_dict(m) -> dict:
    import dataclasses

    return dataclasses.asdict(m)


class RuleSetWatcher:
    """Background mtime poll driving ``check_reload`` + ``poll`` — the
    plain-file analog of the reference's ZooKeeper-watched Siddhi app
    deployments (and the exact shape of config.TenantConfigWatcher,
    thread-flavored because the engine API is synchronous)."""

    def __init__(self, manager: RulesManager, path, interval_s: float = 1.0,
                 poll_alerts: bool = True):
        self.manager = manager
        self.path = path
        self.interval_s = interval_s
        self.poll_alerts = poll_alerts
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self.manager.watch_file(self.path)

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.manager.check_reload()
                except Exception:
                    pass               # counted + logged by the manager
                if self.poll_alerts:
                    try:
                        self.manager.poll()
                    except Exception:
                        logger.exception("rule poll failed")

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="swtpu-rules-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
