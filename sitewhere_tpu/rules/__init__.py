"""On-device streaming rules engine (the Siddhi-analog CEP tier).

``model`` — declarative rule sets (threshold / windowed aggregate /
sequence / absence over device/area/tenant groups) + continuous-rollup
specs, validated and lowered to the device tables in ops/rules.py.
``manager`` — the host runtime: compile-before-swap installs, mtime
hot-reload, dedup-keyed alert emission through the normal ingest
pipeline, rollup reads. ``oracle`` — host-side reference semantics used
by tests and the bench parity gates.
"""

from sitewhere_tpu.rules.manager import RuleSetWatcher, RulesManager
from sitewhere_tpu.rules.model import RuleSet, RuleSetError

__all__ = ["RuleSet", "RuleSetError", "RulesManager", "RuleSetWatcher"]
