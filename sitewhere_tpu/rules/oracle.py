"""Host-side reference semantics for rules and rollups.

Plain sequential Python/numpy reimplementations of the device kernels in
ops/rules.py, processing the event stream ONE EVENT AT A TIME (the
finest possible batch partition). Because the device kernels are
batch-partition invariant by construction, their fire-key sets and
rollup tables must match this oracle exactly — that equivalence is what
tests/test_rules.py pins and the bench rules leg hard-gates.

Events are dicts: ``{"ts": int_ms, "group": int, "value": float | None,
"value_b": float | None}`` — ``group`` already resolved for the rule's
scope, ``value``/``value_b`` are the predicate channels' values (None =
channel not populated on this event). Out-of-filter events should simply
be omitted by the caller.
"""

from __future__ import annotations

INT_MIN = -(2**31)


def _cmp(v: float, op: int, ref: float) -> bool:
    return [v > ref, v >= ref, v < ref, v <= ref][op]


def threshold_fire_keys(events, *, op, value, cooldown_ms) -> set:
    """(group, window_id) keys a threshold rule fires — at most one per
    group per cooldown window."""
    keys = set()
    for e in events:
        v = e.get("value")
        if v is None or not _cmp(v, op, value):
            continue
        keys.add((e["group"], e["ts"] // cooldown_ms))
    return keys


def window_fire_keys(events, *, agg, op, value, window_ms,
                     where=None) -> set:
    """(group, window_id) keys a windowed-aggregate rule fires: the
    running aggregate of the group's current tumbling window crossed the
    predicate. ``where`` (op, value) optionally filters contributing
    events; agg in {'count','sum','min','max'}."""
    acc: dict = {}          # group -> [wid, cnt, sum, mn, mx]
    keys = set()
    for e in events:
        v = e.get("value")
        if v is None:
            continue
        if where is not None and not _cmp(v, where[0], where[1]):
            continue
        g, wid = e["group"], e["ts"] // window_ms
        st = acc.get(g)
        if st is None or wid > st[0]:
            st = acc[g] = [wid, 0, 0.0, float("inf"), float("-inf")]
        elif wid < st[0]:
            continue        # late: never mixed into a newer window
        st[1] += 1
        st[2] += v
        st[3] = min(st[3], v)
        st[4] = max(st[4], v)
        cur = {"count": st[1], "sum": st[2], "min": st[3],
               "max": st[4]}[agg]
        if _cmp(cur, op, value):
            keys.add((g, wid))
    return keys


def sequence_fire_keys(events, *, op_a, val_a, op_b, val_b,
                       within_ms) -> set:
    """(group, window_id) keys of B-after-A pairs within the horizon.
    ``value`` feeds predicate A, ``value_b`` predicate B."""
    mark: dict = {}
    keys = set()
    for e in events:
        g, ts = e["group"], e["ts"]
        vb = e.get("value_b")
        if vb is not None and _cmp(vb, op_b, val_b):
            a = mark.get(g)
            if a is not None and a <= ts <= a + within_ms:
                keys.add((g, ts // within_ms))
        va = e.get("value")
        if va is not None and _cmp(va, op_a, val_a):
            mark[g] = max(mark.get(g, INT_MIN), ts)
    return keys


def absence_fire_keys(events, *, op, value, deadline_ms,
                      final_watermark=None) -> set:
    """(group, silence_opening_ts) keys: the group matched at t, then
    stayed silent past t + deadline (observed either by its own next
    match or by the stream watermark — pass ``final_watermark`` to close
    the stream the way the kernel's trailing check does)."""
    last: dict = {}
    wm = INT_MIN
    keys = set()
    for e in events:
        g, ts = e["group"], e["ts"]
        wm = max(wm, ts)
        v = e.get("value")
        if v is None or not _cmp(v, op, value):
            continue
        prev = last.get(g)
        if prev is not None and ts - prev > deadline_ms:
            keys.add((g, prev))
        last[g] = max(last.get(g, INT_MIN), ts)
    if final_watermark is not None:
        wm = max(wm, final_watermark)
    for g, prev in last.items():
        if wm - prev > deadline_ms:
            keys.add((g, prev))
    return keys


def rollup_oracle(events, *, window_ms, buckets) -> dict:
    """Recompute a rollup's ring exactly as the device maintains it:
    ``{(group, slot): (wid, count, sum, min, max)}`` for non-empty
    slots. Newest window id wins a slot; older events for an already-
    advanced slot are late and dropped (mirrors ops/rules.py)."""
    table: dict = {}
    late = 0
    for e in events:
        v = e.get("value")
        if v is None:
            continue
        g = e["group"]
        wid = e["ts"] // window_ms
        slot = wid % buckets
        st = table.get((g, slot))
        if st is None or wid > st[0]:
            st = table[(g, slot)] = [wid, 0, 0.0, float("inf"),
                                     float("-inf")]
        elif wid < st[0]:
            late += 1
            continue
        st[1] += 1
        st[2] += v
        st[3] = min(st[3], v)
        st[4] = max(st[4], v)
    return {k: tuple(v) for k, v in table.items()}
