"""Embedded event search: the event-search service without external Solr.

The reference's service-event-search is a thin passthrough to a Solr core
fed by the Solr outbound connector (SolrSearchProvider.java:45-95 — raw query
strings in, documents out; SURVEY.md §2.8). Here the index is embedded and
host-side: a pure in-memory inverted index over outbound event documents,
with a Solr-ish query surface (field:value clauses, ranges, implicit AND) so
the REST parity endpoint (/events/search) behaves like the reference's raw
provider without a sidecar JVM. Ad-hoc filtered scans over the HBM ring
store are the separate `ops/query.py` path; this module never touches the
device.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from collections import defaultdict

from sitewhere_tpu.outbound.feed import OutboundEvent

_CLAUSE = re.compile(r"(\w+):(\[([^\]]+) TO ([^\]]+)\]|\S+)")


def event_order_key(doc: dict):
    """THE newest-first ordering for event documents — shared by the
    index's own ranking and every cluster merge (per-rank top-N
    truncation and the cross-rank merge must sort identically or the
    merge drops documents that belong in the top-N). Ties break on
    deviceToken so every rank orders the same."""
    return (-doc.get("eventDateMs", 0), -doc.get("receivedDateMs", 0),
            doc.get("deviceToken") or "")


@dataclasses.dataclass
class SearchProviderInfo:
    provider_id: str = "embedded"
    name: str = "Embedded event index"
    docs: int = 0           # corpus size behind this provider — for a
                            # cluster provider, summed over every rank


class EventSearchIndex:
    """Inverted index over outbound events (documents = event dicts)."""

    def __init__(self, capacity: int = 1 << 20):
        self.capacity = capacity
        self.docs: dict[int, dict] = {}
        self.postings: dict[tuple[str, str], set[int]] = defaultdict(set)
        self.provider_id = "embedded"
        # indexing runs on the server event loop while searches may run
        # on worker threads (REST off-loop search): short critical
        # sections, one lock
        self._lock = threading.Lock()

    @property
    def info(self) -> SearchProviderInfo:
        """Computed, not cached — ``docs`` must track the live corpus."""
        return SearchProviderInfo(provider_id=self.provider_id,
                                  docs=len(self.docs))

    def add(self, event: OutboundEvent) -> None:
        doc = event.to_json_dict()
        doc_id = event.event_id
        with self._lock:
            if doc_id in self.docs:
                # re-delivered id (at-least-once feed): drop the old
                # version's postings first so no stale key survives
                self._remove(doc_id)
            elif len(self.docs) >= self.capacity:
                # drop the oldest — ring semantics like the store.
                # Insertion order == arrival order, so the dict's first
                # key is oldest.
                self._remove(next(iter(self.docs)))
            self.docs[doc_id] = doc
            for key in self._keys_of(doc):
                self.postings[key].add(doc_id)

    @staticmethod
    def _keys_of(doc: dict) -> list[tuple[str, str]]:
        keys = [(f, str(doc[f])) for f in ("type", "deviceToken", "tenant")]
        keys.extend(("measurement", name) for name in doc["measurements"])
        return keys

    def _remove(self, doc_id: int) -> None:
        """Evict one document — O(keys of that doc), not O(all postings)."""
        doc = self.docs.pop(doc_id, None)
        if doc is None:
            return
        for key in self._keys_of(doc):
            ids = self.postings.get(key)
            if ids is not None:
                ids.discard(doc_id)
                if not ids:
                    del self.postings[key]

    def search(self, query: str, max_results: int = 100,
               order: str = "eventDate") -> list[dict]:
        """Solr-flavored query: ``field:value`` clauses are ANDed;
        ``eventDateMs:[a TO b]`` range clauses supported; ``*:*`` matches
        all. ``order``: "eventDate" (default) ranks by event_order_key
        BEFORE truncation — newest event time first, the same ordering
        every deployment topology serves (and the one a multi-index merge
        needs, or backdated events silently fall outside the top-N);
        "id" ranks by arrival (insertion id)."""
        with self._lock:
            if not query or query.strip() == "*:*":
                candidate: set[int] | None = set(self.docs)
                ranges: list[tuple[str, float, float]] = []
            else:
                candidate = None
                ranges = []
                for m in _CLAUSE.finditer(query):
                    field, value = m.group(1), m.group(2)
                    if m.group(3) is not None:  # range clause
                        lo = (-float("inf") if m.group(3) == "*"
                              else float(m.group(3)))
                        hi = (float("inf") if m.group(4) == "*"
                              else float(m.group(4)))
                        ranges.append((field, lo, hi))
                        continue
                    ids = self.postings.get((field, value), set())
                    candidate = (ids.copy() if candidate is None
                                 else candidate & ids)
                if candidate is None:
                    candidate = set(self.docs)
            key = ((lambda i: event_order_key(self.docs[i]))
                   if order == "eventDate" else (lambda i: -i))
            if ranges:
                # range filters drop candidates AFTER ranking, so top-k
                # selection could under-fill — full sort only here
                ranked = sorted(candidate, key=key)
            else:
                # top-k selection: O(n log k) and a far shorter critical
                # section than sorting a near-full index under the lock
                import heapq

                ranked = heapq.nsmallest(max_results, candidate, key=key)
            out = []
            for doc_id in ranked:
                doc = self.docs[doc_id]
                if all(lo <= float(doc.get(f, 0) or 0) <= hi
                       for f, lo, hi in ranges):
                    out.append(doc)
                    if len(out) >= max_results:
                        break
            return out


class SearchProviderManager:
    """Named search providers (reference: SearchProviderManager)."""

    def __init__(self):
        self.providers: dict[str, EventSearchIndex] = {}

    def add_provider(self, provider_id: str, index: EventSearchIndex) -> None:
        index.provider_id = provider_id
        self.providers[provider_id] = index

    def get(self, provider_id: str) -> EventSearchIndex | None:
        return self.providers.get(provider_id)

    def list_providers(self) -> list[SearchProviderInfo]:
        return [p.info for p in self.providers.values()]
