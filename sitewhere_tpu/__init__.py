"""sitewhere_tpu — a TPU-native IoT event-processing framework.

A ground-up JAX/XLA/Pallas rebuild of the capability set of SiteWhere
(KevinXu816/sitewhere): multi-protocol telemetry ingestion, device registry and
auto-registration, batched event persistence, windowed per-device state
aggregation and presence, command routing, outbound connectors, batch
operations, scheduling, and a multi-tenant REST API — with the hot pipeline as
fused XLA programs over HBM-resident state (see SURVEY.md).
"""

__version__ = "0.1.0"
