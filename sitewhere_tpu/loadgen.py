"""Load generator: the benchmark driver for the ingest→device-state path.

The reference's only load tooling is a manual JMS sender — 5 threads x 100
hard-coded JSON measurement messages aimed at a live instance
(service-event-sources/src/test/java/com/sitewhere/sources/
EventSourceTests.java:49-71, payloads built by EventsHelper.java). This
module is the CI-runnable equivalent (SURVEY.md §4d): it generates the same
canonical DeviceRequest measurement JSON, drives either the engine's native
host path or a live REST gateway, and reports throughput plus end-to-end
ingest→device-state latency percentiles — the BASELINE.md north-star metrics
(events/sec/chip, inbound→state p99 < 50 ms).

Modes:
  * engine — payload bytes → C++ batch decode → staging → fused TPU step →
    state merged. Latency is measured per batch from first submit to the
    flush return that made the batch's events visible in device state
    (CLOSED loop: the next batch waits for the previous one).
  * open loop — a seeded, deterministic schedule of per-tenant Poisson
    arrivals carrying a MIXED ingest/query/entity-mutation workload
    (``build_open_loop_schedule`` + ``run_open_loop``). The generator
    fires on the schedule's clock, never the engine's: when the engine
    falls behind, events queue and their measured latency GROWS — the
    queueing delay a closed-loop driver structurally hides, and exactly
    what per-tenant SLO measurement must see. Per-event wire→state
    latencies sample into log-bucketed histograms (p50/p99/p99.9).
  * rest — HTTP POSTs against a running gateway (wire-level e2e).

CLI: ``python -m sitewhere_tpu.loadgen --batches 50 --batch-size 4096``.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import time

import numpy as np


def generate_measurements_message(token: str, seq: int,
                                  name: str = "engine.temperature",
                                  value: float | None = None) -> bytes:
    """Canonical JSON measurement DeviceRequest
    (EventsHelper.generateJsonMeasurementsMessage analog)."""
    payload = {
        "deviceToken": token,
        "type": "DeviceMeasurement",
        "request": {
            "name": name,
            "value": value if value is not None else round(20.0 + (seq % 80) * 0.5, 2),
            "eventDate": None,
            "updateState": True,
            "metadata": {"seq": str(seq)},
        },
    }
    return json.dumps(payload).encode()


@dataclasses.dataclass
class LoadStats:
    events_sent: int
    events_decoded: int
    events_failed: int
    wall_s: float
    events_per_s: float
    latency_p50_ms: float
    latency_p99_ms: float
    latency_max_ms: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _percentiles(lat_ms: list[float]) -> tuple[float, float, float]:
    arr = np.asarray(lat_ms)
    return (float(np.percentile(arr, 50)), float(np.percentile(arr, 99)),
            float(arr.max()))


def run_engine_load(engine, n_batches: int = 50, batch_size: int = 4096,
                    n_devices: int = 10_000, seed: int = 0,
                    warmup_batches: int = 3,
                    pipelined: bool = False,
                    sample_every: int = 8) -> LoadStats:
    """Drive the full host path: JSON bytes → native decode → staged → fused
    step → device state.

    pipelined=False — per-batch latency = submit → flush return (state
    merged and visible on the host), the inbound→device-state span of
    SURVEY.md §3.2-3.3.
    pipelined=True — steady-state throughput: batches dispatch as scanned
    chunks; every chunk dispatch is completion-synchronous inside the
    engine (depth-1), so each batch's e2e latency — submit → its chunk's
    state merge completed — is observed WITHOUT any device->host readback
    (readbacks permanently downshift remote-tunnel transfer streams). The
    timed window ends at a readback-free ``barrier()``; mirror drain is
    teardown/reporting, not ingest.
    """
    rng = np.random.default_rng(seed)
    toks = [f"lg-{i}" for i in range(n_devices)]

    def make_batch(b: int) -> list[bytes]:
        picks = rng.integers(0, n_devices, batch_size)
        return [generate_measurements_message(toks[d], b * batch_size + i)
                for i, d in enumerate(picks)]

    for w in range(warmup_batches):          # compile + interner warm:
        engine.ingest_json_batch(make_batch(w))
        if not pipelined:
            engine.flush()
    if pipelined:
        # warmup compiles the scan-chunk program (incl. the padded-tail
        # shape) without a mirror readback
        engine.barrier()
    else:
        engine.flush()

    # pre-build payloads so the generator itself stays out of the timing
    prebuilt = [make_batch(b) for b in range(n_batches)]
    latencies: list[float] = []
    decoded = failed = 0
    submits: list[float] = []
    t0 = time.perf_counter()
    for i, payloads in enumerate(prebuilt):
        s0 = time.perf_counter()
        res = engine.ingest_json_batch(payloads)
        if pipelined:
            submits.append(s0)
            if engine.staged_count:
                engine.flush_async()
            if engine.staged_count == 0:
                # the chunk holding every pending submit just completed
                # (dispatch blocks until the state merge finished)
                done = time.perf_counter()
                latencies.extend((done - s) * 1e3 for s in submits)
                submits.clear()
        else:
            engine.flush()                    # state merged on return
            latencies.append((time.perf_counter() - s0) * 1e3)
        decoded += res["decoded"]
        failed += res["failed"]
    if pipelined:
        engine.barrier()                      # tail chunk, no readback
        done = time.perf_counter()
        latencies.extend((done - s) * 1e3 for s in submits)
    wall = time.perf_counter() - t0
    p50, p99, mx = _percentiles(latencies)
    sent = n_batches * batch_size
    return LoadStats(sent, decoded, failed, wall, sent / wall, p50, p99, mx)


# ---------------------------------------------------------------------------
# Open-loop mixed-workload harness (ISSUE 7).
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class TenantLoad:
    """One tenant's arrival process and workload mix."""

    tenant: str
    rate_eps: float                    # mean event arrival rate (Poisson)
    n_devices: int = 64
    device_prefix: str | None = None   # default "<tenant>-dev"
    query_every: int = 0               # one query per N ingest frames
    mutate_every: int = 0              # one entity mutation per N frames
    history_every: int = 0             # one HISTORICAL query per N frames:
                                       # a date range ending history_age_ms
                                       # in the past, so an archive-primed
                                       # engine serves it from the tiered
                                       # (ring + disk) read path
    history_age_ms: int = 60_000       # how far behind "now" the range ends
    analytics_every: int = 0           # one historical SCORING JOB per N
                                       # frames (ISSUE 19): a deterministic
                                       # marker, mirror of history_every —
                                       # the schedule stays a pure function
                                       # of the spec (the driver resolves
                                       # it against engine.analytics_jobs
                                       # at fire time; engines without the
                                       # manager skip it), and with the
                                       # knob OFF the schedule is
                                       # byte-identical to pre-knob runs
    abusive_mult: float = 1.0          # noisy-neighbor knob (ISSUE 9):
                                       # during burst windows the tenant
                                       # offers rate_eps * abusive_mult.
                                       # Extra arrivals come from a
                                       # SEPARATE seeded stream, so a
                                       # schedule with the knob OFF stays
                                       # byte-identical to pre-knob runs
    abusive_period_s: float = 0.0      # burst window period; 0 (with
                                       # mult > 1) = the whole horizon
    abusive_burst_s: float = 0.0       # burst length within each period
    abusive_device: int | None = None  # hotspot knob (ISSUE 18): pin
                                       # every EXTRA (abusive-stream)
                                       # event onto this one device
                                       # index, concentrating the burst
                                       # on a single placement slot /
                                       # shard lane so the heat plane
                                       # has a known-hot target. None
                                       # (default) keeps the extra
                                       # stream's device picks from the
                                       # base RNG — byte-identical to
                                       # pre-knob schedules
    rule_trigger_eps: float = 0.0      # rule-trigger traffic (ISSUE 13):
                                       # a SEPARATE seeded Poisson stream
                                       # of threshold-crossing
                                       # measurements (value =
                                       # rule_value on rule_channel)
                                       # superimposed on the base load —
                                       # same additivity/fingerprint
                                       # discipline as the abusive knob:
                                       # with the knob OFF (rate 0) the
                                       # schedule is byte-identical to a
                                       # pre-knob run
    rule_period_s: float = 0.0         # trigger burst period; 0 (with
                                       # eps > 0) = the whole horizon
    rule_burst_s: float = 0.0          # burst length within each period
    rule_channel: str = "engine.temperature"   # channel the crossings hit
    rule_value: float = 96.5           # crossing value (exactly f32-
                                       # representable so sum-rollup
                                       # parity is rounding-order-free)


@dataclasses.dataclass(frozen=True)
class OpenLoopSpec:
    """A complete, seed-determined load description: same spec + same
    seed => byte-identical payload stream and identical arrival
    schedule (pinned by tests/test_loadgen.py)."""

    tenants: tuple
    duration_s: float = 1.0
    frame_size: int = 64               # events per ingest submission
    seed: int = 0


@dataclasses.dataclass
class ScheduledOp:
    """One scheduled action. ``t_s`` is the arrival offset from schedule
    start; ingest frames also carry each event's OWN arrival offset so
    latency is measured per event, from the moment it notionally hit
    the wire — not from whenever the backlogged driver got to it."""

    t_s: float
    kind: str                          # "ingest" | "query" | "mutate"
    tenant: str
    payloads: list | None = None
    arrivals: tuple | None = None
    query: dict | None = None
    mutate: tuple | None = None        # (op, token, metadata)
    analytics: dict | None = None      # AnalyticsJobSpec kwargs (ISSUE 19)


_KIND_ORDER = {"ingest": 0, "query": 1, "mutate": 2, "analytics": 3}


def build_open_loop_schedule(spec: OpenLoopSpec) -> list[ScheduledOp]:
    """Deterministic open-loop schedule: per-tenant Poisson arrivals
    (seeded per tenant index), events grouped into frames of
    ``frame_size`` (a frame departs when its LAST event has arrived),
    with query and entity-mutation ops interleaved at each tenant's
    configured cadence. Pure function of the spec — no wall clock, no
    global RNG."""
    ops: list[ScheduledOp] = []
    for ti, tl in enumerate(spec.tenants):
        rng = np.random.default_rng([spec.seed, ti])
        prefix = tl.device_prefix or f"{tl.tenant}-dev"
        if tl.rate_eps <= 0:
            continue
        # draw inter-arrival gaps in chunks until past the horizon
        gaps: list[np.ndarray] = []
        total = 0.0
        while total < spec.duration_s:
            g = rng.exponential(1.0 / tl.rate_eps,
                                size=max(64, int(tl.rate_eps * 0.25) or 64))
            gaps.append(g)
            total += float(g.sum())
        arr = np.cumsum(np.concatenate(gaps))
        arr = arr[arr < spec.duration_s]
        if tl.abusive_mult > 1.0:
            # noisy-neighbor bursts: superimpose an EXTRA Poisson stream
            # at rate * (mult - 1), thinned to the burst windows — the
            # union of Poisson processes is Poisson at the summed rate,
            # so inside a window the tenant offers rate * mult. The
            # extra stream draws from its own seeded generator: the base
            # stream's draws (and every other tenant's schedule) are
            # untouched, keeping non-abusive fingerprints stable.
            xrng = np.random.default_rng([spec.seed, ti, 0xAB])
            xrate = tl.rate_eps * (tl.abusive_mult - 1.0)
            xgaps: list[np.ndarray] = []
            xtotal = 0.0
            while xtotal < spec.duration_s:
                g = xrng.exponential(
                    1.0 / xrate, size=max(64, int(xrate * 0.25) or 64))
                xgaps.append(g)
                xtotal += float(g.sum())
            xarr = np.cumsum(np.concatenate(xgaps))
            xarr = xarr[xarr < spec.duration_s]
            if tl.abusive_period_s > 0 and tl.abusive_burst_s > 0:
                xarr = xarr[(xarr % tl.abusive_period_s)
                            < tl.abusive_burst_s]
            # stable argsort == np.sort(kind="stable") on the times,
            # while also carrying WHICH rows came from the extra stream
            # (the hotspot knob needs the provenance; the merged arrival
            # array is byte-identical either way)
            n_base = len(arr)
            both = np.concatenate([arr, xarr])
            order = np.argsort(both, kind="stable")
            arr = both[order]
            abusive_at = order >= n_base
        else:
            abusive_at = None
        picks = rng.integers(0, tl.n_devices, len(arr))
        if abusive_at is not None and tl.abusive_device is not None:
            # hotspot: the extra stream's events all land on one device
            # (one slot, one shard). picks is drawn BEFORE this with the
            # same count either way, so base-stream devices — and every
            # abusive_device=None schedule — keep their fingerprints
            picks = picks.copy()
            picks[abusive_at] = int(tl.abusive_device) % tl.n_devices
        is_rule = np.zeros(len(arr), bool)
        if tl.rule_trigger_eps > 0:
            # rule-trigger traffic (ISSUE 13): threshold-crossing
            # measurements from their OWN seeded stream, merged after the
            # base draws — the base stream's draws (and every other
            # tenant's schedule) are untouched, so a schedule with the
            # knob OFF keeps its pre-knob fingerprint (the abusive-knob
            # additivity discipline)
            rrng = np.random.default_rng([spec.seed, ti, 0x51])
            rgaps: list[np.ndarray] = []
            rtotal = 0.0
            while rtotal < spec.duration_s:
                g = rrng.exponential(
                    1.0 / tl.rule_trigger_eps,
                    size=max(64, int(tl.rule_trigger_eps * 0.25) or 64))
                rgaps.append(g)
                rtotal += float(g.sum())
            rarr = np.cumsum(np.concatenate(rgaps))
            rarr = rarr[rarr < spec.duration_s]
            if tl.rule_period_s > 0 and tl.rule_burst_s > 0:
                rarr = rarr[(rarr % tl.rule_period_s) < tl.rule_burst_s]
            rpicks = rrng.integers(0, tl.n_devices, len(rarr))
            order = np.argsort(np.concatenate([arr, rarr]), kind="stable")
            arr = np.concatenate([arr, rarr])[order]
            picks = np.concatenate([picks, rpicks])[order]
            is_rule = np.concatenate(
                [is_rule, np.ones(len(rarr), bool)])[order]
        mut_registered: set[str] = set()
        n_frames = 0
        for lo in range(0, len(arr), spec.frame_size):
            hi = min(lo + spec.frame_size, len(arr))
            payloads = [generate_measurements_message(
                f"{prefix}-{int(picks[k])}", ti * 10_000_000 + k,
                **({"name": tl.rule_channel, "value": tl.rule_value}
                   if is_rule[k] else {}))
                for k in range(lo, hi)]
            frame_t = float(arr[hi - 1])
            ops.append(ScheduledOp(
                t_s=frame_t, kind="ingest", tenant=tl.tenant,
                payloads=payloads,
                arrivals=tuple(float(a) for a in arr[lo:hi])))
            n_frames += 1
            if tl.query_every and n_frames % tl.query_every == 0:
                variant = (n_frames // tl.query_every) % 3
                if variant == 0:
                    q = {"limit": 20}
                elif variant == 1:
                    q = {"device_token":
                         f"{prefix}-{int(picks[lo])}", "limit": 20}
                else:
                    q = {"since_ms": 0, "limit": 20}
                ops.append(ScheduledOp(t_s=frame_t, kind="query",
                                       tenant=tl.tenant, query=q))
            if tl.history_every and n_frames % tl.history_every == 0:
                # deterministic MARKER, not a concrete range: the schedule
                # is a pure function of the spec (no wall clock), so the
                # driver resolves the range against the engine's epoch at
                # fire time — "everything up to history_age_ms ago", which
                # on an archive-primed engine lands beyond the ring
                hv = (n_frames // tl.history_every) % 2
                q = {"history_age_ms": tl.history_age_ms, "limit": 20}
                if hv == 1:
                    q["device_token"] = f"{prefix}-{int(picks[lo])}"
                ops.append(ScheduledOp(t_s=frame_t, kind="query",
                                       tenant=tl.tenant, query=q))
            if tl.analytics_every and n_frames % tl.analytics_every == 0:
                # deterministic scoring-job MARKER (ISSUE 19), the
                # history_every mirror: a pure function of the spec — the
                # driver resolves it into an archive->device batched
                # scoring job at fire time. emit=False keeps the measured
                # ingest stream closed (scores don't feed back into the
                # event counts the run asserts on); the name pins the
                # job's dedup-key lineage per marker
                j = n_frames // tl.analytics_every
                a = {"window": 8, "min_fill": 1, "batch_devices": 8,
                     "emit": False, "name": f"lg-{tl.tenant}-{j}"}
                ops.append(ScheduledOp(t_s=frame_t, kind="analytics",
                                       tenant=tl.tenant, analytics=a))
            if tl.mutate_every and n_frames % tl.mutate_every == 0:
                j = n_frames // tl.mutate_every
                token = f"{prefix}-m{j % 8}"
                if token not in mut_registered:
                    mut_registered.add(token)
                    mut = ("register", token, None)
                else:
                    mut = ("update", token, {"rev": str(j)})
                ops.append(ScheduledOp(t_s=frame_t, kind="mutate",
                                       tenant=tl.tenant, mutate=mut))
    ops.sort(key=lambda op: (op.t_s, op.tenant, _KIND_ORDER[op.kind]))
    return ops


def schedule_fingerprint(schedule: list[ScheduledOp]) -> str:
    """SHA-256 over the canonical byte form of a schedule — the
    determinism pin (same seed => same fingerprint) and the provenance
    field the bench records next to its measured numbers."""
    h = hashlib.sha256()
    for op in schedule:
        h.update(f"{op.kind}|{op.tenant}|{op.t_s!r}\n".encode())
        for p in op.payloads or ():
            h.update(p)
        for a in op.arrivals or ():
            h.update(repr(a).encode())
        if op.query is not None:
            h.update(json.dumps(op.query, sort_keys=True).encode())
        if op.mutate is not None:
            h.update(repr(op.mutate).encode())
        if op.analytics is not None:
            h.update(json.dumps(op.analytics, sort_keys=True).encode())
    return h.hexdigest()


def _pcts(lat_ms: list[float]) -> dict:
    if not lat_ms:
        return {"p50_ms": None, "p99_ms": None, "p999_ms": None,
                "max_ms": None}
    a = np.asarray(lat_ms)
    return {"p50_ms": round(float(np.percentile(a, 50)), 3),
            "p99_ms": round(float(np.percentile(a, 99)), 3),
            "p999_ms": round(float(np.percentile(a, 99.9)), 3),
            "max_ms": round(float(a.max()), 3)}


@dataclasses.dataclass
class OpenLoopResult:
    """Per-tenant SLO view of one open-loop run. For each tenant,
    ``per_tenant[t]`` carries two latency families:

      e2e_*      scheduled arrival -> visible in device state. THE SLO
                 number: includes queueing delay whenever the engine
                 (or the driver) fell behind the arrival process.
      service_*  submit -> visible. The engine-side span comparable to
                 the flight-recorder-harvested swtpu_ingest_e2e_seconds
                 histogram (same start edge as the batch's flight
                 record). e2e == service when the run kept pace.

    With QoS enabled on the engine (``engine.qos``), the driver acts as
    the admission EDGE: shed frames are counted per tenant (``shed`` in
    ``per_tenant``, ``shed_events`` in total) and never submitted —
    ``events`` is the ADMITTED count, the denominator of any
    zero-admitted-loss check.
    """

    wall_s: float
    events: int
    events_per_s: float
    offered_eps: float
    queries: int
    query_p99_ms: float | None
    history_queries: int
    history_p99_ms: float | None
    scoring_jobs: int
    scoring_p50_ms: float | None
    scoring_p99_ms: float | None
    mutations: int
    max_lateness_s: float
    per_tenant: dict
    shed_events: int = 0
    # span/trace coverage (ISSUE 10): fraction of a sample of this run's
    # ingest trace ids that still resolve on the engine (flight records
    # or spans) after the run — the observability plane's own SLO. None
    # when the run ingested nothing.
    trace_coverage: float | None = None
    # device plane (ISSUE 11): XLA programs compiled per watched family
    # DURING this run (devicewatch totals delta). A warm steady-state
    # run should show {} — any entry is a latency cliff the SLO
    # histograms would otherwise launder into "one slow frame". None
    # when devicewatch is unavailable/disabled.
    compile_counts: dict | None = None
    # ingest-path provenance (ISSUE 17): host_counters deltas over the
    # run — ``arena_rows`` (rows scattered zero-copy into staging
    # arenas) vs ``staged_copy_rows`` (rows that took a per-row host
    # copy). On an SpmdEngine in its default arena mode every measured
    # event should land in arena_rows, pinning that open-loop --shards
    # numbers exercise the batch ingest edge, not per-event staging.
    ingest_path: dict | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def run_open_loop(engine, schedule: list[ScheduledOp], *,
                  checkpoint_frames: int = 4,
                  time_scale: float = 1.0) -> OpenLoopResult:
    """Replay a schedule against a live engine (Engine, DistributedEngine,
    ClusterEngine or the mesh-sharded SpmdEngine — anything with
    ingest_json_batch / query_events / flush; the driver never looks
    inside, so the SPMD router's slot fan-out is exercised exactly as
    production traffic would). Ops fire at their scheduled time; a late
    driver fires
    immediately and the lateness lands in the measured latency (open
    loop). Completion checkpoints every ``checkpoint_frames`` ingest
    frames call ``engine.flush()`` — on a cluster facade that fans out,
    so forwarded events count only once visible at their OWNER."""
    pending: list[tuple[str, list[float], float]] = []
    per: dict[str, tuple[list, list]] = {}
    qlat: list[float] = []
    hlat: list[float] = []
    alat: list[float] = []
    epoch = getattr(engine, "epoch", None)
    # the driver is an ingest EDGE: with QoS on, every frame faces the
    # engine's admission controller here — shed frames count per tenant
    # and are never submitted (the client saw an explicit 429)
    qos = getattr(engine, "qos", None)
    shed: dict[str, int] = {}
    trace_sample: list[str] = []   # first few ingest trace ids: span
    #                                coverage is checked after the run
    mutations = 0
    max_late = 0.0
    frames = 0
    events = 0
    # devicewatch (ISSUE 11): snapshot per-family compile totals so the
    # result reports compiles observed DURING the run
    compiles0 = None
    try:
        from sitewhere_tpu.utils.devicewatch import WATCH, compile_totals

        if WATCH.enabled:
            compiles0 = compile_totals()
    except ImportError:
        pass
    hc0 = dict(getattr(engine, "host_counters", None) or {})
    t0 = time.perf_counter()

    def checkpoint():
        nonlocal frames
        frames = 0
        if not pending:
            return
        engine.flush()
        t_done = time.perf_counter()
        for tenant, arrivals, submit in pending:
            e2e, svc = per.setdefault(tenant, ([], []))
            e2e.extend((t_done - a) * 1e3 for a in arrivals)
            svc.extend([(t_done - submit) * 1e3] * len(arrivals))
        pending.clear()

    for op in schedule:
        target = t0 + op.t_s * time_scale
        now = time.perf_counter()
        if now < target:
            time.sleep(target - now)
        else:
            max_late = max(max_late, now - target)
        if op.kind == "ingest":
            if qos is not None:
                d = qos.admit(op.tenant, len(op.payloads))
                if not d.admitted:
                    shed[op.tenant] = (shed.get(op.tenant, 0)
                                       + len(op.payloads))
                    continue
            submit = time.perf_counter()
            summary = engine.ingest_json_batch(op.payloads, op.tenant)
            tid = (summary or {}).get("trace_id")
            if tid and len(trace_sample) < 16:
                trace_sample.append(tid)
            pending.append((op.tenant,
                            [t0 + a * time_scale for a in op.arrivals],
                            submit))
            events += len(op.payloads)
            frames += 1
            if frames >= checkpoint_frames:
                checkpoint()
        elif op.kind == "query":
            q = dict(op.query)
            age = q.pop("history_age_ms", None)
            if age is not None:
                # resolve the historical marker at fire time: a range from
                # the beginning of history (unbounded start — backfilled
                # events can sit at negative epoch-relative ms) to ``age``
                # before now — older than the ring on any archive-primed
                # run, so the tiered read path serves it
                now_rel = (int(epoch.now_ms()) if epoch is not None
                           else 0)
                q["until_ms"] = now_rel - int(age)
            t1 = time.perf_counter()
            engine.query_events(**q)
            (hlat if age is not None
             else qlat).append((time.perf_counter() - t1) * 1e3)
        elif op.kind == "analytics":
            # archive->device scoring-job marker (ISSUE 19): resolved
            # against the engine's job manager at fire time; engines
            # without the manager (or without an archive to stream from)
            # skip it, so plain-store schedules replay unchanged
            aj = getattr(engine, "analytics_jobs", None)
            if aj is not None and getattr(engine, "archive", None) is not None:
                t1 = time.perf_counter()
                aj.run_job(dict(op.analytics, tenant=op.tenant))
                alat.append((time.perf_counter() - t1) * 1e3)
        else:
            kind, token, md = op.mutate
            if kind == "register":
                engine.register_device(token, tenant=op.tenant)
            else:
                try:
                    engine.update_device(token, metadata=md)
                except KeyError:
                    engine.register_device(token, tenant=op.tenant)
            mutations += 1
    checkpoint()
    wall = time.perf_counter() - t0
    # span/trace coverage (ISSUE 10): every sampled ingest trace id must
    # still resolve to a non-empty timeline (flight-record intervals or
    # live spans) — the observability plane's own SLO, reported by the
    # bench cluster leg
    coverage = None
    get_tl = getattr(engine, "get_trace_timeline", None)
    if trace_sample and get_tl is not None:
        hits = 0
        for tid in trace_sample:
            try:
                doc = get_tl(tid)
            except Exception:
                continue
            if any(e.get("ph") == "X" for e in doc.get("traceEvents", ())):
                hits += 1
        coverage = round(hits / len(trace_sample), 3)
    horizon = max((op.t_s for op in schedule), default=0.0) * time_scale
    per_tenant = {}
    for tenant in sorted(set(per) | set(shed)):
        e2e, svc = per.get(tenant, ([], []))
        per_tenant[tenant] = {
            "events": len(e2e),
            "shed": shed.get(tenant, 0),
            **{f"e2e_{k}": v for k, v in _pcts(e2e).items()},
            **{f"service_{k}": v for k, v in _pcts(svc).items()},
        }
    compile_counts = None
    if compiles0 is not None:
        from sitewhere_tpu.utils.devicewatch import compile_totals

        compile_counts = {
            fam: n - compiles0.get(fam, 0)
            for fam, n in compile_totals().items()
            if n - compiles0.get(fam, 0)}
    hc1 = getattr(engine, "host_counters", None) or {}
    ingest_path = {k: int(hc1.get(k, 0)) - int(hc0.get(k, 0))
                   for k in ("arena_rows", "staged_copy_rows")}
    qp = _pcts(qlat)
    hp = _pcts(hlat)
    ap = _pcts(alat)
    return OpenLoopResult(
        wall_s=round(wall, 3), events=events,
        events_per_s=round(events / wall, 1) if wall else 0.0,
        offered_eps=round((events + sum(shed.values())) / horizon, 1)
        if horizon else 0.0,
        queries=len(qlat), query_p99_ms=qp["p99_ms"],
        history_queries=len(hlat), history_p99_ms=hp["p99_ms"],
        scoring_jobs=len(alat), scoring_p50_ms=ap["p50_ms"],
        scoring_p99_ms=ap["p99_ms"],
        mutations=mutations, max_lateness_s=round(max_late, 4),
        per_tenant=per_tenant, shed_events=sum(shed.values()),
        trace_coverage=coverage, compile_counts=compile_counts,
        ingest_path=ingest_path)


# ---------------------------------------------------------------------------
# Persistent-connection wire mode (ISSUE 20).
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class WireLoadSpec:
    """Seed-determined description of a connection-holding load: N live
    connections, each carrying its own deterministic frame list. Same
    spec + same seed => byte-identical frames per connection (the
    ``build_open_loop_schedule`` fingerprint discipline; existing
    open-loop schedules are untouched by this mode)."""

    n_connections: int = 1000
    frames_per_conn: int = 10
    n_devices: int = 256
    tenant: str = "default"
    device_prefix: str = "wl-dev"
    seed: int = 0


def build_wire_schedule(spec: WireLoadSpec) -> list[list[bytes]]:
    """Per-connection payload lists — a pure function of the spec (each
    connection draws from its own seeded stream, so connection counts can
    change without disturbing other connections' frames)."""
    out: list[list[bytes]] = []
    for c in range(spec.n_connections):
        rng = np.random.default_rng([spec.seed, c])
        picks = rng.integers(0, spec.n_devices, spec.frames_per_conn)
        out.append([
            generate_measurements_message(
                f"{spec.device_prefix}-{int(d)}", c * 1_000_000 + i)
            for i, d in enumerate(picks)
        ])
    return out


def wire_schedule_fingerprint(payload_lists: list[list[bytes]]) -> str:
    """SHA-256 over the canonical byte form — the determinism pin the
    bench records next to its measured wire numbers."""
    h = hashlib.sha256()
    for i, frames in enumerate(payload_lists):
        h.update(f"conn|{i}|{len(frames)}\n".encode())
        for p in frames:
            h.update(p)
    return h.hexdigest()


def _rss_bytes() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


@dataclasses.dataclass
class WireLoadResult:
    """One connection-holding run against a live wire edge. Connections
    stay OPEN for the whole run — ``per_connection_bytes`` is the RSS
    delta from before the connect wave to all-connected, divided by the
    connection count (client and server share the process in the bench,
    so the figure covers both ends of each connection)."""

    connections: int
    events: int
    acked: int
    wall_s: float
    events_per_s: float
    connect_s: float
    per_connection_bytes: float
    publish_p50_ms: float | None
    publish_p99_ms: float | None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


async def run_wire_load(host: str, port: int,
                        payload_lists: list[list[bytes]], *,
                        tenant: str = "default", qos: int = 1,
                        connect_wave: int = 100,
                        client_id_prefix: str = "wl") -> WireLoadResult:
    """Hold ``len(payload_lists)`` live MQTT connections against a wire
    edge and publish each connection's frames (QoS 1 by default: every
    publish awaits its WAL-durable PUBACK). Connections open in waves of
    ``connect_wave`` to keep the accept queue shallow, then ALL of them
    stay open while frames interleave across the full set — the
    persistent-connection contrast to one-request-per-event drivers."""
    from sitewhere_tpu.ingest.mqtt import MqttClient

    rss0 = _rss_bytes()
    t_conn = time.perf_counter()
    clients: list[MqttClient] = []
    for lo in range(0, len(payload_lists), connect_wave):
        wave = []
        for i in range(lo, min(lo + connect_wave, len(payload_lists))):
            c = MqttClient(host, port, client_id=f"{client_id_prefix}-{i}",
                           keepalive=0)
            clients.append(c)
            wave.append(c.connect())
        await asyncio.gather(*wave)
    connect_s = time.perf_counter() - t_conn
    per_conn = ((_rss_bytes() - rss0) / len(clients)) if clients else 0.0

    topic = f"swtpu/{tenant}/events"
    lat: list[float] = []
    acked = 0

    async def one_conn(c: MqttClient, frames: list[bytes]) -> None:
        nonlocal acked
        for p in frames:
            s0 = time.perf_counter()
            await asyncio.wait_for(c.publish(topic, p, qos=qos), 60)
            lat.append((time.perf_counter() - s0) * 1e3)
            if qos:
                acked += 1

    t0 = time.perf_counter()
    await asyncio.gather(*(one_conn(c, f)
                           for c, f in zip(clients, payload_lists)))
    wall = time.perf_counter() - t0
    await asyncio.gather(*(c.disconnect() for c in clients),
                         return_exceptions=True)
    events = sum(len(f) for f in payload_lists)
    pct = _pcts(lat)
    return WireLoadResult(
        connections=len(clients), events=events,
        acked=acked if qos else events,
        wall_s=round(wall, 3),
        events_per_s=round(events / wall, 1) if wall else 0.0,
        connect_s=round(connect_s, 3),
        per_connection_bytes=round(per_conn, 1),
        publish_p50_ms=pct["p50_ms"], publish_p99_ms=pct["p99_ms"])


async def run_rest_load(base_url: str, jwt: str, n_workers: int = 5,
                        msgs_per_worker: int = 100,
                        device_prefix: str = "rest-lg") -> LoadStats:
    """Wire-level driver: N concurrent workers x M posts each (the 5x100
    pattern of EventSourceTests.java:50-53) against /api/devices/{t}/events."""
    import asyncio

    import aiohttp

    latencies: list[float] = []
    failed = 0
    headers = {"Authorization": f"Bearer {jwt}"}

    async def worker(w: int, session: aiohttp.ClientSession):
        nonlocal failed
        token = f"{device_prefix}-{w}"
        for i in range(msgs_per_worker):
            body = json.loads(generate_measurements_message(token, i))
            s0 = time.perf_counter()
            async with session.post(
                f"{base_url}/api/devices/{token}/events",
                json=body, headers=headers,
            ) as r:
                if r.status != 201:
                    failed += 1
                await r.read()
            latencies.append((time.perf_counter() - s0) * 1e3)

    t0 = time.perf_counter()
    async with aiohttp.ClientSession() as session:
        await asyncio.gather(*(worker(w, session) for w in range(n_workers)))
    wall = time.perf_counter() - t0
    sent = n_workers * msgs_per_worker
    p50, p99, mx = _percentiles(latencies)
    return LoadStats(sent, sent - failed, failed, wall, sent / wall, p50, p99, mx)


def main() -> None:
    import argparse

    from sitewhere_tpu.engine import Engine, EngineConfig

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batches", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=4096)
    ap.add_argument("--devices", type=int, default=10_000)
    ap.add_argument("--open-loop", action="store_true",
                    help="seeded open-loop mixed workload instead of the "
                         "closed-loop batch driver")
    ap.add_argument("--rate", type=float, default=5000.0,
                    help="open-loop arrival rate (events/s)")
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shards", type=int, default=0,
                    help="drive the mesh-sharded SPMD engine with N "
                         "shards instead of a single-chip engine "
                         "(0 = single-chip; requires >= N attached "
                         "devices). Wire frames go through the batch "
                         "ingest edge (arena scatter), never per-event "
                         "staging — the result's ingest_path counters "
                         "pin it")
    args = ap.parse_args()

    cfg = EngineConfig(
        device_capacity=max(1 << 15, 1 << (args.devices - 1).bit_length()),
        token_capacity=1 << 17, assignment_capacity=1 << 17,
        store_capacity=1 << 18, batch_capacity=args.batch_size,
    )
    if args.shards:
        from sitewhere_tpu.parallel.sharded import SpmdEngine

        engine = SpmdEngine(cfg, n_shards=args.shards)
    else:
        engine = Engine(cfg)
    if args.open_loop:
        # warm OUTSIDE the measured schedule: the first flush pays the
        # fused-step jit compile (seconds), which would otherwise land
        # in — and, open-loop, cascade through — every reported latency
        run_engine_load(engine, n_batches=1, batch_size=args.batch_size,
                        n_devices=min(args.devices, 4096),
                        warmup_batches=1)
        spec = OpenLoopSpec(
            tenants=(TenantLoad("default", args.rate,
                                n_devices=min(args.devices, 4096),
                                query_every=8, mutate_every=16),),
            duration_s=args.duration,
            frame_size=min(args.batch_size, 512), seed=args.seed)
        schedule = build_open_loop_schedule(spec)
        res = run_open_loop(engine, schedule)
        print(json.dumps({
            "schedule_fingerprint": schedule_fingerprint(schedule),
            **res.to_dict()}))
        return
    stats = run_engine_load(engine, args.batches, args.batch_size, args.devices)
    print(json.dumps(stats.to_dict()))


if __name__ == "__main__":
    main()
