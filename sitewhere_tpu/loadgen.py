"""Load generator: the benchmark driver for the ingest→device-state path.

The reference's only load tooling is a manual JMS sender — 5 threads x 100
hard-coded JSON measurement messages aimed at a live instance
(service-event-sources/src/test/java/com/sitewhere/sources/
EventSourceTests.java:49-71, payloads built by EventsHelper.java). This
module is the CI-runnable equivalent (SURVEY.md §4d): it generates the same
canonical DeviceRequest measurement JSON, drives either the engine's native
host path or a live REST gateway, and reports throughput plus end-to-end
ingest→device-state latency percentiles — the BASELINE.md north-star metrics
(events/sec/chip, inbound→state p99 < 50 ms).

Modes:
  * engine — payload bytes → C++ batch decode → staging → fused TPU step →
    state merged. Latency is measured per batch from first submit to the
    flush return that made the batch's events visible in device state.
  * rest — HTTP POSTs against a running gateway (wire-level e2e).

CLI: ``python -m sitewhere_tpu.loadgen --batches 50 --batch-size 4096``.
"""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np


def generate_measurements_message(token: str, seq: int,
                                  name: str = "engine.temperature",
                                  value: float | None = None) -> bytes:
    """Canonical JSON measurement DeviceRequest
    (EventsHelper.generateJsonMeasurementsMessage analog)."""
    payload = {
        "deviceToken": token,
        "type": "DeviceMeasurement",
        "request": {
            "name": name,
            "value": value if value is not None else round(20.0 + (seq % 80) * 0.5, 2),
            "eventDate": None,
            "updateState": True,
            "metadata": {"seq": str(seq)},
        },
    }
    return json.dumps(payload).encode()


@dataclasses.dataclass
class LoadStats:
    events_sent: int
    events_decoded: int
    events_failed: int
    wall_s: float
    events_per_s: float
    latency_p50_ms: float
    latency_p99_ms: float
    latency_max_ms: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _percentiles(lat_ms: list[float]) -> tuple[float, float, float]:
    arr = np.asarray(lat_ms)
    return (float(np.percentile(arr, 50)), float(np.percentile(arr, 99)),
            float(arr.max()))


def run_engine_load(engine, n_batches: int = 50, batch_size: int = 4096,
                    n_devices: int = 10_000, seed: int = 0,
                    warmup_batches: int = 3,
                    pipelined: bool = False,
                    sample_every: int = 8) -> LoadStats:
    """Drive the full host path: JSON bytes → native decode → staged → fused
    step → device state.

    pipelined=False — per-batch latency = submit → flush return (state
    merged and visible on the host), the inbound→device-state span of
    SURVEY.md §3.2-3.3.
    pipelined=True — steady-state throughput: batches dispatch as scanned
    chunks; every chunk dispatch is completion-synchronous inside the
    engine (depth-1), so each batch's e2e latency — submit → its chunk's
    state merge completed — is observed WITHOUT any device->host readback
    (readbacks permanently downshift remote-tunnel transfer streams). The
    timed window ends at a readback-free ``barrier()``; mirror drain is
    teardown/reporting, not ingest.
    """
    rng = np.random.default_rng(seed)
    toks = [f"lg-{i}" for i in range(n_devices)]

    def make_batch(b: int) -> list[bytes]:
        picks = rng.integers(0, n_devices, batch_size)
        return [generate_measurements_message(toks[d], b * batch_size + i)
                for i, d in enumerate(picks)]

    for w in range(warmup_batches):          # compile + interner warm:
        engine.ingest_json_batch(make_batch(w))
        if not pipelined:
            engine.flush()
    if pipelined:
        # warmup compiles the scan-chunk program (incl. the padded-tail
        # shape) without a mirror readback
        engine.barrier()
    else:
        engine.flush()

    # pre-build payloads so the generator itself stays out of the timing
    prebuilt = [make_batch(b) for b in range(n_batches)]
    latencies: list[float] = []
    decoded = failed = 0
    submits: list[float] = []
    t0 = time.perf_counter()
    for i, payloads in enumerate(prebuilt):
        s0 = time.perf_counter()
        res = engine.ingest_json_batch(payloads)
        if pipelined:
            submits.append(s0)
            if engine.staged_count:
                engine.flush_async()
            if engine.staged_count == 0:
                # the chunk holding every pending submit just completed
                # (dispatch blocks until the state merge finished)
                done = time.perf_counter()
                latencies.extend((done - s) * 1e3 for s in submits)
                submits.clear()
        else:
            engine.flush()                    # state merged on return
            latencies.append((time.perf_counter() - s0) * 1e3)
        decoded += res["decoded"]
        failed += res["failed"]
    if pipelined:
        engine.barrier()                      # tail chunk, no readback
        done = time.perf_counter()
        latencies.extend((done - s) * 1e3 for s in submits)
    wall = time.perf_counter() - t0
    p50, p99, mx = _percentiles(latencies)
    sent = n_batches * batch_size
    return LoadStats(sent, decoded, failed, wall, sent / wall, p50, p99, mx)


async def run_rest_load(base_url: str, jwt: str, n_workers: int = 5,
                        msgs_per_worker: int = 100,
                        device_prefix: str = "rest-lg") -> LoadStats:
    """Wire-level driver: N concurrent workers x M posts each (the 5x100
    pattern of EventSourceTests.java:50-53) against /api/devices/{t}/events."""
    import asyncio

    import aiohttp

    latencies: list[float] = []
    failed = 0
    headers = {"Authorization": f"Bearer {jwt}"}

    async def worker(w: int, session: aiohttp.ClientSession):
        nonlocal failed
        token = f"{device_prefix}-{w}"
        for i in range(msgs_per_worker):
            body = json.loads(generate_measurements_message(token, i))
            s0 = time.perf_counter()
            async with session.post(
                f"{base_url}/api/devices/{token}/events",
                json=body, headers=headers,
            ) as r:
                if r.status != 201:
                    failed += 1
                await r.read()
            latencies.append((time.perf_counter() - s0) * 1e3)

    t0 = time.perf_counter()
    async with aiohttp.ClientSession() as session:
        await asyncio.gather(*(worker(w, session) for w in range(n_workers)))
    wall = time.perf_counter() - t0
    sent = n_workers * msgs_per_worker
    p50, p99, mx = _percentiles(latencies)
    return LoadStats(sent, sent - failed, failed, wall, sent / wall, p50, p99, mx)


def main() -> None:
    import argparse

    from sitewhere_tpu.engine import Engine, EngineConfig

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batches", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=4096)
    ap.add_argument("--devices", type=int, default=10_000)
    args = ap.parse_args()

    engine = Engine(EngineConfig(
        device_capacity=max(1 << 15, 1 << (args.devices - 1).bit_length()),
        token_capacity=1 << 17, assignment_capacity=1 << 17,
        store_capacity=1 << 18, batch_capacity=args.batch_size,
    ))
    stats = run_engine_load(engine, args.batches, args.batch_size, args.devices)
    print(json.dumps(stats.to_dict()))


if __name__ == "__main__":
    main()
