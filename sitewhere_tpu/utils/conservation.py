"""Event conservation ledger & continuous audit plane (ISSUE 14).

The platform's core promise is that no tenant event silently vanishes
between ingest, persistence, aggregation, and delivery. PRs 6/9/12
prove the zero-loss/zero-dup guarantees inside individual chaos tests;
this module makes loss continuously *measurable* in a live system:

  * :class:`FlowLedger` — host-side flow counters incremented at the
    two boundaries the engine itself controls (rows staged, valid rows
    dispatched to the device). Every other stage the ledger reports is
    sampled from counters that already exist (QoS admission, the
    device-side tenant counter grid, WAL sequence tickets, the replica
    feed, the forward spill queue, the archive spill cursors, the CEP
    harvest counters), so the ingest hot path pays only a dict add per
    batch plus one vectorized ``np.sum`` per dispatch.
  * :func:`build_ledger` — one mutually-consistent snapshot of every
    stage, taken under the engine lock: per-stage counts, monotone
    watermarks (WAL durable seq, dispatched rows, feed seq, standby
    applied seq, archive spill cursor, rollup window id), and the
    per-stage lag derived from them.
  * :func:`check_conservation` — a PURE function evaluating the
    conservation equations over one ledger snapshot. Slack terms are
    explicit (see ``EQUATIONS``): in-flight staged backlog, the WAL
    group-commit window, ring-wrap losses the archive already counted.
  * :class:`ConservationAuditor` — a background thread running the
    checker on a cadence. A violation must survive two consecutive
    audits before it escalates (a spill-file rename and its counter
    update are not atomic with a concurrent audit); escalated
    violations increment ``swtpu_conservation_violation_total`` and
    emit one loud structured log line.

Import hygiene: this module must import with jax blocked (the offline
bench tooling reads ledger documents); jax is imported lazily inside
the snapshot helpers only.

Conservation equations (the contract future PRs must keep balanced):

  staging-balance       staged_rows == dispatched_rows + backlog_rows
                        (slack: the staged-but-undispatched backlog,
                        measured in the same critical section)
  device-processed      dispatched_rows == device ``processed`` delta
                        (exact at snapshot: reading the device counter
                        forces every dispatched program)
  device-disposition    accepted + invalid == processed (the tenant
                        counter grid partitions every valid row;
                        dedup_dropped / geofence_hit are annotations of
                        accepted rows, not extra dispositions)
  edge-admission        offered == admitted + edge sheds (offered is
                        counted independently at admit() entry; sheds
                        noted after admission — arena stalls — are
                        subtracted), and the per-tenant shed counts sum
                        to the total
  wal-durability        0 <= durable_seq <= appended_seq (the group
                        commit window is the only legal gap)
  forward-queue         spilled == redelivered + deadlettered +
                        rerouted + depth (dead-letter and placement
                        re-route are the ONLY legal sinks; a spilled
                        batch never just disappears. Re-route — ISSUE
                        15 — consumes the original and re-spills its
                        payloads toward the new owner, so the re-spills
                        count as fresh ``spilled`` while the consumed
                        original lands in ``rerouted``: the handoff
                        slack term that keeps the equation balanced
                        across a live migration)
  replication-feed      published == feed_seq and every follower's
                        acked <= feed_seq (slack: un-acked in-flight
                        publications; an un-resynced standby gap shows
                        as acked < seq, never as acked > seq)
  archive-spill         spilled(part) <= ring_head(part), and
                        ring_head - spilled <= arena_capacity +
                        lost_rows (rows wrapped before spooling are
                        only legal when the archive counted them)
  rules-harvest         harvested == emitted + suppressed + skipped,
                        and device missed <= fires, pending >= 0
  placement-handoff     moves_started == moves_completed +
                        moves_aborted + moves_in_flight (ISSUE 15: a
                        handoff always terminates in exactly one of
                        commit/abort; the in-flight term is the only
                        legal slack and is read in the same
                        lock-consistent snapshot)
  spmd-shard-flow       per shard s: accepted[s] + invalid[s] ==
                        processed[s], and routed_rows[s] ==
                        dispatched_rows[s] + backlog_rows[s]; every
                        per-shard lane sums EXACTLY to the folded
                        device-stage lane (ISSUE 18: the unfolded
                        counter grid is the same grid, read before the
                        fold — no new slack term anywhere)
  analytics-windows     windows_planned == windows_scored +
                        windows_skipped_underfilled + windows_cancelled
                        (ISSUE 19: every device window a scoring batch
                        plans lands in exactly one sink; the manager
                        commits planned ALONGSIDE its sinks in one lock
                        block per batch, so there is no in-flight slack
                        term — the equation is exact at every audit)
  wire-frames           frames_received == frames_admitted + frames_shed
                        + frames_invalid + frames_duplicate (ISSUE 20:
                        every frame a persistent connection delivers
                        gets exactly one edge disposition; received is
                        counted independently at frame arrival, so the
                        equation can actually fail)
  wire-rows             frames_admitted == rows_submitted +
                        frames_stalled + pending (admitted frames land
                        in the engine's batch-ingest facade — flowing
                        into staging-balance from there — or are
                        stall-shed with their acks withheld; the
                        arrival-window backlog is the only legal slack)
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time

logger = logging.getLogger(__name__)

EQUATIONS = (
    "staging-balance", "device-processed", "device-disposition",
    "edge-admission", "wal-durability", "forward-queue",
    "replication-feed", "archive-spill", "rules-harvest",
    "placement-handoff", "spmd-shard-flow", "analytics-windows",
    "wire-frames", "wire-rows",
)


class FlowLedger:
    """Host-side flow counters for the boundaries nothing else counts.

    All mutation sites hold the engine lock, so no lock of its own;
    ``enabled`` toggles counting (the bench overhead estimator flips it
    per batch). ``rebase`` records the device counters a restored
    snapshot already carries, so a recovered engine's ledger balances
    over the rows IT staged (WAL replay), not the pre-crash history."""

    __slots__ = ("enabled", "counters", "baseline")

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.counters: dict[str, int] = {"staged_rows": 0,
                                         "dispatched_rows": 0}
        self.baseline: dict[str, int] = {}

    def add(self, key: str, n: int) -> None:
        if self.enabled and n:
            self.counters[key] = self.counters.get(key, 0) + int(n)

    def rebase(self, engine) -> None:
        """Snapshot the engine's device-side counters as the baseline —
        called after a snapshot restore, BEFORE any replay, so the
        ledger's device deltas cover exactly the rows this process
        staged."""
        m = engine.metrics()
        base = {"processed": int(m.get("processed", 0)),
                "persisted": int(m.get("persisted", 0))}
        grid = _grid_totals(engine)
        for lane, n in grid.items():
            base[f"grid_{lane}"] = n
        self.baseline = base


def _grid_totals(eng) -> dict[str, int]:
    """Lane totals of the device-side tenant counter grid (scrape-path
    readback; {} when the engine has no grid)."""
    tpc = getattr(eng, "tenant_pipeline_counters", None)
    if not callable(tpc):
        return {}
    totals: dict[str, int] = {}
    for lanes in tpc().values():
        for lane, n in lanes.items():
            totals[lane] = totals.get(lane, 0) + int(n)
    return totals


def _backlog_rows(eng) -> int:
    """Valid rows staged but not yet dispatched — measured field by
    field (``staged_count`` counts an arena's failed-decode padding
    rows too, which never dispatch as valid). Caller holds the lock."""
    import numpy as np

    n = 0
    buf = getattr(eng, "_buf", None)
    if buf is not None:
        total = getattr(buf, "total", None)
        n += int(total()) if callable(total) else len(buf)
    fq = getattr(eng, "_fair_queued", 0)
    n += int(fq.sum()) if hasattr(fq, "sum") else int(fq)
    fill = getattr(eng, "_arena_fill", None)
    if fill is not None:
        cursors = getattr(fill, "cursors", None)
        if cursors is not None:
            # SPMD stacked arena: [S, rows] lanes with per-shard cursors
            for s, cnt in enumerate(cursors):
                n += int(np.sum(fill.valid[s, :int(cnt)]))
        else:
            n += int(np.sum(fill.valid[:fill.cursor]))
    for b in getattr(eng, "_staged_batches", ()):
        n += int(np.sum(b.valid))
    # SPMD engine (ISSUE 16): per-shard staging buffers
    for b in getattr(eng, "_shard_bufs", ()):
        n += len(b)
    return n


def _rules_stage(eng, rules_manager) -> dict | None:
    """Device CEP counters + the manager's harvest accounting."""
    import jax
    import numpy as np

    rs = getattr(eng.state, "rules", None)
    if rs is None or (rs.rules is None and rs.rollups is None):
        return None
    out: dict = {}
    if rs.rules is not None:
        rb = rs.rules
        f, m, l, o, pw, ph, wid = jax.device_get(
            (rb.fires, rb.missed, rb.late, rb.oob, rb.pend_w, rb.pend_h,
             rb.acc_wid))
        # np.sum casts keep this correct for an SPMD engine's STACKED
        # rules block ([S, ...] leaves): totals sum over every shard
        out.update(fires=int(np.sum(f)), missed=int(np.sum(m)),
                   late=int(np.sum(l)), oob=int(np.sum(o)),
                   pending=int(np.sum(np.minimum(
                       np.asarray(pw) - np.asarray(ph),
                       rb.pend_key.shape[-1]))),
                   max_window_id=int(np.max(wid)))
    if rs.rollups is not None:
        wid = np.asarray(jax.device_get(rs.rollups.wid))
        live = wid[wid > np.iinfo(np.int32).min]
        out["rollup_window_id"] = int(live.max()) if live.size else None
        out["rollup_late"] = int(jax.device_get(rs.rollups.late))
    if rules_manager is not None:
        # one consistent read under the manager lock: poll() commits
        # its four counters in a single _mu block, so the harvest
        # equation is evaluated over pre- or post-poll totals only
        with rules_manager._mu:
            out.update(
                harvested=int(getattr(rules_manager,
                                      "fires_harvested", 0)),
                emitted=int(getattr(rules_manager, "alerts_emitted", 0)),
                suppressed=int(getattr(rules_manager,
                                       "alerts_suppressed", 0)),
                skipped=int(getattr(rules_manager, "harvest_skipped", 0)))
    return out


def build_ledger(engine, rules_manager=None) -> dict:
    """One mutually-consistent flow-accounting snapshot of ``engine``
    (a cluster facade snapshots its LOCAL rank — rank ledgers federate
    through the cluster fan-out, never through one merged snapshot).
    Reads the device counters (forcing in-flight dispatches), so this
    belongs on scrape/audit cadences, never the ingest hot loop."""
    eng = getattr(engine, "local", engine)
    led: FlowLedger | None = getattr(eng, "ledger", None)
    with eng.lock:
        base = dict(led.baseline) if led is not None else {}
        m = eng.metrics()
        grid = _grid_totals(eng)
        stages: dict = {}
        qos = getattr(eng, "qos", None)
        if qos is not None:
            with qos._lock:
                stages["edge"] = {
                    # offered is counted INDEPENDENTLY at admit() entry
                    # (never derived from admitted + shed), so the edge
                    # equation can actually fail on a real ledger
                    "offered": int(qos.offered_events),
                    "admitted": int(qos.admitted_events),
                    "shed": int(qos.shed_events),
                    # sheds noted AFTER admission (arena stall): those
                    # events were offered-and-admitted, the checker
                    # subtracts them from the edge shed total
                    "shed_noted": int(qos.shed_noted),
                    "shed_by_tenant": dict(qos.shed_by_tenant)}
        # persistent-connection wire edge (ISSUE 20): disposition
        # counters sampled from the attached edges' own snapshots. The
        # edge/batcher locks are distinct from the engine lock, so a
        # frame between its admission increment and its batcher append
        # can transiently skew wire-rows — exactly the non-atomic-update
        # race the auditor's two-consecutive-audit rule exists for; a
        # quiescent edge balances exactly.
        if getattr(eng, "wire_edges", None):
            from sitewhere_tpu.ingest.wire_edge import (
                aggregate_wire_snapshot)

            ws = aggregate_wire_snapshot(eng)
            if ws is not None:
                stages["wire"] = {k: ws[k] for k in (
                    "frames_received", "frames_admitted", "frames_shed",
                    "frames_invalid", "frames_duplicate",
                    "rows_submitted", "frames_stalled", "pending",
                    "backpressure_events", "connections_live",
                    "connections_peak")}
        ing = {"staged_rows": 0, "dispatched_rows": 0,
               "backlog_rows": _backlog_rows(eng), "counting": False}
        if led is not None:
            ing.update(staged_rows=led.counters.get("staged_rows", 0),
                       dispatched_rows=led.counters.get(
                           "dispatched_rows", 0),
                       counting=led.enabled)
        stages["ingest"] = ing
        stages["device"] = {
            "processed": int(m.get("processed", 0))
                          - base.get("processed", 0),
            "persisted": int(m.get("persisted", 0))
                          - base.get("persisted", 0),
            **{lane: n - base.get(f"grid_{lane}", 0)
               for lane, n in grid.items()},
        }
        # SPMD shard plane (ISSUE 18): the per-shard breakdown of the
        # device stage. Skipped when a restore baseline is active — the
        # device stage above is baseline-SUBTRACTED while the unfolded
        # grid is cumulative, and splitting the baseline per shard
        # would manufacture slack the equations don't have.
        sf = getattr(eng, "shard_flow", None)
        if callable(sf) and not base:
            stages["spmd"] = sf()
        wal = getattr(eng, "wal", None)
        if wal is not None:
            with wal._lock:
                appended, durable = int(wal._seq), int(wal._durable_seq)
            stages["wal"] = {"appended_seq": appended,
                             "durable_seq": durable,
                             "group_commit": bool(wal.group_commit)}
        fq = getattr(eng, "forward_queue", None)
        if fq is not None:
            fm = fq.metrics()
            stages["forward"] = {
                "spilled_batches": fm["forward_spilled_batches"],
                "redelivered_batches": fm["forward_redelivered_batches"],
                "deadlettered_batches":
                    fm["forward_deadlettered_batches"],
                "rerouted_batches":
                    fm.get("forward_rerouted_batches", 0),
                "queue_depth": fm["forward_queue_depth"],
                "open_circuits": fm["forward_open_circuits"],
            }
        pm = getattr(eng, "placement", None)
        if pm is not None:
            stages["placement"] = pm.ledger_stage()
        feed = getattr(eng, "replica_feed", None)
        applier = getattr(eng, "replica_applier", None)
        if feed is not None or applier is not None:
            rep: dict = {}
            if feed is not None:
                wm = feed.watermarks()
                rep.update(feed_seq=wm["seq"], published=wm["published"],
                           acked=wm["acked"], buffer=wm["buffer"])
            if applier is not None:
                rep["applied_by_leader"] = {
                    str(r): applier.applied(r)
                    for r in applier.leaders()}
            stages["replication"] = rep
        arch = getattr(eng, "archive", None)
        if arch is not None:
            # heads/capacity come from the engine's OWN spooler helpers
            # (engine.ring_heads / ring_arena_capacity) — one definition
            # for the spooler and its checker, no drift
            heads = eng.ring_heads()
            acap = eng.ring_arena_capacity()
            stages["archive"] = {
                "parts": {str(p): {"head": h,
                                   "spilled": arch.spilled(p),
                                   "capacity": acap}
                          for p, h in heads.items()},
                "rows": arch.total_rows(),
                "lost_rows": int(arch.lost_rows),
                "expired_rows": int(arch.expired_rows),
            }
        rules = _rules_stage(eng, rules_manager)
        if rules is not None:
            stages["rules"] = rules
        aj = getattr(eng, "analytics_jobs", None)
        if aj is not None:
            # one consistent read under the manager lock (the scoring
            # pass commits planned + sinks in a single _mu block, so
            # this only ever observes pre- or post-batch totals)
            stages["analytics"] = aj.ledger_stage()

    watermarks: dict = {"dispatched_rows": ing["dispatched_rows"]}
    lag: dict = {"staged_backlog_rows": ing["backlog_rows"]}
    if "wal" in stages:
        w = stages["wal"]
        watermarks["wal_appended"] = w["appended_seq"]
        watermarks["wal_durable"] = w["durable_seq"]
        lag["wal_durable_lag"] = w["appended_seq"] - w["durable_seq"]
    if "replication" in stages:
        r = stages["replication"]
        if "feed_seq" in r:
            watermarks["feed_seq"] = r["feed_seq"]
            acked = r.get("acked", {})
            lag["replication_lag_batches"] = (
                max(r["feed_seq"] - a for a in acked.values())
                if acked else 0)
        if r.get("applied_by_leader"):
            watermarks["standby_applied"] = r["applied_by_leader"]
    if "archive" in stages:
        parts = stages["archive"]["parts"]
        watermarks["archive_spill"] = {p: v["spilled"]
                                       for p, v in parts.items()}
        lag["archive_spill_lag_rows"] = (
            max((v["head"] - v["spilled"] for v in parts.values()),
                default=0))
    if "forward" in stages:
        lag["forward_queue_depth"] = stages["forward"]["queue_depth"]
    if "rules" in stages and "rollup_window_id" in stages["rules"]:
        watermarks["rollup_window_id"] = stages["rules"][
            "rollup_window_id"]
    if "placement" in stages:
        # the placement epoch is a monotone watermark like every other:
        # a rank observed at a LOWER epoch than its peers is lagging
        # the commit broadcast (redirects converge it)
        watermarks["placement_epoch"] = stages["placement"]["epoch"]

    return {
        "generatedMs": int(time.time() * 1000),
        "rank": getattr(engine, "rank", 0),
        "engine": getattr(eng, "metrics_label", "e?"),
        "stages": stages,
        "watermarks": watermarks,
        "lag": lag,
    }


@dataclasses.dataclass
class Violation:
    """One broken conservation equation: ``lhs`` and ``rhs`` are the
    evaluated sides, ``slack`` the tolerance the equation already
    granted when it still failed."""

    equation: str
    message: str
    lhs: float
    rhs: float
    slack: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def check_conservation(ledger: dict) -> list[Violation]:
    """Evaluate every conservation equation over one ledger snapshot.
    Pure: no engine access, no clock — the same ledger always yields
    the same verdict (the falsifiability tests perturb a ledger by one
    and must see a Violation)."""
    out: list[Violation] = []

    def bad(eq: str, msg: str, lhs, rhs, slack: float = 0.0) -> None:
        out.append(Violation(eq, msg, float(lhs), float(rhs),
                             float(slack)))

    st = ledger.get("stages", {})
    ing = st.get("ingest")
    dev = st.get("device", {})
    if ing and ing.get("counting"):
        staged = ing["staged_rows"]
        dispatched = ing["dispatched_rows"]
        backlog = ing["backlog_rows"]
        if staged != dispatched + backlog:
            bad("staging-balance",
                f"staged_rows {staged} != dispatched_rows {dispatched} "
                f"+ backlog {backlog}", staged, dispatched + backlog,
                slack=backlog)
        processed = dev.get("processed")
        if processed is not None and dispatched != processed:
            bad("device-processed",
                f"dispatched_rows {dispatched} != device processed "
                f"{processed}", dispatched, processed)
    if "accepted" in dev and "invalid" in dev and "processed" in dev:
        lhs = dev["accepted"] + dev["invalid"]
        if lhs != dev["processed"]:
            bad("device-disposition",
                f"accepted {dev['accepted']} + invalid {dev['invalid']}"
                f" != processed {dev['processed']}", lhs,
                dev["processed"])
    edge = st.get("edge")
    if edge:
        edge_shed = edge["shed"] - edge.get("shed_noted", 0)
        if edge["offered"] != edge["admitted"] + edge_shed:
            bad("edge-admission",
                f"offered {edge['offered']} != admitted "
                f"{edge['admitted']} + edge shed {edge_shed} "
                f"(shed total {edge['shed']} incl. "
                f"{edge.get('shed_noted', 0)} post-admission)",
                edge["offered"], edge["admitted"] + edge_shed,
                slack=edge.get("shed_noted", 0))
        by_tenant = sum(edge.get("shed_by_tenant", {}).values())
        if by_tenant != edge["shed"]:
            bad("edge-admission",
                f"per-tenant shed sum {by_tenant} != shed total "
                f"{edge['shed']}", by_tenant, edge["shed"])
    wal = st.get("wal")
    if wal and not (0 <= wal["durable_seq"] <= wal["appended_seq"]):
        bad("wal-durability",
            f"durable_seq {wal['durable_seq']} outside "
            f"[0, appended_seq {wal['appended_seq']}]",
            wal["durable_seq"], wal["appended_seq"])
    fwd = st.get("forward")
    if fwd:
        rerouted = fwd.get("rerouted_batches", 0)
        rhs = (fwd["redelivered_batches"] + fwd["deadlettered_batches"]
               + rerouted + fwd["queue_depth"])
        if fwd["spilled_batches"] != rhs:
            bad("forward-queue",
                f"spilled {fwd['spilled_batches']} != redelivered "
                f"{fwd['redelivered_batches']} + deadlettered "
                f"{fwd['deadlettered_batches']} + rerouted {rerouted} "
                f"+ depth {fwd['queue_depth']}",
                fwd["spilled_batches"], rhs,
                slack=fwd["queue_depth"])
    rep = st.get("replication")
    if rep and "feed_seq" in rep:
        if rep["published"] != rep["feed_seq"]:
            bad("replication-feed",
                f"published {rep['published']} != feed_seq "
                f"{rep['feed_seq']}", rep["published"], rep["feed_seq"])
        for f, acked in rep.get("acked", {}).items():
            if acked > rep["feed_seq"]:
                bad("replication-feed",
                    f"follower {f} acked {acked} > feed_seq "
                    f"{rep['feed_seq']}", acked, rep["feed_seq"])
    arch = st.get("archive")
    if arch:
        lost = arch.get("lost_rows", 0)
        for p, v in arch.get("parts", {}).items():
            if v["spilled"] > v["head"]:
                bad("archive-spill",
                    f"part {p} spill cursor {v['spilled']} ahead of "
                    f"ring head {v['head']}", v["spilled"], v["head"])
            elif v["head"] - v["spilled"] > v["capacity"] + lost:
                bad("archive-spill",
                    f"part {p} unspilled backlog "
                    f"{v['head'] - v['spilled']} exceeds capacity "
                    f"{v['capacity']} + counted losses {lost}",
                    v["head"] - v["spilled"], v["capacity"] + lost,
                    slack=v["capacity"] + lost)
    pl = st.get("placement")
    if pl:
        rhs = (pl["moves_completed"] + pl["moves_aborted"]
               + pl["moves_in_flight"])
        if pl["moves_started"] != rhs:
            bad("placement-handoff",
                f"moves_started {pl['moves_started']} != completed "
                f"{pl['moves_completed']} + aborted "
                f"{pl['moves_aborted']} + in_flight "
                f"{pl['moves_in_flight']}", pl["moves_started"], rhs,
                slack=pl["moves_in_flight"])
        if pl.get("fenced_slots", 0) and not pl["moves_in_flight"]:
            bad("placement-handoff",
                f"{pl['fenced_slots']} fenced slot(s) with no move in "
                "flight (a fence must belong to a live handoff)",
                pl["fenced_slots"], 0)
    sp = st.get("spmd")
    if sp:
        per = sp.get("perShard", [])
        for row in per:
            s = row["shard"]
            lhs = row["accepted"] + row["invalid"]
            if lhs != row["processed"]:
                bad("spmd-shard-flow",
                    f"shard {s}: accepted {row['accepted']} + invalid "
                    f"{row['invalid']} != processed {row['processed']}",
                    lhs, row["processed"])
            if sp.get("counting"):
                rhs = row["dispatched_rows"] + row["backlog_rows"]
                if row["routed_rows"] != rhs:
                    bad("spmd-shard-flow",
                        f"shard {s}: routed_rows {row['routed_rows']} "
                        f"!= dispatched_rows {row['dispatched_rows']} "
                        f"+ backlog {row['backlog_rows']}",
                        row["routed_rows"], rhs,
                        slack=row["backlog_rows"])
        # the unfolded grid is the SAME grid the device stage folds:
        # every per-shard lane must sum EXACTLY to the folded total
        for lane in ("processed", "accepted", "invalid",
                     "dedup_dropped", "geofence_hit"):
            if lane not in dev:
                continue
            total = sum(row.get(lane, 0) for row in per)
            if total != dev[lane]:
                bad("spmd-shard-flow",
                    f"per-shard {lane} sum {total} != device {lane} "
                    f"{dev[lane]}", total, dev[lane])
        if sp.get("counting") and ing and ing.get("counting"):
            routed = sum(row["routed_rows"] for row in per)
            if routed != ing["staged_rows"]:
                bad("spmd-shard-flow",
                    f"per-shard routed sum {routed} != staged_rows "
                    f"{ing['staged_rows']}", routed, ing["staged_rows"])
    rules = st.get("rules")
    if rules:
        if "harvested" in rules:
            rhs = (rules.get("emitted", 0) + rules.get("suppressed", 0)
                   + rules.get("skipped", 0))
            if rules["harvested"] != rhs:
                bad("rules-harvest",
                    f"harvested {rules['harvested']} != emitted "
                    f"{rules.get('emitted', 0)} + suppressed "
                    f"{rules.get('suppressed', 0)} + skipped "
                    f"{rules.get('skipped', 0)}", rules["harvested"],
                    rhs)
        if "fires" in rules and rules.get("missed", 0) > rules["fires"]:
            bad("rules-harvest",
                f"missed {rules['missed']} > fires {rules['fires']}",
                rules["missed"], rules["fires"])
        if rules.get("pending", 0) < 0:
            bad("rules-harvest",
                f"negative pending ring depth {rules['pending']}",
                rules["pending"], 0)
    an = st.get("analytics")
    if an and "planned" in an:
        rhs = (an.get("scored", 0) + an.get("skipped_underfilled", 0)
               + an.get("cancelled", 0))
        if an["planned"] != rhs:
            bad("analytics-windows",
                f"windows planned {an['planned']} != scored "
                f"{an.get('scored', 0)} + skipped_underfilled "
                f"{an.get('skipped_underfilled', 0)} + cancelled "
                f"{an.get('cancelled', 0)}", an["planned"], rhs)
    wire = st.get("wire")
    if wire:
        rhs = (wire.get("frames_admitted", 0) + wire.get("frames_shed", 0)
               + wire.get("frames_invalid", 0)
               + wire.get("frames_duplicate", 0))
        if wire.get("frames_received", 0) != rhs:
            bad("wire-frames",
                f"frames received {wire.get('frames_received', 0)} != "
                f"admitted {wire.get('frames_admitted', 0)} + shed "
                f"{wire.get('frames_shed', 0)} + invalid "
                f"{wire.get('frames_invalid', 0)} + duplicate "
                f"{wire.get('frames_duplicate', 0)}",
                wire.get("frames_received", 0), rhs)
        rhs = (wire.get("rows_submitted", 0)
               + wire.get("frames_stalled", 0) + wire.get("pending", 0))
        if wire.get("frames_admitted", 0) != rhs:
            bad("wire-rows",
                f"frames admitted {wire.get('frames_admitted', 0)} != "
                f"rows_submitted {wire.get('rows_submitted', 0)} + "
                f"stalled {wire.get('frames_stalled', 0)} + pending "
                f"{wire.get('pending', 0)}",
                wire.get("frames_admitted", 0), rhs,
                slack=wire.get("pending", 0))
    return out


def conservation_metrics(registry=None) -> dict:
    """The conservation plane's registry instruments. Kept OUT of
    ``engine.metrics()`` (dispatch-shape equality) like every plane
    before it:

      swtpu_conservation_violation_total  confirmed violations, per
                                          equation (auditor-escalated)
      swtpu_conservation_violations       current violation count of
                                          the latest audit (gauge)
      swtpu_conservation_audits_total     audit passes run (gauge,
                                          scrape-synced)
      swtpu_flow_rows                     ledger flow counters, labeled
                                          by stage (staged | dispatched
                                          | backlog), per engine
      swtpu_flow_lag                      per-stage lag derived from
                                          the watermarks at scrape
    """
    from sitewhere_tpu.utils.metrics import REGISTRY

    reg = registry or REGISTRY
    return {
        "violations_total": reg.counter(
            "swtpu_conservation_violation_total",
            "confirmed conservation-equation violations, per equation"),
        "violations": reg.gauge(
            "swtpu_conservation_violations",
            "violations in the most recent conservation audit"),
        "audits": reg.gauge(
            "swtpu_conservation_audits_total",
            "conservation audit passes run"),
        "flow": reg.gauge(
            "swtpu_flow_rows",
            "conservation ledger flow counters, per stage"),
        "lag": reg.gauge(
            "swtpu_flow_lag",
            "per-stage lag derived from the conservation watermarks"),
    }


def export_conservation_metrics(engine, registry=None) -> None:
    """Scrape-time export of the ledger's host-side counters and the
    auditor's posture. Deliberately does NOT build a full ledger (the
    device readbacks stay on the audit cadence); only the cheap host
    counters and the latest audit verdict land on the scrape."""
    eng = getattr(engine, "local", engine)
    led = getattr(eng, "ledger", None)
    if led is None:
        return
    inst = conservation_metrics(registry)
    lbl = getattr(eng, "metrics_label", "e?")
    flow = inst["flow"]
    flow.set(led.counters.get("staged_rows", 0), stage="staged",
             engine=lbl)
    flow.set(led.counters.get("dispatched_rows", 0), stage="dispatched",
             engine=lbl)
    aud = getattr(eng, "conservation_auditor", None)
    if aud is not None:
        inst["violations"].set(len(aud.last_violations), engine=lbl)
        inst["audits"].set(aud.audits, engine=lbl)
        for k, v in (aud.last_ledger or {}).get("lag", {}).items():
            inst["lag"].set(v, stage=k, engine=lbl)


class ConservationAuditor:
    """Background invariant checker: builds a ledger and evaluates the
    conservation equations every ``interval_s`` seconds. A violation
    escalates (counter + loud structured log) only when the SAME
    equation fails two consecutive audits — a spill file's rename and
    its counter update are not atomic with a concurrent audit, so a
    single-read imbalance is a suspect, not a verdict."""

    def __init__(self, engine, rules_manager=None,
                 interval_s: float = 5.0, registry=None):
        self.engine = engine
        self.rules_manager = rules_manager
        self.interval_s = float(interval_s)
        self._registry = registry
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._suspect: set[str] = set()
        self.audits = 0
        self.confirmed_total = 0
        self.last_ledger: dict | None = None
        self.last_violations: list[dict] = []
        # attach so the scrape exporter and REST payload can find us
        getattr(engine, "local", engine).conservation_auditor = self

    def audit(self) -> tuple[dict, list[Violation]]:
        """One audit pass (also the synchronous entry tests/bench use):
        returns (ledger, violations) and applies the two-read
        confirmation rule to the escalation side effects."""
        ledger = build_ledger(self.engine, self.rules_manager)
        violations = check_conservation(ledger)
        self.audits += 1
        self.last_ledger = ledger
        self.last_violations = [v.to_dict() for v in violations]
        now_suspect = {v.equation for v in violations}
        confirmed = [v for v in violations
                     if v.equation in self._suspect]
        self._suspect = now_suspect - {v.equation for v in confirmed}
        if confirmed:
            inst = conservation_metrics(self._registry)
            for v in confirmed:
                self.confirmed_total += 1
                inst["violations_total"].inc(equation=v.equation)
                logger.error(
                    "CONSERVATION VIOLATION %s",
                    json.dumps({"equation": v.equation,
                                "message": v.message, "lhs": v.lhs,
                                "rhs": v.rhs, "slack": v.slack,
                                "rank": ledger.get("rank"),
                                "engine": ledger.get("engine")}))
        return ledger, violations

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.audit()
            except Exception:
                logger.exception("conservation audit pass failed")

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="swtpu-conservation",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def conservation_payload(engine, rules_manager=None) -> dict:
    """THE document behind ``GET /api/instance/conservation`` and the
    ``Instance.conservation`` RPC: a fresh ledger + verdict, plus the
    background auditor's posture when one is attached."""
    ledger = build_ledger(engine, rules_manager)
    violations = check_conservation(ledger)
    out = {"ledger": ledger,
           "violations": [v.to_dict() for v in violations],
           "balanced": not violations}
    aud = getattr(getattr(engine, "local", engine),
                  "conservation_auditor", None)
    if aud is not None:
        out["auditor"] = {"audits": aud.audits,
                          "confirmedViolations": aud.confirmed_total,
                          "intervalS": aud.interval_s,
                          "running": aud.running}
    return out
