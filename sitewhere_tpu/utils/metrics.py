"""Metrics: counters/gauges/histograms with Prometheus text exposition.

The reference creates Prometheus metrics through its framework — per-tenant
labeled counters (InboundEventSource.java:50-59, EventPersistenceMapper.java:
46-47) and histograms (DeviceLookupMapper.java:34-36,
DeviceStatePersistenceMapper.java:55-60) scraped from each microservice.
Here one in-process registry covers the host services, the engine exports
its device-side counters into it, and /api/instance/metrics/prometheus
serves the standard text format.
"""

from __future__ import annotations

import bisect
import itertools
import threading
import time
from typing import Iterator

# process-unique engine labels ("e0", "e1", ...) scoping one engine's
# series on the process-global registry — the SLO harvest (and anything
# else steering per-engine) writes under ``engine=<label>`` so
# in-process multi-engine tests and loopback cluster ranks can never
# read each other's tenants (ISSUE 10 satellite; same convention as the
# autotuner's and the QoS controller's labels)
_ENGINE_LABELS = itertools.count()


def next_engine_label() -> str:
    return f"e{next(_ENGINE_LABELS)}"

_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)

# log-bucketed ladder for end-to-end SLO latency (seconds): a 1-2.5-5
# decade scale from 1ms to 30s, wide enough that open-loop queueing
# delay under overload still lands in a finite bucket
E2E_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0,
)


# Prometheus text-format label escaping: backslash first (escaping the
# escapes), then quote and newline — a label value containing any of the
# three must not corrupt the line structure of the exposition
_LABEL_ESCAPES = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _escape_label(value) -> str:
    s = str(value)
    if "\\" in s or '"' in s or "\n" in s:
        for raw, esc in _LABEL_ESCAPES.items():
            s = s.replace(raw, esc)
    return s


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _LabeledSeries:
    """Shared labeled-value storage behind Counter and Gauge. NOT a metric
    kind itself: Counter and Gauge expose disjoint APIs (a counter only
    increases; a gauge moves freely), so neither inherits the other."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def value(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def expose(self, exemplars: bool = False) -> Iterator[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:       # snapshot: a concurrent write mid-iteration
            items = sorted(self._values.items())
        for key, val in items:
            yield f"{self.name}{_fmt_labels(dict(key))} {val}"


class Counter(_LabeledSeries):
    """Monotonically increasing count. There is deliberately no ``set``:
    a sample that can move backwards is a Gauge, and Prometheus rate()
    over a counter that decreased reads as a counter reset."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease; use a gauge")
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_LabeledSeries):
    """Point-in-time sample: settable, and inc/dec move it either way."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def retain(self, keys: set, **scope) -> None:
        """Drop series not written by the current export — a drained
        queue's age gauge or a dead rank's counters must disappear, not
        freeze at their last sample. ``scope`` label filters limit the
        sweep to one writer's series (e.g. ``engine="e0"``) so exporters
        sharing a gauge never retain-away each other's samples."""
        with self._lock:
            for key in [k for k in self._values if k not in keys]:
                if scope and any(dict(key).get(a) != v
                                 for a, v in scope.items()):
                    continue
                del self._values[key]


class Histogram:
    def __init__(self, name: str, help_text: str,
                 buckets: tuple[float, ...] = _DEFAULT_BUCKETS):
        self.name = name
        self.help = help_text
        self.buckets = buckets
        self._lock = threading.Lock()
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}
        # last exemplar per (series, bucket index): OpenMetrics-style
        # trace links on the bucket lines (bucket len(buckets) = +Inf)
        self._exemplars: dict[tuple, dict[int, tuple[str, float]]] = {}

    def observe(self, value: float, **labels) -> None:
        self.observe_n(value, 1, **labels)

    def observe_n(self, value: float, count: int = 1,
                  exemplar: str | None = None, **labels) -> None:
        """Record ``count`` observations of ``value`` in one update — the
        scrape-time harvest path observes one flight record per BATCH,
        weighted by its payload count, so per-tenant quantiles weight
        events, not batches, without 10^3 bisects per record. ``exemplar``
        (a trace id) sticks to the bucket the value fell in and is served
        on exemplar-aware expositions."""
        if count <= 0:
            return
        key = tuple(sorted(labels.items()))
        with self._lock:
            if key not in self._counts:
                self._counts[key] = [0] * len(self.buckets)
                self._sums[key] = 0.0
                self._totals[key] = 0
            idx = bisect.bisect_left(self.buckets, value)
            if idx < len(self.buckets):
                self._counts[key][idx] += count
            self._sums[key] += value * count
            self._totals[key] += count
            if exemplar is not None:
                self._exemplars.setdefault(key, {})[idx] = (exemplar, value)

    def time(self, **labels):
        """Context manager measuring a stage duration — the per-stage latency
        histograms of the reference's pipeline mappers."""
        hist = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.t0, **labels)

        return _Timer()

    def count(self, **labels) -> int:
        """Total observations for one series — lets tests and controllers
        assert on event COUNTS (e.g. "fewer WAL fsyncs than batches")
        without parsing the exposition text."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._totals.get(key, 0)

    def _matching_keys(self, labels: dict) -> list[tuple]:
        want = {k: str(v) for k, v in labels.items()}
        return [key for key in self._totals
                if all(k in dict(key) and str(dict(key)[k]) == v
                       for k, v in want.items())]

    def count_where(self, **labels) -> int:
        """Total observations summed over every series whose label set
        CONTAINS ``labels`` — the aggregate view for series that carry
        scoping labels (the SLO histogram's ``engine=e<n>``): a test
        asserting "every ingested event observed once" sums across
        engines with ``count_where(tenant=...)``."""
        with self._lock:
            return sum(self._totals[k] for k in self._matching_keys(labels))

    def quantile_where(self, q: float, **labels) -> float | None:
        """:meth:`quantile` over the MERGED bucket counts of every series
        matching the ``labels`` subset — one per-tenant quantile across
        in-process ranks whose observations landed under different
        ``engine`` labels."""
        with self._lock:
            keys = self._matching_keys(labels)
            if not keys:
                return None
            counts = [0] * len(self.buckets)
            total = 0
            for k in keys:
                for i, c in enumerate(self._counts[k]):
                    counts[i] += c
                total += self._totals[k]
        return self._quantile_from(q, counts, total)

    def _quantile_from(self, q: float, counts, total) -> float | None:
        """The histogram_quantile interpolation rule over one (possibly
        merged) bucket-count vector — shared by :meth:`quantile` and
        :meth:`quantile_where` so the two readings can never diverge."""
        if not counts or not total:
            return None
        target = q * total
        acc = 0
        for i, c in enumerate(counts):
            if c and acc + c >= target:
                lo = self.buckets[i - 1] if i else 0.0
                hi = self.buckets[i]
                frac = min(1.0, max(0.0, (target - acc) / c))
                return lo + (hi - lo) * frac
            acc += c
        return self.buckets[-1]

    def quantile(self, q: float, **labels) -> float | None:
        """Bucket-quantile estimate: locate the bounding bucket, then
        linearly interpolate within it — the standard
        ``histogram_quantile`` rule, so SLO summaries and the autotuner
        can read a p99 straight from the exposition buckets without any
        raw-sample retention. Values beyond the last finite bucket clamp
        to that bound (the +Inf bucket has no width to interpolate
        into); None until a series observes."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = list(self._counts.get(key) or ())
            total = self._totals.get(key, 0)
        return self._quantile_from(q, counts, total)

    def expose(self, exemplars: bool = False) -> Iterator[str]:
        """Prometheus text exposition. ``exemplars`` appends OpenMetrics
        trace-id exemplars to the bucket lines — only the federated
        cluster scrape asks for them; the plain text-format endpoint
        stays strictly 0.0.4-parseable."""
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:       # snapshot: observe() mutates these in place
            keys = sorted(self._counts)
            counts = {k: list(self._counts[k]) for k in keys}
            sums = dict(self._sums)
            totals = dict(self._totals)
            exm = ({k: dict(v) for k, v in self._exemplars.items()}
                   if exemplars else {})

        def _ex(key, idx) -> str:
            ex = exm.get(key, {}).get(idx)
            if ex is None:
                return ""
            tid, val = ex
            return f' # {{trace_id="{_escape_label(tid)}"}} {val:.9g}'

        for key in keys:
            labels = dict(key)
            acc = 0
            for i, (bound, c) in enumerate(zip(self.buckets, counts[key])):
                acc += c
                le = dict(labels, le=repr(bound))
                yield (f"{self.name}_bucket{_fmt_labels(le)} {acc}"
                       f"{_ex(key, i)}")
            inf = dict(labels, le="+Inf")
            yield (f"{self.name}_bucket{_fmt_labels(inf)} {totals[key]}"
                   f"{_ex(key, len(self.buckets))}")
            yield f"{self.name}_sum{_fmt_labels(labels)} {sums[key]}"
            yield f"{self.name}_count{_fmt_labels(labels)} {totals[key]}"


class MetricsRegistry:
    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_text), Counter)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_text), Gauge)

    def histogram(self, name: str, help_text: str = "",
                  buckets: tuple[float, ...] = _DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_text, buckets), Histogram)

    def _get(self, name, build, kind):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = build()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(f"metric {name!r} already registered as {type(m).__name__}")
            return m

    def expose_text(self, exemplars: bool = False) -> str:
        with self._lock:       # snapshot the registry: a concurrent
            metrics = list(self._metrics.values())   # register() mid-scrape
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.expose(exemplars=exemplars))
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()


# batch-size buckets for the shared-scan query coalescer (counts, not
# seconds — the default latency buckets would squash every batch into the
# first bucket)
QUERY_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


def query_metrics(registry: MetricsRegistry | None = None) -> dict:
    """The ``swtpu_query_*`` instruments for the batched read path — one
    definition so the engine's QueryBatcher, bench.py, and tests always
    agree on names and bucket layouts:

      swtpu_query_latency_seconds   end-to-end query_events latency
                                    (lookup + coalesce wait + device +
                                    formatting + archive merge)
      swtpu_query_batch_size        predicates fused per device program
      swtpu_queries_total           query_events calls served
      swtpu_query_programs_total    device programs launched (the
                                    amortization ratio vs queries_total)
    """
    reg = registry or REGISTRY
    return {
        "latency": reg.histogram(
            "swtpu_query_latency_seconds",
            "end-to-end engine query latency in seconds"),
        "batch": reg.histogram(
            "swtpu_query_batch_size",
            "event queries coalesced into one device program",
            buckets=QUERY_BATCH_BUCKETS),
        "queries": reg.counter(
            "swtpu_queries_total", "event queries served"),
        "programs": reg.counter(
            "swtpu_query_programs_total",
            "batched query device programs launched"),
    }


def _safe_size(path) -> int:
    try:
        return path.stat().st_size
    except OSError:
        return 0


def archive_metrics(registry: MetricsRegistry | None = None) -> dict:
    """The ``swtpu_archive_*`` gauges for the historical retention tier
    (ISSUE 8). Registered here — NOT in engine.metrics(), whose dict is
    pinned equal across dispatch shapes — exactly like the query and
    replication instruments. All gauges, synced at scrape time from the
    archive's own counters (the archive mutates under the engine lock;
    the scrape must never take it):

      swtpu_archive_segments            live segment files on disk
      swtpu_archive_rows                rows held by the archive tier
      swtpu_archive_bytes               bytes in live segment files
      swtpu_archive_queries_total       pushdown scans served
      swtpu_archive_segments_considered_total
                                        segments admitted by the eviction
                                        cap (what a full scan would open)
      swtpu_archive_segments_pruned_total
                                        ...of which zone maps/blooms
                                        pruned without decoding
      swtpu_archive_segments_decoded_total
                                        unique segments actually decoded
                                        (pruned + decoded + shortcut ==
                                        considered per round)
      swtpu_archive_count_shortcut_total
                                        provably-full-match segments
                                        counted from stats alone
      swtpu_archive_cache_hits_total / swtpu_archive_cache_loads_total
                                        LRU segment-decode cache traffic
      swtpu_archive_corrupt_segments    files quarantined (rebuild+decode)
      swtpu_archive_lost_rows / swtpu_archive_expired_rows
                                        rows wrapped before spool / rows
                                        expired by retention policy
    """
    reg = registry or REGISTRY
    return {
        "segments": reg.gauge(
            "swtpu_archive_segments", "live archived segment files"),
        "rows": reg.gauge(
            "swtpu_archive_rows", "rows held by the archive tier"),
        "bytes": reg.gauge(
            "swtpu_archive_bytes", "bytes on disk in live segments"),
        "queries": reg.gauge(
            "swtpu_archive_queries_total", "archive pushdown scans served"),
        "considered": reg.gauge(
            "swtpu_archive_segments_considered_total",
            "segments admitted by the eviction cap across all scans"),
        "pruned": reg.gauge(
            "swtpu_archive_segments_pruned_total",
            "segments pruned by zone maps/bloom filters without decoding"),
        "decoded": reg.gauge(
            "swtpu_archive_segments_decoded_total",
            "unique segments decoded per scan, summed"),
        "count_shortcuts": reg.gauge(
            "swtpu_archive_count_shortcut_total",
            "provably-full-match segments counted from stats alone"),
        "planner_calls": reg.gauge(
            "swtpu_archive_planner_calls_total",
            "segment-planner planning passes served (a batcher round's "
            "archive requests share exactly one)"),
        "cache_hits": reg.gauge(
            "swtpu_archive_cache_hits_total",
            "segment-decode cache calls served without touching disk"),
        "cache_loads": reg.gauge(
            "swtpu_archive_cache_loads_total",
            "segment-decode cache np.load file opens"),
        "corrupt": reg.gauge(
            "swtpu_archive_corrupt_segments",
            "segment files quarantined as corrupt (at index rebuild or "
            "first decode)"),
        "lost_rows": reg.gauge(
            "swtpu_archive_lost_rows",
            "ring rows overwritten before they could spill"),
        "expired_rows": reg.gauge(
            "swtpu_archive_expired_rows",
            "archived rows expired by retention policy"),
    }


def analytics_metrics(registry: MetricsRegistry | None = None) -> dict:
    """The ``swtpu_analytics_*`` gauges for the fleet-scale historical
    scoring tier (ISSUE 19). Registered here — NOT in engine.metrics()
    (dispatch-shape equality) — like every plane before it; all synced
    at scrape time from the AnalyticsManager's own counters (committed
    under the manager lock, read without the engine lock):

      swtpu_analytics_jobs_total          jobs, labeled by terminal state
                                          (started|completed|cancelled|
                                          failed)
      swtpu_analytics_rounds_total        planner-batched streaming
                                          rounds executed
      swtpu_analytics_segments_streamed_total
                                          archive segments decoded into
                                          scoring rounds
      swtpu_analytics_bytes_streamed_total
                                          archive->device planner-cost
                                          bytes streamed (decode cost of
                                          compressed columns included)
      swtpu_analytics_rows_streamed_total measurement rows surviving the
                                          host predicate filter
      swtpu_analytics_windows_total       device windows, labeled by
                                          conservation sink (planned|
                                          scored|skipped_underfilled|
                                          cancelled)
      swtpu_analytics_alerts_total        score alerts, labeled
                                          emitted|suppressed
      swtpu_analytics_rollup_spilled_windows_total
                                          rollup ring windows aged out to
                                          the rollup archive (the PR-12
                                          leftover this tier pays for)
    """
    reg = registry or REGISTRY
    return {
        "jobs": reg.gauge(
            "swtpu_analytics_jobs_total",
            "historical scoring jobs, labeled by state"),
        "rounds": reg.gauge(
            "swtpu_analytics_rounds_total",
            "planner-batched archive streaming rounds executed"),
        "segments": reg.gauge(
            "swtpu_analytics_segments_streamed_total",
            "archive segments decoded into scoring rounds"),
        "bytes": reg.gauge(
            "swtpu_analytics_bytes_streamed_total",
            "archive->device planner-cost bytes streamed"),
        "rows": reg.gauge(
            "swtpu_analytics_rows_streamed_total",
            "measurement rows surviving the host predicate filter"),
        "windows": reg.gauge(
            "swtpu_analytics_windows_total",
            "device windows, labeled by conservation sink"),
        "alerts": reg.gauge(
            "swtpu_analytics_alerts_total",
            "historical score alerts, labeled emitted|suppressed"),
        "rollup_spilled": reg.gauge(
            "swtpu_analytics_rollup_spilled_windows_total",
            "rollup ring windows aged out to the rollup archive"),
    }


def replication_metrics(registry: MetricsRegistry | None = None) -> dict:
    """The ``swtpu_replication_*`` instruments for the event-plane
    replica feed (ISSUE 6). Registered here — NOT in engine.metrics(),
    whose dict is pinned equal across dispatch shapes — exactly like the
    query instruments:

      swtpu_replication_published_total   WAL appends published to the feed
      swtpu_replication_applied_total     feed batches applied into standbys
      swtpu_replication_failover_reads_total  reads served from a standby
      swtpu_replication_fireovers_total   schedule fire-over takeovers
      swtpu_replication_lag_batches       publish-to-apply lag (gauge)
      swtpu_replication_stale_ms          standby staleness watermark,
                                          labeled per LEADER rank (one
                                          series per peer this rank
                                          stands by for — a single
                                          lagging follower must be
                                          visible, not averaged away)
    """
    reg = registry or REGISTRY
    return {
        "published": reg.counter(
            "swtpu_replication_published_total",
            "ingest batches published to the replica feed"),
        "applied": reg.counter(
            "swtpu_replication_applied_total",
            "replica feed batches applied into standby stores"),
        "failover_reads": reg.counter(
            "swtpu_replication_failover_reads_total",
            "reads served from a follower standby during owner outage"),
        "fireovers": reg.counter(
            "swtpu_replication_fireovers_total",
            "schedule fire-over takeovers for dead owners"),
        "lag": reg.gauge(
            "swtpu_replication_lag_batches",
            "replica feed publish-to-ack lag in batches"),
        "stale": reg.gauge(
            "swtpu_replication_stale_ms",
            "standby staleness watermark in milliseconds, per leader "
            "rank this rank follows"),
    }


def placement_metrics(registry: MetricsRegistry | None = None) -> dict:
    """Elastic-placement instruments (ISSUE 15). Kept OUT of
    engine.metrics() (dispatch-shape equality) like every plane before
    it; the federated scrape re-labels them per rank:

      swtpu_placement_epoch            the rank's installed map epoch
                                       (a lagging rank is visible as a
                                       lower epoch than its peers)
      swtpu_placement_moves_total      handoffs by terminal state,
                                       labeled started|completed|aborted
      swtpu_placement_redirects_total  fenced-write + stale-sender 473
                                       redirects served by this rank's
                                       owner-side guard, labeled by kind
      swtpu_placement_fenced_slots     slots currently fenced here
                                       (nonzero only mid-handoff)
    """
    reg = registry or REGISTRY
    return {
        "epoch": reg.gauge(
            "swtpu_placement_epoch",
            "installed placement map epoch on this rank"),
        "moves": reg.counter(
            "swtpu_placement_moves_total",
            "placement handoffs by state (started/completed/aborted)"),
        "redirects": reg.counter(
            "swtpu_placement_redirects_total",
            "fenced-write and stale-sender ownership redirects served"),
        "fenced": reg.gauge(
            "swtpu_placement_fenced_slots",
            "slots currently fenced on this rank (mid-handoff only)"),
    }


def export_placement_metrics(engine, registry: MetricsRegistry | None
                             = None) -> None:
    """Scrape-time export of the placement posture gauges (the move /
    redirect counters increment live on their paths)."""
    pm = getattr(engine, "placement", None)
    if pm is None:
        return
    inst = placement_metrics(registry)
    inst["epoch"].set(pm.epoch)
    inst["fenced"].set(len(pm.fenced_slots()))


def spmd_metrics(registry: MetricsRegistry | None = None) -> dict:
    """Multi-chip SPMD store instruments (ISSUE 16). Kept OUT of
    engine.metrics() (dispatch-shape equality — and the SPMD engine's
    metrics() dict is pinned equal to single-chip) like every plane
    before it. All scrape-time gauges synced from the router's host
    mirrors; every series carries the exporting engine's ``engine=e<n>``
    label, the per-lane series a ``shard`` label on top:

      swtpu_spmd_shards          shards in the engine's device mesh
                                 (the fixed slot-space partition count)
      swtpu_shard_staged_rows    staged ingest rows per shard lane —
                                 router skew shows up as one lane
                                 filling (and forcing flushes) while
                                 its siblings idle — and
      swtpu_shard_staged_rows_hwm  its per-lane high-water mark, RESET
                                 on scrape (PR-11 arena-HWM
                                 discipline): a transient one-lane
                                 pileup that drained before the scrape
                                 is visible after the fact (ISSUE 18
                                 blind-spot fix)
      swtpu_shard_devices        devices registered per shard (local
                                 device-id high-water mark)
      swtpu_shard_assignments    assignments created per shard

    Shard heat & skew plane (ISSUE 18), synced at scrape from the
    unfolded device counter grid (one ``device_get`` of data the fused
    step already materialized — no new program, no extra dispatch):

      swtpu_shard_flow_rows      per-shard flow breakdown, labeled
                                 shard + lane (processed | accepted |
                                 invalid | dedup_dropped | geofence_hit
                                 | routed_rows | dispatched_rows |
                                 backlog_rows)
      swtpu_shard_heat           decayed-EWMA events/s per
                                 (shard, tenant bucket); quiet cells
                                 retained away
      swtpu_slot_heat_topk       the K hottest placement slots' EWMA
                                 events/s, labeled by slot id — the
                                 signal placement.propose_moves reads
      swtpu_spmd_skew            last dispatch's max/mean routed-rows
                                 imbalance (1.0 = perfectly balanced;
                                 the mesh runs at ~1/k throughput at k)
      swtpu_spmd_skew_hwm        worst skew since the last scrape
                                 (reset on scrape)
      swtpu_spmd_skew_sustained_total  sustained-skew escalations (two
                                 consecutive scrape-audits over the
                                 threshold, PR-13 confirmation rule)
    """
    reg = registry or REGISTRY
    return {
        "shards": reg.gauge(
            "swtpu_spmd_shards",
            "shards in the engine's SPMD device mesh"),
        "staged": reg.gauge(
            "swtpu_shard_staged_rows",
            "staged ingest rows per shard lane (pre-dispatch)"),
        "staged_hwm": reg.gauge(
            "swtpu_shard_staged_rows_hwm",
            "per-shard staged-rows high-water mark since last scrape "
            "(reset on scrape)"),
        "devices": reg.gauge(
            "swtpu_shard_devices",
            "devices registered per shard (local id high-water mark)"),
        "assignments": reg.gauge(
            "swtpu_shard_assignments",
            "assignments created per shard (local id high-water mark)"),
        "flow": reg.gauge(
            "swtpu_shard_flow_rows",
            "per-shard flow breakdown from the unfolded device counter "
            "grid + host route table, per shard + lane"),
        "heat": reg.gauge(
            "swtpu_shard_heat",
            "decayed-EWMA events/s per (shard, tenant)"),
        "slot_heat": reg.gauge(
            "swtpu_slot_heat_topk",
            "EWMA events/s of the hottest placement slots"),
        "skew": reg.gauge(
            "swtpu_spmd_skew",
            "per-dispatch max/mean routed-rows imbalance index"),
        "skew_hwm": reg.gauge(
            "swtpu_spmd_skew_hwm",
            "worst dispatch skew since last scrape (reset on scrape)"),
        "skew_sustained": reg.counter(
            "swtpu_spmd_skew_sustained_total",
            "sustained-skew escalations (two-consecutive-audit "
            "confirmation)"),
    }


def export_spmd_metrics(engine, registry: MetricsRegistry | None
                        = None) -> None:
    """Scrape-time export of the SPMD router's per-shard posture. Duck
    typing, like every other plane: anything carrying per-shard staging
    lanes (the mesh-sharded SpmdEngine) exports; single-chip engines
    export nothing."""
    bufs = getattr(engine, "_shard_bufs", None)
    if bufs is None:
        return
    inst = spmd_metrics(registry)
    lbl = getattr(engine, "metrics_label", "e?")
    inst["shards"].set(len(bufs), engine=lbl)
    devices = getattr(engine, "_next_local_device", None)
    assigns = getattr(engine, "_next_local_assignment", None)
    take_hwm = getattr(engine, "take_shard_staged_hwm", None)
    hwms = take_hwm() if callable(take_hwm) else None
    for s, buf in enumerate(bufs):
        inst["staged"].set(len(buf), engine=lbl, shard=str(s))
        if hwms is not None:
            inst["staged_hwm"].set(hwms[s], engine=lbl, shard=str(s))
        if devices is not None:
            inst["devices"].set(devices[s], engine=lbl, shard=str(s))
        if assigns is not None:
            inst["assignments"].set(assigns[s], engine=lbl, shard=str(s))
    # shard heat & skew plane (ISSUE 18): the scrape IS the harvest
    # seam AND the skew-audit cadence (mirrors the conservation
    # auditor's scrape-synced posture)
    sf = getattr(engine, "shard_flow", None)
    if callable(sf):
        for row in sf()["perShard"]:
            s = str(row["shard"])
            for lane, n in row.items():
                if lane != "shard":
                    inst["flow"].set(n, engine=lbl, shard=s, lane=lane)
    harvest = getattr(engine, "harvest_shard_heat", None)
    if callable(harvest):
        from sitewhere_tpu.utils.shardobs import heat_map_doc

        tracker = harvest()
        written = set()
        for s, cells in heat_map_doc(tracker, engine.tenants).items():
            for tenant, eps in cells.items():
                labels = {"engine": lbl, "shard": s, "tenant": tenant}
                inst["heat"].set(eps, **labels)
                written.add(tuple(sorted(labels.items())))
        inst["heat"].retain(written, engine=lbl)
        written = set()
        for slot, eps in tracker.top_slots():
            labels = {"engine": lbl, "slot": str(slot)}
            inst["slot_heat"].set(eps, **labels)
            written.add(tuple(sorted(labels.items())))
        inst["slot_heat"].retain(written, engine=lbl)
        inst["skew"].set(tracker.skew_index, engine=lbl)
        inst["skew_hwm"].set(tracker.take_skew_hwm(), engine=lbl)
        if tracker.audit_skew():
            inst["skew_sustained"].inc(engine=lbl)


def slo_metrics(registry: MetricsRegistry | None = None) -> dict:
    """The SLO latency plane (ISSUE 7): per-tenant end-to-end ingest
    latency harvested from flight-recorder lifecycle records at SCRAPE
    time — the ingest hot path never pays an extra device sync for it.
    Kept OUT of engine.metrics() (dispatch-shape equality) like the
    query and replication instruments.

      swtpu_ingest_e2e_seconds   wire->state latency per tenant
                                 (log-bucketed; slowest-decile
                                 observations carry trace-id exemplars
                                 resolving via /api/instance/trace/<id>)
    """
    reg = registry or REGISTRY
    return {
        "ingest_e2e": reg.histogram(
            "swtpu_ingest_e2e_seconds",
            "per-tenant ingest wire->state latency harvested from "
            "flight records at scrape time",
            buckets=E2E_LATENCY_BUCKETS),
    }


def qos_metrics(registry: MetricsRegistry | None = None) -> dict:
    """Overload-discipline instruments (ISSUE 9). Kept OUT of
    engine.metrics() (dispatch-shape equality) like the query /
    replication / archive instruments. Every series carries an
    ``engine`` label (the controller's autotuner-style ``e<n>`` tag) —
    the REGISTRY is process-global, so in-process cluster ranks and
    multi-engine tests would otherwise merge counters and
    last-writer-win each other's gauges.

      swtpu_qos_admitted_total   events admitted, per tenant (live)
      swtpu_qos_shed_total       events shed, per tenant + reason
                                 ("rate" | "saturated" | "stall"; live)
      swtpu_qos_bucket_fill      token-bucket balance per tenant (scrape)
      swtpu_qos_saturated        1 while backlog >= shed threshold
      swtpu_qos_shed_threshold   current saturation threshold (rows)
      swtpu_qos_wfq_vtime        weighted-fair virtual time per tenant,
                                 labeled by resource (ingest | query)
    """
    reg = registry or REGISTRY
    return {
        "admitted": reg.counter(
            "swtpu_qos_admitted_total",
            "events admitted by per-tenant admission control"),
        "shed": reg.counter(
            "swtpu_qos_shed_total",
            "events shed by admission control, per tenant and reason"),
        "fill": reg.gauge(
            "swtpu_qos_bucket_fill",
            "admission token-bucket balance per tenant"),
        "saturated": reg.gauge(
            "swtpu_qos_saturated",
            "1 while the engine backlog exceeds the shed threshold"),
        "threshold": reg.gauge(
            "swtpu_qos_shed_threshold",
            "staged-row backlog beyond which ingest sheds"),
        "wfq_vtime": reg.gauge(
            "swtpu_qos_wfq_vtime",
            "weighted-fair virtual time per tenant and resource"),
    }


def rules_metrics(registry: MetricsRegistry | None = None) -> dict:
    """Streaming-rules CEP tier instruments (ISSUE 13). Kept OUT of
    engine.metrics() (dispatch-shape equality) like the query / qos /
    replication instruments; the partition-invariant ``rule_fires``
    counter IS in metrics() — these cover the host-side lifecycle.

      swtpu_rules_swaps_total           rule-set installs/hot-reloads
      swtpu_rules_reload_errors_total   rejected rule-set documents
                                        (the active set kept serving)
      swtpu_rules_alerts_total          alert events emitted through
                                        the ingest pipeline
      swtpu_rules_suppressed_total      fires suppressed by the
                                        rule+group+window dedup key
                                        (replay / standby promotion)
    """
    reg = registry or REGISTRY
    return {
        "swaps": reg.counter(
            "swtpu_rules_swaps_total",
            "rule-set installs and hot-reload swaps"),
        "reload_errors": reg.counter(
            "swtpu_rules_reload_errors_total",
            "rule-set documents rejected at validate/compile time"),
        "alerts": reg.counter(
            "swtpu_rules_alerts_total",
            "rule alert events emitted through the ingest pipeline"),
        "suppressed": reg.counter(
            "swtpu_rules_suppressed_total",
            "rule fires suppressed by the dedup key (replay/standby)"),
    }


# compile-wall-time buckets (seconds): XLA compiles run 10ms (tiny admin
# updaters) to tens of seconds (the fused scan step on a loaded host) —
# the default latency ladder would squash every compile into +Inf
COMPILE_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def devicewatch_metrics(registry: MetricsRegistry | None = None) -> dict:
    """Device-plane telemetry instruments (ISSUE 11). Kept OUT of
    ``engine.metrics()`` (dispatch-shape equality) like every plane
    before it. The ``swtpu_xla_*`` series are PROCESS-scoped (one XLA
    compile cache per process — in-process cluster ranks share it); the
    ``swtpu_device_mem_*`` gauges carry the exporting engine's
    ``engine=e<n>`` label because each engine owns its own stores.

      swtpu_xla_compile_seconds      wall time of compiling dispatches,
                                     per program family (for jit-watched
                                     families this is the first dispatch
                                     of a new shape key — trace+compile+
                                     first run, the latency cliff a
                                     retrace actually costs; the AOT
                                     query path times lower()+compile()
                                     exactly)
      swtpu_xla_compiles_total       distinct programs compiled
      swtpu_xla_cache_hits_total     watched dispatches served by an
                                     already-compiled program
      swtpu_xla_retrace_excess_total shape-churn compiles beyond a
                                     scope's declared budget (the
                                     watchdog's loud counter)
      swtpu_xla_programs_live        distinct program references held by
                                     live watch scopes, per family
      swtpu_xla_program_flops /      cost_analysis() of the most recent
      swtpu_xla_program_bytes_accessed   compile, per family
      swtpu_device_exec_seconds      device execution time per family,
                                     harvested from flight records at
                                     scrape time (no hot-path syncs)
      swtpu_device_mem_bytes         memory-ledger component sizes
      swtpu_device_mem_hwm           high-watermarks (reset on scrape)
    """
    reg = registry or REGISTRY
    return {
        "compile": reg.histogram(
            "swtpu_xla_compile_seconds",
            "XLA compile wall time per program family (jit-watched "
            "families time the compiling dispatch)",
            buckets=COMPILE_BUCKETS),
        "compiles": reg.counter(
            "swtpu_xla_compiles_total",
            "distinct XLA programs compiled, per family"),
        "hits": reg.counter(
            "swtpu_xla_cache_hits_total",
            "watched dispatches served by an already-compiled program"),
        "excess": reg.counter(
            "swtpu_xla_retrace_excess_total",
            "compiles beyond a watch scope's declared shape budget "
            "(shape churn)"),
        "live": reg.gauge(
            "swtpu_xla_programs_live",
            "distinct program references held by live watch scopes"),
        "flops": reg.gauge(
            "swtpu_xla_program_flops",
            "cost_analysis flops of the family's most recent compile"),
        "bytes": reg.gauge(
            "swtpu_xla_program_bytes_accessed",
            "cost_analysis bytes accessed of the family's most recent "
            "compile"),
        "exec": reg.histogram(
            "swtpu_device_exec_seconds",
            "device execution time per program family, harvested from "
            "flight records at scrape time"),
        "mem": reg.gauge(
            "swtpu_device_mem_bytes",
            "memory-ledger component bytes (ring store, arenas, segment "
            "cache, live arrays), per engine"),
        "mem_hwm": reg.gauge(
            "swtpu_device_mem_hwm",
            "memory-ledger high-watermarks since the last scrape "
            "(reset on scrape), per engine"),
    }


def cluster_metrics_instruments(registry: MetricsRegistry | None
                                = None) -> dict:
    """Cluster data-plane instruments (ISSUE 7):

      swtpu_forward_hop_seconds    sender-observed cross-rank forward
                                   RPC latency, labeled by destination
                                   rank (the forwarded-hop p99 the bench
                                   cluster leg reports)
      swtpu_cluster_scrapes_total  federated metric scrapes served
    """
    reg = registry or REGISTRY
    return {
        "forward_hop": reg.histogram(
            "swtpu_forward_hop_seconds",
            "cross-rank ingest forward RPC latency (sender-observed)",
            buckets=E2E_LATENCY_BUCKETS),
        "scrapes": reg.counter(
            "swtpu_cluster_scrapes_total",
            "federated cluster metric scrapes served"),
    }


def export_engine_metrics(engine, registry: MetricsRegistry | None = None,
                          tenant: str = "all") -> None:
    """Push the engine's device-side counters into the registry (scrape-time
    sync; the device counters are the source of truth). Per-tenant event
    counts export labeled, mirroring the reference's buildLabels() tenant
    labeling on every metric."""
    reg = registry or REGISTRY
    metrics = engine.metrics()
    by_rank = metrics.pop("by_rank", None)

    def _numeric(items):
        return ((n, v) for n, v in items
                if isinstance(v, (int, float)) and not isinstance(v, bool))

    written: dict[str, set] = {}

    def _set(name: str, value, **labels) -> None:
        g = reg.gauge(f"swtpu_engine_{name}", f"engine counter {name}")
        g.set(value, **labels)
        written.setdefault(g.name, set()).add(
            tuple(sorted(labels.items())))

    for name, value in _numeric(metrics.items()):
        labels = {"tenant": tenant}
        if by_rank is not None:
            labels["rank"] = "all"   # cluster-merged series
        _set(name, value, **labels)
    if by_rank is not None:
        # per-rank series: the "which rank is hot" view the reference
        # gets from scraping each microservice replica separately
        for rank, rank_metrics in by_rank.items():
            for name, value in _numeric(rank_metrics.items()):
                _set(name, value, tenant=tenant, rank=str(rank))
    # conditional keys (a drained queue's age) and dead ranks must
    # DISAPPEAR from the exposition, not freeze at their last sample
    for mname, metric in list(reg._metrics.items()):
        if mname.startswith("swtpu_engine_") and isinstance(metric, Gauge):
            metric.retain(written.get(mname, set()))
    g = reg.gauge("swtpu_tenant_events",
                  "persisted event count per tenant and type")
    current: set[tuple] = set()
    for ten, counts in engine.tenant_metrics().items():
        for etype, n in counts.items():
            if n:
                g.set(n, tenant=ten, type=etype)
                current.add(tuple(sorted({"tenant": ten,
                                          "type": etype}.items())))
    # a tenant that went quiet (devices deactivated) must scrape as 0, not
    # freeze at its last nonzero sample
    with g._lock:
        stale = [k for k in g._values if k not in current]
    for key in stale:
        g.set(0, **dict(key))
    export_observability_metrics(engine, reg)
    export_placement_metrics(engine, reg)
    export_spmd_metrics(engine, reg)
    export_wire_metrics(engine, reg)


def export_wire_metrics(engine, registry: MetricsRegistry | None = None) -> None:
    """Scrape-time export of the persistent-connection wire edge (ISSUE
    20): connection gauges, per-disposition frame totals, arrival-window
    flush occupancy, and backpressure events. Sampled from the attached
    edges' own counter snapshots — like every plane, these series are
    deliberately NOT ``engine.metrics()`` keys (dispatch-shape equality
    pin); an engine with no edge attached exports nothing."""
    eng = getattr(engine, "local", engine)
    if not getattr(eng, "wire_edges", None):
        return
    from sitewhere_tpu.ingest.wire_edge import aggregate_wire_snapshot

    snap = aggregate_wire_snapshot(eng)
    if snap is None:
        return
    reg = registry or REGISTRY
    reg.gauge("swtpu_wire_connections_live",
              "persistent connections currently attached to the wire "
              "edge").set(snap["connections_live"])
    reg.gauge("swtpu_wire_connections_peak",
              "peak concurrent persistent connections").set(
                  snap["connections_peak"])
    reg.gauge("swtpu_wire_connections_opened_total",
              "persistent connections accepted since edge start").set(
                  snap["connections_opened"])
    frames = reg.gauge("swtpu_wire_frames_total",
                       "wire frames by edge disposition")
    for disp in ("admitted", "shed", "invalid", "duplicate"):
        frames.set(snap[f"frames_{disp}"], disposition=disp)
    frames.set(snap["frames_received"], disposition="received")
    reg.gauge("swtpu_wire_rows_submitted_total",
              "frames handed to the batched arena-ingest path").set(
                  snap["rows_submitted"])
    reg.gauge("swtpu_wire_frames_stalled_total",
              "admitted frames shed by arena stall (acks withheld)").set(
                  snap["frames_stalled"])
    reg.gauge("swtpu_wire_pending_frames",
              "frames buffered in open arrival windows").set(
                  snap["pending"])
    reg.gauge("swtpu_wire_flushes_total",
              "arrival-window flushes (size, deadline, or drain)").set(
                  snap["flushes"])
    reg.gauge("swtpu_wire_flush_occupancy_pct",
              "mean flushed rows as % of the size threshold — low means "
              "the deadline fires first (latency-bound windows)").set(
                  snap["flush_occupancy_pct"])
    reg.gauge("swtpu_wire_backpressure_total",
              "protocol-level backpressure signals sent (PUBACK "
              "withheld / SWP shed codes)").set(
                  snap["backpressure_events"])
    reg.gauge("swtpu_wire_keepalive_timeouts_total",
              "connections dropped for keepalive silence").set(
                  snap["keepalive_timeouts"])


def export_observability_metrics(engine, registry: MetricsRegistry | None
                                 = None) -> None:
    """Scrape-time export of the telemetry surfaces PR 3 added: the
    device-side per-tenant pipeline counter grid (computed INSIDE the jit
    step — zero extra host<->device syncs on the ingest path; the grid is
    read back here, on the scrape path, like every other device counter),
    plus host gauges for arena-pool occupancy, in-flight dispatch depth,
    and the cross-rank spill queue."""
    reg = registry or REGISTRY

    tpc = getattr(engine, "tenant_pipeline_counters", None)
    if callable(tpc):
        for ten, lanes in tpc().items():
            for lane, n in lanes.items():
                reg.gauge(f"swtpu_pipeline_{lane}",
                          f"device-side per-tenant {lane} event count "
                          "(computed in the jit step)").set(n, tenant=ten)

    # CEP-tier cadence-dependent counters (ISSUE 14 satellite): the
    # missed/late/oob fires live in rule_counters() — deliberately OUT
    # of engine.metrics() (dispatch-shape equality) — so until now a
    # pending-ring overflow was invisible unless you polled the Python
    # API. Scrape-time sync, like every other device-counter export;
    # an engine without an installed rule set exports nothing.
    rc = getattr(engine, "rule_counters", None)
    if callable(rc):
        counters = rc()
        for key, name, help_text in (
                ("ruleFires", "swtpu_rules_fires_total",
                 "distinct rule fire keys detected on device"),
                ("ruleMissedFires", "swtpu_rules_missed_total",
                 "rule fires dropped by pending-ring overflow"),
                ("ruleLateEvents", "swtpu_rules_late_total",
                 "events older than their rule window carry"),
                ("ruleOobGroups", "swtpu_rules_oob_groups_total",
                 "rule matches whose group id exceeded the group table"),
                ("rulesActive", "swtpu_rules_active",
                 "rules in the installed set"),
                ("rollupLateEvents", "swtpu_rollup_late_total",
                 "events older than their rollup slot's window"),
                ("rollupsActive", "swtpu_rollups_active",
                 "continuous rollups in the installed set")):
            if key in counters:
                reg.gauge(name, help_text).set(counters[key])

    pool = getattr(engine, "_arena_pool", None)
    if pool is not None:
        reg.gauge("swtpu_arena_pool_arenas",
                  "staging arenas in the ingest pool").set(pool.n_arenas)
        reg.gauge("swtpu_arena_pool_free",
                  "staging arenas currently fillable").set(pool.free_count)
        reg.gauge("swtpu_arena_pool_inflight",
                  "staging arenas tied to in-flight dispatches").set(
                      pool.inflight_count)
        reg.gauge("swtpu_arena_pool_waits",
                  "times ingest blocked on arena recycle").set(pool.waits)
        # capacity headroom (ISSUE 11 satellite): worst occupancy since
        # the last scrape, not just "now" — RESET on scrape, so each
        # sample reads "worst case this scrape window"
        take_hwm = getattr(pool, "take_occupancy_hwm", None)
        if take_hwm is not None:
            reg.gauge("swtpu_arena_pool_occupancy_hwm",
                      "max arenas simultaneously out of the free pool "
                      "since the last scrape (reset on scrape)").set(
                          take_hwm())
    take_backlog = getattr(engine, "take_backlog_hwm", None)
    if take_backlog is not None:
        reg.gauge("swtpu_staged_backlog_hwm_rows",
                  "max staged-row ingest backlog since the last scrape "
                  "(reset on scrape)").set(take_backlog())

    pending = getattr(engine, "_pending_outs", None)
    if pending is not None:
        reg.gauge("swtpu_dispatch_inflight",
                  "device programs dispatched but not yet drained").set(
                      len(pending))

    arch = getattr(engine, "archive", None)
    if arch is not None:
        inst = archive_metrics(reg)
        inst["segments"].set(len(arch.segments))
        inst["rows"].set(arch.total_rows())
        inst["bytes"].set(sum(
            _safe_size(arch.dir / s.path) for s in list(arch.segments)))
        inst["queries"].set(arch.queries)
        inst["considered"].set(arch.plan_considered)
        inst["pruned"].set(arch.plan_pruned)
        inst["decoded"].set(arch.plan_decoded)
        inst["count_shortcuts"].set(arch.count_shortcuts)
        inst["planner_calls"].set(arch.planner_calls)
        inst["cache_hits"].set(arch.cache.hits)
        inst["cache_loads"].set(arch.cache.loads)
        inst["corrupt"].set(arch.corrupt_segments)
        inst["lost_rows"].set(arch.lost_rows)
        inst["expired_rows"].set(arch.expired_rows)

    # fleet analytics tier (ISSUE 19): the scoring-job manager's own
    # counter snapshot — one consistent read under its lock, never the
    # engine lock
    aj = getattr(engine, "analytics_jobs", None)
    if aj is not None:
        inst = analytics_metrics(reg)
        s = aj.ledger_stage()
        for state in ("started", "completed", "cancelled", "failed"):
            inst["jobs"].set(s[f"jobs_{state}"], state=state)
        inst["rounds"].set(s["rounds"])
        inst["segments"].set(s["segments"])
        inst["bytes"].set(s["bytes"])
        inst["rows"].set(s["rows"])
        for sink in ("planned", "scored", "skipped_underfilled",
                     "cancelled"):
            inst["windows"].set(s[sink], sink=sink)
        inst["alerts"].set(s["alerts_emitted"], disposition="emitted")
        inst["alerts"].set(s["alerts_suppressed"],
                           disposition="suppressed")
        hc = getattr(engine, "host_counters", None) or {}
        inst["rollup_spilled"].set(hc.get("rollup_windows_spilled", 0))

    fq = getattr(engine, "forward_queue", None)
    if fq is not None:
        fm = fq.metrics()
        reg.gauge("swtpu_spill_queue_depth",
                  "cross-rank forward batches spilled to disk").set(
                      fm.get("forward_queue_depth", 0))
        oldest = fm.get("forward_queue_oldest_ms")
        if oldest is not None:
            reg.gauge("swtpu_spill_queue_oldest_ms",
                      "age of the oldest spilled forward").set(oldest)

    sreg = getattr(engine, "spill_registry", None)
    if sreg is not None:
        sm = sreg.metrics()
        reg.gauge("swtpu_forward_dedup_horizon_age_ms",
                  "age of the forward dedup eviction watermark (-1 = "
                  "nothing evicted yet)").set(
                      sm["forward_dedup_horizon_age_ms"])
        reg.gauge("swtpu_forward_dedup_entries",
                  "forward ids the dedup registry currently holds").set(
                      sm["forward_dedup_entries"])

    feed = getattr(engine, "replica_feed", None)
    applier = getattr(engine, "replica_applier", None)
    if feed is not None or applier is not None:
        inst = replication_metrics(reg)
        if feed is not None:
            fm = feed.metrics()
            inst["lag"].set(fm.get("replica_feed_max_lag_batches", 0))
        if applier is not None:
            # one series PER LEADER this rank stands by for (not a
            # global max): a single lagging follower must show up on
            # the scrape before a failover read hits it
            written: set[tuple] = set()
            for leader, ms in applier.stale_by_leader().items():
                labels = {"leader": str(leader)}
                inst["stale"].set(ms, **labels)
                written.add(tuple(sorted(labels.items())))
            inst["stale"].retain(written)

    flight = getattr(engine, "flight", None)
    if flight is not None:
        reg.gauge("swtpu_flight_records",
                  "batch lifecycle records held by the flight "
                  "recorder").set(len(flight))

    # span plane (ISSUE 10) — scrape-time sync of the tracer's own
    # counters; like every PR-10 instrument these stay OUT of
    # engine.metrics() (dispatch-shape equality pin)
    tracer = getattr(engine, "tracer", None)
    if tracer is not None:
        reg.gauge("swtpu_span_records",
                  "completed spans held by the span tracer").set(
                      len(tracer))
        reg.gauge("swtpu_spans_recorded_total",
                  "spans inserted into the tracer ring").set(
                      tracer.recorded)
        reg.gauge("swtpu_spans_sampled_out_total",
                  "spans dropped by the head+tail sampling verdict").set(
                      tracer.sampled_out)

    # SLO latency plane (ISSUE 7): drain completed ingest lifecycles the
    # recorder accumulated since the last scrape into the per-tenant e2e
    # histogram (the SLO autotuner shares the same drain via
    # harvest_slo — both feed ONE histogram, so exactly-once totals hold
    # no matter which consumer drains first)
    harvest_slo(engine, reg)

    # conservation plane (ISSUE 14): the flow ledger's host counters +
    # the background auditor's verdict. Lazy import (jax-free module,
    # but keep the scrape path's import graph explicit).
    try:
        from sitewhere_tpu.utils.conservation import (
            export_conservation_metrics)
    except ImportError:
        export_conservation_metrics = None
    if export_conservation_metrics is not None:
        export_conservation_metrics(engine, reg)

    # device plane (ISSUE 11): compile/retrace posture, memory ledger,
    # and the query-path device-time harvest. Lazy import keeps this
    # module importable without jax (offline tooling pins it).
    try:
        from sitewhere_tpu.utils import devicewatch as _dw
    except ImportError:
        _dw = None
    if _dw is not None:
        _dw.export_devicewatch(engine, reg)

    # overload-discipline plane (ISSUE 9): admission-bucket balances,
    # saturation state, and the weighted-fair virtual clocks — the
    # admitted/shed counters are incremented LIVE by the controller;
    # only balances/clocks are sampled here at scrape time
    qos = getattr(engine, "qos", None)
    if qos is not None:
        inst = qos_metrics(reg)
        lbl = getattr(qos, "label", "e?")
        fill = inst["fill"]
        current: set[tuple] = set()
        for tenant, tokens in qos.bucket_fill().items():
            fill.set(tokens, tenant=tenant, engine=lbl)
            current.add(tuple(sorted({"tenant": tenant,
                                      "engine": lbl}.items())))
        fill.retain(current, engine=lbl)
        inst["threshold"].set(qos.shed_threshold, engine=lbl)
        vt = inst["wfq_vtime"]
        keep: set[tuple] = set()
        gate = getattr(engine, "_wfq_gate", None)
        if gate is not None:
            for tenant, v in gate.vtimes().items():
                vt.set(v, tenant=tenant, resource="ingest", engine=lbl)
                keep.add(tuple(sorted({"tenant": tenant,
                                       "resource": "ingest",
                                       "engine": lbl}.items())))
        picker = getattr(getattr(engine, "_query_batcher", None),
                         "_wfq", None)
        if picker is not None:
            for tenant, v in picker.vtimes().items():
                vt.set(v, tenant=tenant, resource="query", engine=lbl)
                keep.add(tuple(sorted({"tenant": tenant,
                                       "resource": "query",
                                       "engine": lbl}.items())))
        vt.retain(keep, engine=lbl)


def harvest_slo(engine, registry: MetricsRegistry | None = None) -> None:
    """Drain completed ingest lifecycles into the per-tenant e2e SLO
    histogram — each record observed exactly once, weighted by its
    payload count, with a trace-id exemplar when the batch landed in the
    slowest decile of its tenant's series (a p99 spike on the scrape
    then links straight to /api/instance/trace/<id>). Shared by the
    scrape exporter and the SLO autotuner.

    Every series carries the harvesting engine's ``engine=e<n>`` label
    (ISSUE 10 satellite): the registry is process-global, so without the
    scope one in-process engine's ``decide_slo`` would steer on another
    engine's default-tenant p99 — the PR-9 documented leak. Aggregate
    readers sum across engines via ``count_where``/``quantile_where``."""
    reg = registry or REGISTRY
    harvest = getattr(engine, "slo_harvest", None)
    if callable(harvest):
        hist = slo_metrics(reg)["ingest_e2e"]
        # device-plane sibling (ISSUE 11): the dispatch->device_ready
        # interval of the SAME records feeds the per-family device
        # execution-time histogram. It rides THIS drain because the
        # records are consume-once — a second consumer would see nothing
        exec_hist = devicewatch_metrics(reg)["exec"]
        lbl = getattr(engine, "metrics_label", "e?")
        for rec in harvest():
            end = rec.stages.get("device_ready")
            if end is None:
                continue
            secs = max(0.0, (end - rec.t0_ns) / 1e9)
            ex = None
            if rec.trace_id is not None:
                q90 = hist.quantile(0.9, tenant=rec.tenant, engine=lbl)
                if q90 is None or secs >= q90:
                    ex = rec.trace_id
            hist.observe_n(secs, max(1, int(rec.n_payloads)),
                           exemplar=ex, tenant=rec.tenant, engine=lbl)
            disp = rec.stages.get("dispatch")
            if disp is not None and end >= disp:
                exec_hist.observe((end - disp) / 1e9, family="ingest")


# --------------------------------------------------------------------------
# Federated cluster exposition (ISSUE 7): every rank's registry merged
# into ONE rank-labeled payload served from any rank.
# --------------------------------------------------------------------------
def _inject_rank_label(line: str, rank) -> str:
    """Prepend ``rank="<rank>"`` to one sample line's label set without
    reparsing the rest of the line: the existing label body may contain
    escaped quotes and the tail may carry an OpenMetrics exemplar, both
    of which survive verbatim. The closing-brace scan honors quoted
    strings so a ``}`` inside a label VALUE never truncates the set."""
    i, n = 0, len(line)
    while i < n and line[i] not in "{ ":
        i += 1
    name = line[:i]
    rl = f'rank="{_escape_label(rank)}"'
    if i < n and line[i] == "{":
        j, in_str, esc = i + 1, False, False
        while j < n:
            ch = line[j]
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = not in_str
            elif ch == "}" and not in_str:
                break
            j += 1
        if j >= n:
            raise ValueError(f"unterminated label set: {line!r}")
        body = line[i + 1:j]
        sep = "," if body else ""
        return f"{name}{{{rl}{sep}{body}}}{line[j + 1:]}"
    return f"{name}{{{rl}}}{line[i:]}"


def federate_expositions(parts: dict) -> str:
    """Merge per-rank Prometheus expositions into ONE lint-clean payload:
    every sample gains a ``rank`` label, HELP/TYPE comments are deduped
    across ranks (first rank's text wins; a TYPE that genuinely differs
    between ranks is a code bug and fails loudly), and families stay
    contiguous. ``parts`` maps rank -> that rank's exposition text."""
    families: dict[str, dict] = {}
    order: list[str] = []
    for rank in sorted(parts, key=str):
        current: str | None = None
        for line in parts[rank].splitlines():
            if not line:
                continue
            if line.startswith("# HELP "):
                name = line.split(maxsplit=3)[2]
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = {"help": line, "type": None,
                                            "samples": []}
                    order.append(name)
                current = name
                continue
            if line.startswith("# TYPE "):
                p = line.split()
                name = p[2]
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = {"help": f"# HELP {name} ",
                                            "type": None, "samples": []}
                    order.append(name)
                if fam["type"] is None:
                    fam["type"] = line
                elif fam["type"] != line:
                    raise ValueError(
                        f"metric {name!r} exposed with conflicting types "
                        f"across ranks: {fam['type']!r} vs {line!r}")
                current = name
                continue
            if line.startswith("#"):
                continue           # other comments don't federate
            if current is None:
                raise ValueError(
                    f"rank {rank!r} sample before any HELP/TYPE: {line!r}")
            families[current]["samples"].append(
                _inject_rank_label(line, rank))
    lines: list[str] = []
    for name in order:
        fam = families[name]
        lines.append(fam["help"])
        if fam["type"] is not None:
            lines.append(fam["type"])
        lines.extend(fam["samples"])
    return "\n".join(lines) + "\n"


def federated_exposition(engine) -> str:
    """THE payload behind ``GET /api/instance/cluster/metrics`` and the
    ``Instance.clusterMetrics`` RPC: a clustered engine fans out to every
    rank (ClusterEngine.cluster_metrics); a single-node engine degrades
    to its own registry under ``rank="0"`` — including the
    ``swtpu_cluster_rank_up`` availability series, so alerts written
    against the clustered payload hold on any topology."""
    fn = getattr(engine, "cluster_metrics", None)
    if fn is not None:
        return fn()
    export_engine_metrics(engine)
    rank = getattr(engine, "rank", 0)
    text = federate_expositions({rank: REGISTRY.expose_text(exemplars=True)})
    return (text
            + "# HELP swtpu_cluster_rank_up 1 if the rank answered the "
              "federated scrape\n"
              "# TYPE swtpu_cluster_rank_up gauge\n"
            + f'swtpu_cluster_rank_up{{rank="{_escape_label(rank)}"}} 1\n')


# an exemplar suffix as THIS module emits it: labels then a float value,
# anchored at end of line — anchoring (rather than splitting on " # {")
# keeps a label VALUE that happens to contain '# {' intact
_EXEMPLAR_SUFFIX_RE = None


def strip_exemplars(text: str) -> str:
    """Drop OpenMetrics exemplar suffixes from an exposition — the
    Prometheus 0.0.4 text parser rejects a trailing ``# {...}`` on a
    sample line, so surfaces serving ``text/plain`` must shed them."""
    global _EXEMPLAR_SUFFIX_RE
    if _EXEMPLAR_SUFFIX_RE is None:
        import re

        _EXEMPLAR_SUFFIX_RE = re.compile(
            r' # \{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"\} [^ ]+$')
    return "\n".join(_EXEMPLAR_SUFFIX_RE.sub("", line)
                     for line in text.splitlines()) + "\n"
