"""Stage-time autotuner: steer ingest knobs toward the measured bottleneck.

The PR-3 flight recorder already timestamps every batch's lifecycle
(decode -> WAL -> commit -> dispatch -> device-ready) at near-zero cost;
this controller closes the loop. Every ``interval`` dispatches it takes
the MEDIAN per-stage durations over the recent record window
(utils/flight.stage_durations — the same harvesting rule bench.py
reports) and nudges ONE knob toward the dominant stage:

  decode dominates      -> widen the sharded-decode worker fan-out
  device dominates      -> deepen ``dispatch_depth`` (host/device overlap)
  dispatch overhead     -> double ``scan_chunk`` (amortize per-dispatch
     dominates             cost; opt-in — a chunk change recompiles the
                           arena scan program and rebuilds the pool)

with hysteresis (raise thresholds ~4x above the lower thresholds) so a
noisy window cannot ping-pong a knob. One change per evaluation keeps
every adjustment attributable. Decisions are kept on the controller
(``decisions``) and exported as gauges so an operator can see WHAT the
tuner believes and WHY without attaching a debugger:

  swtpu_autotune_ingest_workers / _dispatch_depth / _scan_chunk
  swtpu_autotune_adjustments (counter, labeled by knob + direction)

Every series carries a per-controller ``engine`` label (process-wide
creation index): several autotuned engines in one process must not
clobber each other's telemetry.
"""

from __future__ import annotations

import itertools
import statistics

from sitewhere_tpu.utils.flight import stage_durations
from sitewhere_tpu.utils.metrics import REGISTRY

_ENGINE_IDS = itertools.count()

G_WORKERS = REGISTRY.gauge(
    "swtpu_autotune_ingest_workers",
    "Sharded-decode worker fan-out chosen by the stage-time autotuner")
G_DEPTH = REGISTRY.gauge(
    "swtpu_autotune_dispatch_depth",
    "dispatch_depth chosen by the stage-time autotuner")
G_CHUNK = REGISTRY.gauge(
    "swtpu_autotune_scan_chunk",
    "scan_chunk chosen by the stage-time autotuner")
C_ADJUST = REGISTRY.counter(
    "swtpu_autotune_adjustments",
    "Autotuner knob adjustments, labeled by knob and direction")


def decide(stats: dict, current: dict, bounds: dict) -> list[tuple]:
    """Pure decision rule: (median stage durations, current knob values,
    knob bounds) -> ordered [(knob, new_value, reason)] proposals. Pure
    so tests can pin the policy without fabricating an engine. The
    caller applies at most the first proposal."""
    decode = stats.get("decode_ms") or 0.0
    wal = stats.get("wal_ms") or 0.0
    wait = stats.get("dispatch_wait_ms") or 0.0
    device = stats.get("device_ms") or 0.0
    host = decode + wal
    out = []
    workers = current["ingest_workers"]
    depth = current["dispatch_depth"]
    chunk = current["scan_chunk"]
    if (decode > device and decode > wal + wait
            and workers < bounds["max_workers"]):
        out.append(("ingest_workers", workers + 1,
                    f"decode {decode:.2f}ms dominates device "
                    f"{device:.2f}ms"))
    if workers > 1 and decode < 0.25 * device:
        out.append(("ingest_workers", workers - 1,
                    f"decode {decode:.2f}ms << device {device:.2f}ms; "
                    "shed shard overhead"))
    if device > 1.5 * max(host, 1e-9) and depth < bounds["max_depth"]:
        out.append(("dispatch_depth", depth + 1,
                    f"device {device:.2f}ms > host {host:.2f}ms; "
                    "overlap more programs"))
    if depth > 1 and device < 0.25 * max(host, 1e-9):
        out.append(("dispatch_depth", depth - 1,
                    f"device {device:.2f}ms << host {host:.2f}ms; "
                    "shed queue latency"))
    if wait > 2.0 * max(device, 1e-9) and chunk < bounds["max_chunk"]:
        out.append(("scan_chunk", chunk * 2,
                    f"dispatch wait {wait:.2f}ms > 2x device "
                    f"{device:.2f}ms; amortize dispatch"))
    if chunk > 1 and wait < 0.25 * max(device, 1e-9):
        out.append(("scan_chunk", max(1, chunk // 2),
                    f"dispatch wait {wait:.2f}ms << device "
                    f"{device:.2f}ms; shed chunk latency"))
    return out


class StageTimeAutotuner:
    """Periodic controller over one engine's ingest knobs.

    ``note_dispatch()`` is the engine's per-dispatch hook (called under
    the engine lock — applying a knob re-enters the same RLock). Knob
    application goes through ``engine.set_ingest_tuning``, the single
    choke point that knows how to rebuild what each knob invalidates.
    ``adapt_scan_chunk`` stays opt-in: a chunk change recompiles the
    arena scan program, which costs seconds on real chips — only a
    deployment that can afford mid-run recompiles should allow it."""

    MIN_SAMPLES = 8

    def __init__(self, engine, interval: int = 64, window: int = 128,
                 max_workers: int | None = None, max_depth: int = 4,
                 max_chunk: int = 8, adapt_scan_chunk: bool = False):
        self.engine = engine
        self.interval = max(1, interval)
        self.window = window
        sharder = getattr(engine, "_sharder", None)
        self.max_workers = (max_workers if max_workers is not None
                            else (sharder.n_workers if sharder else 1))
        self.max_depth = max_depth
        self.max_chunk = max_chunk
        self.adapt_scan_chunk = adapt_scan_chunk
        self.decisions: list[dict] = []
        self._since = 0
        self.evaluations = 0
        self.label = f"e{next(_ENGINE_IDS)}"

    def current(self) -> dict:
        eng = self.engine
        sharder = getattr(eng, "_sharder", None)
        return {
            "ingest_workers": (sharder.active_workers if sharder else 1),
            "dispatch_depth": max(1, eng.config.dispatch_depth),
            "scan_chunk": max(1, eng.config.scan_chunk),
        }

    def note_dispatch(self) -> None:
        self._since += 1
        if self._since < self.interval:
            return
        self._since = 0
        self.evaluate()

    def window_stats(self) -> dict | None:
        """Median per-stage durations over recent ingest records; None
        until the window holds enough samples to trust."""
        durs = [stage_durations(r.get("stagesUs", {}))
                for r in self.engine.flight.recent(self.window,
                                                   kind="ingest")]
        if len(durs) < self.MIN_SAMPLES:
            return None
        out = {}
        for key in ("decode_ms", "wal_ms", "dispatch_wait_ms", "device_ms"):
            vals = [d[key] for d in durs if d[key] is not None]
            out[key] = statistics.median(vals) if vals else None
        return out

    def evaluate(self) -> dict | None:
        """One control step: measure, decide, apply at most one change,
        export gauges. Returns the applied decision (or None)."""
        self.evaluations += 1
        stats = self.window_stats()
        applied = None
        if stats is not None:
            cur = self.current()
            bounds = {"max_workers": self.max_workers,
                      "max_depth": self.max_depth,
                      "max_chunk": self.max_chunk}
            for knob, value, reason in decide(stats, cur, bounds):
                if knob == "scan_chunk" and not self.adapt_scan_chunk:
                    continue
                self.engine.set_ingest_tuning(**{knob: value})
                applied = {"knob": knob, "from": cur[knob], "to": value,
                           "reason": reason, "stats": stats}
                self.decisions.append(applied)
                del self.decisions[:-64]
                C_ADJUST.inc(engine=self.label, knob=knob,
                             direction="up" if value > cur[knob]
                             else "down")
                break
        cur = self.current()
        G_WORKERS.set(cur["ingest_workers"], engine=self.label)
        G_DEPTH.set(cur["dispatch_depth"], engine=self.label)
        G_CHUNK.set(cur["scan_chunk"], engine=self.label)
        return applied
