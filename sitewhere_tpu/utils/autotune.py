"""Stage-time autotuner: steer ingest knobs toward the measured bottleneck.

The PR-3 flight recorder already timestamps every batch's lifecycle
(decode -> WAL -> commit -> dispatch -> device-ready) at near-zero cost;
this controller closes the loop. Every ``interval`` dispatches it takes
the MEDIAN per-stage durations over the recent record window
(utils/flight.stage_durations — the same harvesting rule bench.py
reports) and nudges ONE knob toward the dominant stage:

  decode dominates      -> widen the sharded-decode worker fan-out
  device dominates      -> deepen ``dispatch_depth`` (host/device overlap)
  dispatch overhead     -> double ``scan_chunk`` (amortize per-dispatch
     dominates             cost; opt-in — a chunk change recompiles the
                           arena scan program and rebuilds the pool)

with hysteresis (raise thresholds ~4x above the lower thresholds) so a
noisy window cannot ping-pong a knob. One change per evaluation keeps
every adjustment attributable. Decisions are kept on the controller
(``decisions``) and exported as gauges so an operator can see WHAT the
tuner believes and WHY without attaching a debugger:

  swtpu_autotune_ingest_workers / _dispatch_depth / _scan_chunk
  swtpu_autotune_adjustments (counter, labeled by knob + direction)

Every series carries a per-controller ``engine`` label (process-wide
creation index): several autotuned engines in one process must not
clobber each other's telemetry.
"""

from __future__ import annotations

import itertools
import statistics

from sitewhere_tpu.utils.flight import stage_durations
from sitewhere_tpu.utils.metrics import REGISTRY

_ENGINE_IDS = itertools.count()

G_WORKERS = REGISTRY.gauge(
    "swtpu_autotune_ingest_workers",
    "Sharded-decode worker fan-out chosen by the stage-time autotuner")
G_DEPTH = REGISTRY.gauge(
    "swtpu_autotune_dispatch_depth",
    "dispatch_depth chosen by the stage-time autotuner")
G_CHUNK = REGISTRY.gauge(
    "swtpu_autotune_scan_chunk",
    "scan_chunk chosen by the stage-time autotuner")
C_ADJUST = REGISTRY.counter(
    "swtpu_autotune_adjustments",
    "Autotuner knob adjustments, labeled by knob and direction")
G_SHED = REGISTRY.gauge(
    "swtpu_autotune_shed_threshold",
    "QoS saturation shed threshold chosen by the SLO autotuner")
G_P99 = REGISTRY.gauge(
    "swtpu_autotune_p99_ms",
    "worst per-tenant ingest-e2e p99 the SLO autotuner last observed")


def decide(stats: dict, current: dict, bounds: dict) -> list[tuple]:
    """Pure decision rule: (median stage durations, current knob values,
    knob bounds) -> ordered [(knob, new_value, reason)] proposals. Pure
    so tests can pin the policy without fabricating an engine. The
    caller applies at most the first proposal."""
    decode = stats.get("decode_ms") or 0.0
    wal = stats.get("wal_ms") or 0.0
    wait = stats.get("dispatch_wait_ms") or 0.0
    device = stats.get("device_ms") or 0.0
    host = decode + wal
    out = []
    workers = current["ingest_workers"]
    depth = current["dispatch_depth"]
    chunk = current["scan_chunk"]
    if (decode > device and decode > wal + wait
            and workers < bounds["max_workers"]):
        out.append(("ingest_workers", workers + 1,
                    f"decode {decode:.2f}ms dominates device "
                    f"{device:.2f}ms"))
    if workers > 1 and decode < 0.25 * device:
        out.append(("ingest_workers", workers - 1,
                    f"decode {decode:.2f}ms << device {device:.2f}ms; "
                    "shed shard overhead"))
    if device > 1.5 * max(host, 1e-9) and depth < bounds["max_depth"]:
        out.append(("dispatch_depth", depth + 1,
                    f"device {device:.2f}ms > host {host:.2f}ms; "
                    "overlap more programs"))
    if depth > 1 and device < 0.25 * max(host, 1e-9):
        out.append(("dispatch_depth", depth - 1,
                    f"device {device:.2f}ms << host {host:.2f}ms; "
                    "shed queue latency"))
    if wait > 2.0 * max(device, 1e-9) and chunk < bounds["max_chunk"]:
        out.append(("scan_chunk", chunk * 2,
                    f"dispatch wait {wait:.2f}ms > 2x device "
                    f"{device:.2f}ms; amortize dispatch"))
    if chunk > 1 and wait < 0.25 * max(device, 1e-9):
        out.append(("scan_chunk", max(1, chunk // 2),
                    f"dispatch wait {wait:.2f}ms << device "
                    f"{device:.2f}ms; shed chunk latency"))
    return out


def decide_slo(p99_ms: float | None, target_ms: float, stats: dict,
               current: dict, bounds: dict) -> list[tuple]:
    """Pure SLO policy (ISSUE 9): steer toward a per-tenant ingest-e2e
    p99 TARGET instead of raw throughput. Proposals only fire outside
    the hysteresis dead band [0.5x, 1.25x] around the target, so scrape
    noise cannot ping-pong a knob.

    Violating (p99 > 1.25x target) — relieve the measured bottleneck
    first (the same stage attribution as the throughput policy: decode
    dominance widens fan-out, device dominance overlaps programs, a
    latency-costly scan chunk halves), then TIGHTEN the shed threshold
    (shed earlier: trade goodput for tail). Comfortable (p99 < 0.5x
    target) — RELAX the shed threshold back toward bounds so goodput
    recovers once the tail is safe. One change per evaluation, like the
    throughput policy; the caller applies the first proposal."""
    out: list[tuple] = []
    if p99_ms is None or target_ms is None or target_ms <= 0:
        return out
    decode = stats.get("decode_ms") or 0.0
    wal = stats.get("wal_ms") or 0.0
    wait = stats.get("dispatch_wait_ms") or 0.0
    device = stats.get("device_ms") or 0.0
    host = decode + wal
    workers = current.get("ingest_workers", 1)
    depth = current.get("dispatch_depth", 1)
    chunk = current.get("scan_chunk", 1)
    shed = current.get("shed_threshold")
    why = f"p99 {p99_ms:.1f}ms vs target {target_ms:.1f}ms"
    if p99_ms > 1.25 * target_ms:
        if (decode > device and decode > wal + wait
                and workers < bounds["max_workers"]):
            out.append(("ingest_workers", workers + 1,
                        f"{why}: decode {decode:.2f}ms dominates; "
                        "widen fan-out"))
        if (device > 1.5 * max(host, 1e-9)
                and depth < bounds["max_depth"]):
            out.append(("dispatch_depth", depth + 1,
                        f"{why}: device {device:.2f}ms dominates; "
                        "overlap programs"))
        if chunk > 1:
            out.append(("scan_chunk", max(1, chunk // 2),
                        f"{why}: scan chunk adds K-1 batches of "
                        "latency; halve it"))
        if shed is not None and shed > bounds.get("min_shed", 1):
            out.append(("shed_threshold",
                        max(bounds.get("min_shed", 1), shed // 2),
                        f"{why}: shed earlier to protect the tail"))
    elif p99_ms < 0.5 * target_ms:
        if shed is not None and shed < bounds.get("max_shed", shed):
            out.append(("shed_threshold",
                        min(bounds["max_shed"], shed * 2),
                        f"{why}: tail is safe; admit more"))
    return out


class StageTimeAutotuner:
    """Periodic controller over one engine's ingest knobs.

    ``note_dispatch()`` is the engine's per-dispatch hook (called under
    the engine lock — applying a knob re-enters the same RLock). Knob
    application goes through ``engine.set_ingest_tuning``, the single
    choke point that knows how to rebuild what each knob invalidates.
    ``adapt_scan_chunk`` stays opt-in: a chunk change recompiles the
    arena scan program, which costs seconds on real chips — only a
    deployment that can afford mid-run recompiles should allow it."""

    MIN_SAMPLES = 8

    def __init__(self, engine, interval: int = 64, window: int = 128,
                 max_workers: int | None = None, max_depth: int = 4,
                 max_chunk: int = 8, adapt_scan_chunk: bool = False):
        self.engine = engine
        self.interval = max(1, interval)
        self.window = window
        sharder = getattr(engine, "_sharder", None)
        self.max_workers = (max_workers if max_workers is not None
                            else (sharder.n_workers if sharder else 1))
        self.max_depth = max_depth
        self.max_chunk = max_chunk
        self.adapt_scan_chunk = adapt_scan_chunk
        self.decisions: list[dict] = []
        self._since = 0
        self.evaluations = 0
        self.label = f"e{next(_ENGINE_IDS)}"
        # SLO objective (ISSUE 9): with a p99 target configured, the
        # controller steers toward the target (decide_slo) instead of
        # raw throughput, and additionally owns the QoS shed threshold
        self.slo_target_ms = getattr(engine.config,
                                     "slo_p99_target_ms", None)
        # per-series (bucket counts, total) snapshot from the previous
        # evaluation — slo_p99_ms() steers on the delta, never the
        # cumulative-forever histogram
        self._slo_prev: dict[tuple, tuple[list[int], int]] = {}
        bc = max(1, getattr(engine.config, "batch_capacity", 1))
        self.min_shed = bc
        self.max_shed = 64 * bc * max(1, getattr(engine.config,
                                                 "scan_chunk", 1))

    def current(self) -> dict:
        eng = self.engine
        sharder = getattr(eng, "_sharder", None)
        out = {
            "ingest_workers": (sharder.active_workers if sharder else 1),
            "dispatch_depth": max(1, eng.config.dispatch_depth),
            "scan_chunk": max(1, eng.config.scan_chunk),
        }
        qos = getattr(eng, "qos", None)
        out["shed_threshold"] = (qos.shed_threshold if qos is not None
                                 else None)
        return out

    def note_dispatch(self) -> None:
        self._since += 1
        if self._since < self.interval:
            return
        self._since = 0
        self.evaluate()

    def window_stats(self) -> dict | None:
        """Median per-stage durations over recent ingest records; None
        until the window holds enough samples to trust."""
        durs = [stage_durations(r.get("stagesUs", {}))
                for r in self.engine.flight.recent(self.window,
                                                   kind="ingest")]
        if len(durs) < self.MIN_SAMPLES:
            return None
        out = {}
        for key in ("decode_ms", "wal_ms", "dispatch_wait_ms", "device_ms"):
            vals = [d[key] for d in durs if d[key] is not None]
            out[key] = statistics.median(vals) if vals else None
        return out

    def slo_p99_ms(self) -> float | None:
        """Worst per-tenant ingest-e2e p99 (ms) over the WINDOW since
        the previous evaluation, read off the registry's SLO histogram
        (``swtpu_ingest_e2e_seconds``) and restricted to THIS engine's
        tenants — the registry is process-global. Windowing matters:
        the histogram is cumulative-forever, so a lifetime quantile
        would let one early overload (jit warmup, a single burst) pin
        the reading above target for the rest of the process and
        ratchet the shed threshold to its floor with no way to observe
        recovery — each evaluation therefore diffs the bucket counts
        against its previous snapshot and interpolates the quantile
        from the delta (same bounding-bucket rule as
        ``Histogram.quantile``; overflow clamps to the last finite
        bound). ``None`` when the window saw no observations — the
        policy then holds rather than acting on stale data. Harvests
        pending flight records first through the same consume-once
        drain the scrape exporter uses; both feed ONE histogram, so
        exactly-once totals hold regardless of who drains first.

        Scope (ISSUE 10 satellite, closing the PR-9 known limit): the
        harvest stamps every series with the harvesting engine's
        ``engine=e<n>`` label (metrics.harvest_slo), and this reader
        keeps ONLY its own engine's series — two SLO-targeted engines in
        one process no longer share the default-tenant reading, so one
        rank's steering can never act on another rank's tenants (pinned
        by a two-engine test in tests/test_qos.py)."""
        from sitewhere_tpu.utils.metrics import harvest_slo, slo_metrics

        harvest_slo(self.engine)
        hist = slo_metrics()["ingest_e2e"]
        with hist._lock:
            snap = {k: (list(v), hist._totals.get(k, 0))
                    for k, v in hist._counts.items()}
        mine = getattr(self.engine, "metrics_label", None)
        worst = None
        for key, (counts, total) in snap.items():
            labels = dict(key)
            tenant = labels.get("tenant")
            if tenant is None or labels.get("engine") != mine:
                continue
            prev_counts, prev_total = self._slo_prev.get(
                key, ([0] * len(counts), 0))
            self._slo_prev[key] = (counts, total)
            delta = [c - p for c, p in zip(counts, prev_counts)]
            n = total - prev_total
            if n <= 0:
                continue
            target = 0.99 * n
            acc = 0
            q = hist.buckets[-1]
            for i, c in enumerate(delta):
                if c and acc + c >= target:
                    lo = hist.buckets[i - 1] if i else 0.0
                    hi = hist.buckets[i]
                    frac = min(1.0, max(0.0, (target - acc) / c))
                    q = lo + (hi - lo) * frac
                    break
                acc += c
            if worst is None or q > worst:
                worst = q
        return worst * 1000.0 if worst is not None else None

    def evaluate(self) -> dict | None:
        """One control step: measure, decide, apply at most one change,
        export gauges. With an SLO target the decision rule is
        ``decide_slo`` (p99-vs-target with hysteresis, shed threshold
        included); otherwise the throughput rule ``decide``. Returns the
        applied decision (or None)."""
        self.evaluations += 1
        stats = self.window_stats()
        applied = None
        p99_ms = None
        if self.slo_target_ms is not None:
            p99_ms = self.slo_p99_ms()
            if p99_ms is not None:
                G_P99.set(p99_ms, engine=self.label)
        if stats is not None:
            cur = self.current()
            bounds = {"max_workers": self.max_workers,
                      "max_depth": self.max_depth,
                      "max_chunk": self.max_chunk,
                      "min_shed": self.min_shed,
                      "max_shed": self.max_shed}
            if self.slo_target_ms is not None:
                proposals = decide_slo(p99_ms, self.slo_target_ms,
                                       stats, cur, bounds)
            else:
                proposals = decide(stats, cur, bounds)
            for knob, value, reason in proposals:
                if knob == "scan_chunk" and not self.adapt_scan_chunk:
                    continue
                self.engine.set_ingest_tuning(**{knob: value})
                applied = {"knob": knob, "from": cur[knob], "to": value,
                           "reason": reason, "stats": stats,
                           "p99_ms": p99_ms}
                self.decisions.append(applied)
                del self.decisions[:-64]
                C_ADJUST.inc(engine=self.label, knob=knob,
                             direction="up" if value > (cur[knob] or 0)
                             else "down")
                break
        cur = self.current()
        G_WORKERS.set(cur["ingest_workers"], engine=self.label)
        G_DEPTH.set(cur["dispatch_depth"], engine=self.label)
        G_CHUNK.set(cur["scan_chunk"], engine=self.label)
        if cur.get("shed_threshold") is not None:
            G_SHED.set(cur["shed_threshold"], engine=self.label)
        return applied
