"""Replayable ingest log: the durability half of checkpoint/resume.

The reference leans on Kafka's durable topics for at-least-once replay
(SURVEY.md §5.4/5.5). Without a broker, the engine appends every accepted
raw payload batch to a segmented, length-prefixed log BEFORE staging it;
on restart, replaying segments past the snapshot's watermark re-feeds the
idempotent pipeline. Segments rotate by size and old segments can be
pruned once a snapshot covers them.

Record framing: u32 LE payload length + payload bytes. A record length of
0xFFFFFFFF marks a watermark record whose payload is the JSON-encoded
absolute store cursor.
"""

from __future__ import annotations

import json
import pathlib
import struct
import threading
from typing import Iterator

_WATERMARK = 0xFFFFFFFF


class IngestLog:
    def __init__(self, directory: str | pathlib.Path,
                 segment_bytes: int = 64 << 20):
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = segment_bytes
        self._lock = threading.Lock()
        existing = sorted(self.dir.glob("segment-*.log"))
        self._seg_index = (
            int(existing[-1].stem.split("-")[1]) + 1 if existing else 0
        )
        self._fh = None
        self._open_segment()

    def _open_segment(self) -> None:
        if self._fh is not None:
            self._fh.close()
        path = self.dir / f"segment-{self._seg_index:08d}.log"
        self._fh = open(path, "ab")

    def append(self, payload: bytes) -> None:
        with self._lock:
            self._fh.write(struct.pack("<I", len(payload)))
            self._fh.write(payload)
            if self._fh.tell() >= self.segment_bytes:
                self._fh.flush()
                self._seg_index += 1
                self._open_segment()

    def append_watermark(self, store_cursor: int) -> None:
        """Record that all payloads so far are reflected at this cursor."""
        body = json.dumps({"cursor": store_cursor}).encode()
        with self._lock:
            self._fh.write(struct.pack("<I", _WATERMARK))
            self._fh.write(struct.pack("<I", len(body)))
            self._fh.write(body)
            self._fh.flush()

    def sync(self) -> None:
        with self._lock:
            self._fh.flush()
            import os

            os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def replay(self, after_cursor: int = -1) -> Iterator[bytes]:
        """Yield payloads recorded after the last watermark <= after_cursor
        (everything, when no watermark qualifies)."""
        pending: list[bytes] = []
        emitting = after_cursor < 0
        for path in sorted(self.dir.glob("segment-*.log")):
            with open(path, "rb") as fh:
                while True:
                    head = fh.read(4)
                    if len(head) < 4:
                        break
                    (n,) = struct.unpack("<I", head)
                    if n == _WATERMARK:
                        (m,) = struct.unpack("<I", fh.read(4))
                        meta = json.loads(fh.read(m))
                        if not emitting:
                            if meta["cursor"] <= after_cursor:
                                pending.clear()  # covered by the snapshot
                            else:
                                # snapshot falls before this watermark: the
                                # held records may not be reflected — replay
                                emitting = True
                                yield from pending
                                pending.clear()
                        continue
                    payload = fh.read(n)
                    if len(payload) < n:
                        break  # torn tail write: stop cleanly
                    if emitting:
                        yield payload
                    else:
                        pending.append(payload)
        yield from pending

    def prune(self, keep_segments: int = 2) -> int:
        """Delete old segments (call after a snapshot); returns count."""
        segs = sorted(self.dir.glob("segment-*.log"))
        removed = 0
        for path in segs[:-keep_segments] if keep_segments else segs:
            path.unlink()
            removed += 1
        return removed
