"""Replayable ingest log: the durability half of checkpoint/resume.

The reference leans on Kafka's durable topics for at-least-once replay
(SURVEY.md §5.4/5.5). Without a broker, the engine appends every accepted
raw payload batch to a segmented, length-prefixed log BEFORE staging it;
on restart, replaying segments past the snapshot's watermark re-feeds the
idempotent pipeline. Segments rotate by size and old segments can be
pruned once a snapshot covers them.

Record framing: u32 LE payload length + u32 LE CRC32 + payload bytes. A
record length of 0xFFFFFFFF marks a watermark record whose payload is the
JSON-encoded absolute store cursor. The CRC catches torn and corrupted
records on replay (Kafka's per-record CRC analog): replay stops cleanly at
the first bad record of the tail segment instead of feeding garbage into
the pipeline.

GROUP COMMIT (``group_commit=True``): the classic DeWitt-style durability
amortizer. Appends land in a user-space buffer and return a sequence
number immediately; a dedicated commit thread drains the buffer, writes
it, and fsyncs ONCE per drain — so concurrent/back-to-back append groups
share an fsync, and the appending (driver) thread never blocks on disk.
``wait_durable(seq)`` is the durability watermark: it blocks until every
record appended at or before ``seq`` is fsync'd (kicking the commit
thread so a waiter never sits out the quiescent window). Because the
buffer is user-space, a crash loses exactly the un-fsynced tail — which
is why the engine gates every device dispatch on its batch's watermark
(strict WAL-before-dispatch, now with the fsync latency overlapped
against next-batch decode instead of serialized on the driver thread).
"""

from __future__ import annotations

import json
import os
import pathlib
import struct
import threading
import time
import zlib
from typing import Iterator

from sitewhere_tpu.utils.metrics import REGISTRY

_WATERMARK = 0xFFFFFFFF
_MAGIC = b"SWAL1\n"   # segment format marker; absent = legacy length-only

# fsync dominates the durability tail; the histogram makes a slow disk
# visible on the same scrape page as the e2e latency it inflates. Under
# group commit the observation count is the number of COMMITS — fewer
# than batches at steady state (the amortization proof, pinned by
# tests/test_group_commit.py).
_FSYNC_HIST = REGISTRY.histogram("swtpu_wal_fsync_seconds",
                                 "WAL fsync latency")


class IngestLog:
    def __init__(self, directory: str | pathlib.Path,
                 segment_bytes: int = 64 << 20, readonly: bool = False,
                 group_commit: bool = False,
                 group_window_s: float = 0.002):
        """``readonly`` opens the log for replay only: no tail segment is
        created and appends raise — the mode for forensic/recovery copies
        that must stay byte-identical. ``group_commit`` starts the commit
        thread (see module docstring); ``group_window_s`` is the
        quiescent window the commit thread waits for more appenders
        before fsyncing, when nobody is blocked on the watermark."""
        self.dir = pathlib.Path(directory)
        self.readonly = readonly
        if not readonly:
            self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = segment_bytes
        self._lock = threading.Lock()
        existing = sorted(self.dir.glob("segment-*.log"))
        self._seg_index = (
            int(existing[-1].stem.split("-")[1]) + 1 if existing else 0
        )
        self._fh = None
        if not readonly:
            self._open_segment()
        # ---- group commit state (all guarded by _lock via _cv) ----
        self.group_commit = group_commit and not readonly
        self.group_window_s = group_window_s
        self._cv = threading.Condition(self._lock)
        self._buf = bytearray()     # appended, not yet written
        self._seq = 0               # last append sequence handed out
        self._written_seq = 0       # written+flushed through this seq
        self._durable_seq = 0       # fsync'd through this seq
        # nothing in the fresh tail segment is fsync'd yet — even its
        # magic header sits in the write buffer until the first commit
        self._durable_tell = 0
        self._durable_seg = self._seg_index
        self._waiters = 0
        self._closed = False
        self._commit_err: BaseException | None = None
        self.fsyncs = 0             # commit fsyncs (amortization proof)
        self.commit_groups = 0      # append groups covered by them
        self._commit_hook = None    # test injection point (pre-fsync)
        if self.group_commit:
            self._commit_thread = threading.Thread(
                target=self._commit_loop, name="swtpu-wal-commit",
                daemon=True)
            self._commit_thread.start()

    def _open_segment(self) -> None:
        if self._fh is not None:
            self._fh.close()
        path = self.dir / f"segment-{self._seg_index:08d}.log"
        self._fh = open(path, "ab")
        if self._fh.tell() == 0:
            self._fh.write(_MAGIC)

    # ------------------------------------------------------------- append
    def append(self, payload: bytes) -> int:
        if self.readonly:
            raise RuntimeError("read-only ingest log")
        frame = struct.pack("<II", len(payload), zlib.crc32(payload)) \
            + payload
        with self._lock:
            if self.group_commit:
                return self._buffer_frames(frame)
            self._fh.write(frame)
            self._maybe_rotate()
            self._seq += 1
            return self._seq

    def append_many(self, payloads, head: bytes = b"") -> int:
        """Append one record per payload (each framed as ``head + payload``)
        with ONE buffered write for the whole group — the batch-ingest WAL
        path frames thousands of records per arena, and a write() per
        record was a measurable slice of the staging budget. Identical
        on-disk format to per-record :meth:`append`. Returns the group's
        append sequence — the ticket :meth:`wait_durable` gates on."""
        if self.readonly:
            raise RuntimeError("read-only ingest log")
        head_crc = zlib.crc32(head)
        frames = bytearray()
        for p in payloads:
            frames += struct.pack("<II", len(head) + len(p),
                                  zlib.crc32(p, head_crc))
            frames += head
            frames += p
        with self._lock:
            if self.group_commit:
                return self._buffer_frames(frames)
            self._fh.write(frames)
            self._maybe_rotate()
            self._seq += 1
            return self._seq

    def append_watermark(self, store_cursor: int) -> None:
        """Record that all payloads so far are reflected at this cursor.
        Under group commit the watermark rides the buffer (order with its
        records preserved); a lost un-fsynced watermark only means extra
        replay, never a gap."""
        if self.readonly:
            raise RuntimeError("read-only ingest log")
        body = json.dumps({"cursor": store_cursor}).encode()
        frame = struct.pack("<I", _WATERMARK) \
            + struct.pack("<II", len(body), zlib.crc32(body)) + body
        with self._lock:
            if self.group_commit:
                self._buffer_frames(frame)
                return
            self._fh.write(frame)
            self._fh.flush()

    def _buffer_frames(self, frames) -> int:
        """Queue frames for the commit thread; caller holds the lock."""
        if not frames:
            # an empty group adds no records: its durability requirement
            # is exactly the prior ticket's (a fresh seq here would never
            # wake the commit thread and would hang the gate)
            return self._seq
        if self._commit_err is not None:
            # surface a stuck durability path at the NEXT append rather
            # than only at the gate — the sooner ingest stops accepting,
            # the less there is to lose
            err = self._commit_err
            raise RuntimeError("WAL commit thread failed") from err
        self._buf += frames
        self._seq += 1
        self._cv.notify_all()
        return self._seq

    def _maybe_rotate(self) -> None:
        if self._fh.tell() >= self.segment_bytes:
            self._fh.flush()
            self._seg_index += 1
            self._open_segment()

    # ------------------------------------------------------- group commit
    def _commit_loop(self) -> None:
        while True:
            with self._cv:
                while (not self._buf and self._durable_seq >= self._seq
                       and not self._closed):
                    self._cv.wait()
                if self._closed and not self._buf \
                        and self._durable_seq >= self._seq:
                    return
                if self._buf and not self._waiters and not self._closed:
                    # quiescent window: let back-to-back appenders pile
                    # into this commit — but never make a waiter pay it
                    self._cv.wait(self.group_window_s)
                buf, self._buf = self._buf, bytearray()
                target = self._seq
            try:
                groups = target - self._written_seq
                if buf:
                    self._fh.write(buf)
                    self._fh.flush()
                hook = self._commit_hook
                if hook is not None:
                    hook()
                t0 = time.perf_counter()
                os.fsync(self._fh.fileno())
                _FSYNC_HIST.observe(time.perf_counter() - t0)
                with self._cv:
                    self._written_seq = max(self._written_seq, target)
                    self._durable_seq = max(self._durable_seq, target)
                    self._durable_tell = self._fh.tell()
                    self._durable_seg = self._seg_index
                    self.fsyncs += 1
                    self.commit_groups += max(0, groups)
                    # rotation AFTER the fsync that covers the tail: the
                    # sealed segment is durable before a new one opens.
                    # NOTHING in the fresh segment is durable yet — its
                    # magic header sits in the write buffer until the
                    # next commit flushes + fsyncs it
                    if self._fh.tell() >= self.segment_bytes:
                        self._seg_index += 1
                        self._open_segment()
                        self._durable_tell = 0
                        self._durable_seg = self._seg_index
                    self._cv.notify_all()
            except Exception as e:
                # FAIL-STOP: after a failed write/fsync the kernel may
                # have dropped dirty pages while marking them clean
                # (fsyncgate) — retrying would *lie* about durability,
                # and a later successful commit must never unblock gates
                # covering frames that were lost here. Poison the log:
                # every gate and every further append raises.
                with self._cv:
                    self._commit_err = e
                    self._cv.notify_all()
                return

    def wait_durable(self, seq: int, timeout: float = 30.0) -> None:
        """Block until every append at or before ``seq`` is fsync'd — the
        dispatch gate's durability watermark. No-op when group commit is
        off (the non-group path flushes inline, preserving its original
        contract). Raises when the commit thread is failing: a dispatch
        must never proceed on a batch whose durability cannot be
        established."""
        if not self.group_commit:
            return
        deadline = time.monotonic() + timeout
        with self._cv:
            self._waiters += 1
            self._cv.notify_all()   # kick: a waiter skips the window
            try:
                while self._durable_seq < seq:
                    if self._commit_err is not None:
                        err = self._commit_err
                        raise RuntimeError(
                            "WAL group commit failed; refusing to "
                            "dispatch an un-durable batch") from err
                    if self._closed:
                        raise RuntimeError("ingest log closed while "
                                           "awaiting durability")
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"WAL durability watermark {seq} not reached "
                            f"within {timeout}s")
                    self._cv.wait(min(remaining, 0.5))
            finally:
                self._waiters -= 1

    @property
    def durable_seq(self) -> int:
        with self._lock:
            return self._durable_seq

    def durable_view(self) -> dict[str, int]:
        """{segment filename: fsync'd byte count} — what would survive a
        machine crash right now. Sealed segments are durable in full
        (rotation happens only after the covering fsync); the live
        segment is durable up to the last commit's tell. Test/forensics
        surface for the crash-safety proof."""
        with self._lock:
            out = {}
            for path in sorted(self.dir.glob("segment-*.log")):
                idx = int(path.stem.split("-")[1])
                if idx < self._durable_seg:
                    out[path.name] = path.stat().st_size
                elif idx == self._durable_seg:
                    out[path.name] = self._durable_tell
                else:
                    out[path.name] = 0
            return out

    def flush(self) -> None:
        """Push buffered records to the OS (survives a process crash).
        Under group commit: drain the user-space buffer through the
        commit thread (which fsyncs — strictly stronger)."""
        if self.group_commit:
            with self._lock:
                seq = self._seq
            self.wait_durable(seq)
            return
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def sync(self) -> None:
        if self.group_commit:
            self.flush()
            return
        with self._lock:
            if self._fh is None:
                return
            self._fh.flush()
            t0 = time.perf_counter()
            os.fsync(self._fh.fileno())
            _FSYNC_HIST.observe(time.perf_counter() - t0)

    def close(self) -> None:
        if self.group_commit:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            self._commit_thread.join(timeout=5)
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def replay(self, after_cursor: int = -1) -> Iterator[bytes]:
        """Yield payloads recorded after the last watermark <= after_cursor
        (everything, when no watermark qualifies)."""
        pending: list[bytes] = []
        emitting = after_cursor < 0

        def read_record(fh, checked: bool):
            """(is_watermark, payload), "eof" at a record boundary, or
            "bad" on a torn/corrupt record. ``checked`` = current framing
            (len+crc); False = legacy (length-only) segments written before
            the CRC format."""
            head = fh.read(4)
            if not head:
                return "eof"
            if len(head) < 4:
                return "bad"
            (n,) = struct.unpack("<I", head)
            wm = n == _WATERMARK
            if wm:
                head = fh.read(4)
                if len(head) < 4:
                    return "bad"
                (n,) = struct.unpack("<I", head)
            if checked:
                crc_raw = fh.read(4)
                if len(crc_raw) < 4:
                    return "bad"
                (crc,) = struct.unpack("<I", crc_raw)
            payload = fh.read(n)
            if len(payload) < n:
                return "bad"
            if checked and zlib.crc32(payload) != crc:
                return "bad"
            return wm, payload

        paths = sorted(self.dir.glob("segment-*.log"))
        for si, path in enumerate(paths):
            with open(path, "rb") as fh:
                probe = fh.read(len(_MAGIC))
                checked = probe == _MAGIC
                if not checked:
                    fh.seek(0)   # legacy segment: no marker, no CRC
                while True:
                    rec = read_record(fh, checked)
                    if rec == "eof":
                        break    # clean end of segment
                    if rec == "bad":
                        if si == len(paths) - 1:
                            break   # torn tail of the live segment: expected
                        # corruption in a SEALED segment: stop the WHOLE
                        # replay — skipping ahead (or into later segments)
                        # would leave a silent gap in the stream
                        yield from pending
                        return
                    wm, payload = rec
                    if wm:
                        meta = json.loads(payload)
                        if not emitting:
                            if meta["cursor"] <= after_cursor:
                                pending.clear()  # covered by the snapshot
                            else:
                                # snapshot falls before this watermark: the
                                # held records may not be reflected — replay
                                emitting = True
                                yield from pending
                                pending.clear()
                        continue
                    if emitting:
                        yield payload
                    else:
                        pending.append(payload)
        yield from pending

    def prune(self, keep_segments: int = 2) -> int:
        """Delete old segments (call after a snapshot); returns count."""
        segs = sorted(self.dir.glob("segment-*.log"))
        removed = 0
        for path in segs[:-keep_segments] if keep_segments else segs:
            path.unlink()
            removed += 1
        return removed
