"""Replayable ingest log: the durability half of checkpoint/resume.

The reference leans on Kafka's durable topics for at-least-once replay
(SURVEY.md §5.4/5.5). Without a broker, the engine appends every accepted
raw payload batch to a segmented, length-prefixed log BEFORE staging it;
on restart, replaying segments past the snapshot's watermark re-feeds the
idempotent pipeline. Segments rotate by size and old segments can be
pruned once a snapshot covers them.

Record framing: u32 LE payload length + u32 LE CRC32 + payload bytes. A
record length of 0xFFFFFFFF marks a watermark record whose payload is the
JSON-encoded absolute store cursor. The CRC catches torn and corrupted
records on replay (Kafka's per-record CRC analog): replay stops cleanly at
the first bad record of the tail segment instead of feeding garbage into
the pipeline.
"""

from __future__ import annotations

import json
import pathlib
import struct
import threading
import time
import zlib
from typing import Iterator

from sitewhere_tpu.utils.metrics import REGISTRY

_WATERMARK = 0xFFFFFFFF
_MAGIC = b"SWAL1\n"   # segment format marker; absent = legacy length-only

# fsync dominates the durability tail; the histogram makes a slow disk
# visible on the same scrape page as the e2e latency it inflates
_FSYNC_HIST = REGISTRY.histogram("swtpu_wal_fsync_seconds",
                                 "WAL fsync latency")


class IngestLog:
    def __init__(self, directory: str | pathlib.Path,
                 segment_bytes: int = 64 << 20, readonly: bool = False):
        """``readonly`` opens the log for replay only: no tail segment is
        created and appends raise — the mode for forensic/recovery copies
        that must stay byte-identical."""
        self.dir = pathlib.Path(directory)
        self.readonly = readonly
        if not readonly:
            self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = segment_bytes
        self._lock = threading.Lock()
        existing = sorted(self.dir.glob("segment-*.log"))
        self._seg_index = (
            int(existing[-1].stem.split("-")[1]) + 1 if existing else 0
        )
        self._fh = None
        if not readonly:
            self._open_segment()

    def _open_segment(self) -> None:
        if self._fh is not None:
            self._fh.close()
        path = self.dir / f"segment-{self._seg_index:08d}.log"
        self._fh = open(path, "ab")
        if self._fh.tell() == 0:
            self._fh.write(_MAGIC)

    def append(self, payload: bytes) -> None:
        if self.readonly:
            raise RuntimeError("read-only ingest log")
        with self._lock:
            self._fh.write(struct.pack("<II", len(payload),
                                       zlib.crc32(payload)))
            self._fh.write(payload)
            if self._fh.tell() >= self.segment_bytes:
                self._fh.flush()
                self._seg_index += 1
                self._open_segment()

    def append_many(self, payloads, head: bytes = b"") -> None:
        """Append one record per payload (each framed as ``head + payload``)
        with ONE buffered write for the whole group — the batch-ingest WAL
        path frames thousands of records per arena, and a write() per
        record was a measurable slice of the staging budget. Identical
        on-disk format to per-record :meth:`append`."""
        if self.readonly:
            raise RuntimeError("read-only ingest log")
        head_crc = zlib.crc32(head)
        frames = bytearray()
        for p in payloads:
            frames += struct.pack("<II", len(head) + len(p),
                                  zlib.crc32(p, head_crc))
            frames += head
            frames += p
        with self._lock:
            self._fh.write(frames)
            if self._fh.tell() >= self.segment_bytes:
                self._fh.flush()
                self._seg_index += 1
                self._open_segment()

    def append_watermark(self, store_cursor: int) -> None:
        """Record that all payloads so far are reflected at this cursor."""
        if self.readonly:
            raise RuntimeError("read-only ingest log")
        body = json.dumps({"cursor": store_cursor}).encode()
        with self._lock:
            self._fh.write(struct.pack("<I", _WATERMARK))
            self._fh.write(struct.pack("<II", len(body), zlib.crc32(body)))
            self._fh.write(body)
            self._fh.flush()

    def flush(self) -> None:
        """Push buffered records to the OS (survives a process crash)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def sync(self) -> None:
        with self._lock:
            if self._fh is None:
                return
            self._fh.flush()
            import os

            t0 = time.perf_counter()
            os.fsync(self._fh.fileno())
            _FSYNC_HIST.observe(time.perf_counter() - t0)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def replay(self, after_cursor: int = -1) -> Iterator[bytes]:
        """Yield payloads recorded after the last watermark <= after_cursor
        (everything, when no watermark qualifies)."""
        pending: list[bytes] = []
        emitting = after_cursor < 0

        def read_record(fh, checked: bool):
            """(is_watermark, payload), "eof" at a record boundary, or
            "bad" on a torn/corrupt record. ``checked`` = current framing
            (len+crc); False = legacy (length-only) segments written before
            the CRC format."""
            head = fh.read(4)
            if not head:
                return "eof"
            if len(head) < 4:
                return "bad"
            (n,) = struct.unpack("<I", head)
            wm = n == _WATERMARK
            if wm:
                head = fh.read(4)
                if len(head) < 4:
                    return "bad"
                (n,) = struct.unpack("<I", head)
            if checked:
                crc_raw = fh.read(4)
                if len(crc_raw) < 4:
                    return "bad"
                (crc,) = struct.unpack("<I", crc_raw)
            payload = fh.read(n)
            if len(payload) < n:
                return "bad"
            if checked and zlib.crc32(payload) != crc:
                return "bad"
            return wm, payload

        paths = sorted(self.dir.glob("segment-*.log"))
        for si, path in enumerate(paths):
            with open(path, "rb") as fh:
                probe = fh.read(len(_MAGIC))
                checked = probe == _MAGIC
                if not checked:
                    fh.seek(0)   # legacy segment: no marker, no CRC
                while True:
                    rec = read_record(fh, checked)
                    if rec == "eof":
                        break    # clean end of segment
                    if rec == "bad":
                        if si == len(paths) - 1:
                            break   # torn tail of the live segment: expected
                        # corruption in a SEALED segment: stop the WHOLE
                        # replay — skipping ahead (or into later segments)
                        # would leave a silent gap in the stream
                        yield from pending
                        return
                    wm, payload = rec
                    if wm:
                        meta = json.loads(payload)
                        if not emitting:
                            if meta["cursor"] <= after_cursor:
                                pending.clear()  # covered by the snapshot
                            else:
                                # snapshot falls before this watermark: the
                                # held records may not be reflected — replay
                                emitting = True
                                yield from pending
                                pending.clear()
                        continue
                    if emitting:
                        yield payload
                    else:
                        pending.append(payload)
        yield from pending

    def prune(self, keep_segments: int = 2) -> int:
        """Delete old segments (call after a snapshot); returns count."""
        segs = sorted(self.dir.glob("segment-*.log"))
        removed = 0
        for path in segs[:-keep_segments] if keep_segments else segs:
            path.unlink()
            removed += 1
        return removed
