"""Scripting component: user script hooks loaded from files.

The reference customizes pipeline behavior with Groovy scripts managed by
the framework's ScriptingComponent/ScriptingUtils (+ Binding): scripted
event decoders (ScriptedEventDecoder.java:32-63), deduplicators, command
routers/encoders, connector filters, payload/URI builders, and dataset
bootstrap scripts — shipped as templates in
dockerimage/script-templates/*/*.groovy with a documented binding contract.

Here scripts are plain Python files. A script exposes one or more named
functions (the binding contract is the function signature); the manager
compiles the file once, caches by (path, mtime) so edits hot-reload —
the analog of the reference's ZooKeeper-backed script versioning — and
hands `ScriptHandle`s to the scripted components in ingest/decoders.py,
ingest/dedup.py, commands/routing.py, connectors/base.py, and config.py.
"""

from __future__ import annotations

import pathlib
import threading
from typing import Any, Callable


class ScriptError(ValueError):
    pass


class ScriptHandle:
    """One callable resolved from a script file; re-resolves on reload."""

    def __init__(self, manager: "ScriptManager", path: pathlib.Path,
                 function: str):
        self._manager = manager
        self._path = path
        self._function = function

    @property
    def name(self) -> str:
        return f"{self._path.name}:{self._function}"

    def __call__(self, *args, **kwargs):
        fn = self._manager._resolve(self._path, self._function)
        return fn(*args, **kwargs)


class ScriptManager:
    """Loads, caches, and hot-reloads script files (ScriptingComponent +
    ScriptingUtils analog)."""

    def __init__(self, root: str | pathlib.Path | None = None):
        self.root = pathlib.Path(root) if root is not None else None
        self._lock = threading.Lock()
        # path -> (mtime, namespace)
        self._cache: dict[pathlib.Path, tuple[float, dict[str, Any]]] = {}

    def _path_of(self, script: str | pathlib.Path) -> pathlib.Path:
        p = pathlib.Path(script)
        if not p.is_absolute() and self.root is not None:
            p = self.root / p
        return p

    def _load(self, path: pathlib.Path) -> dict[str, Any]:
        try:
            mtime = path.stat().st_mtime
        except OSError as e:
            raise ScriptError(f"script {path} not readable: {e}") from e
        with self._lock:
            cached = self._cache.get(path)
        if cached is not None and cached[0] == mtime:
            return cached[1]
        # compile/exec OUTSIDE the lock: scripts may themselves resolve other
        # scripts through this manager at import time (composite scripts),
        # and a slow load must not stall every other scripted hook. Two
        # racing loads of the same file both succeed; last one wins.
        ns: dict[str, Any] = {"__file__": str(path), "__name__": path.stem}
        code = compile(path.read_text(), str(path), "exec")
        exec(code, ns)
        with self._lock:
            self._cache[path] = (mtime, ns)
        return ns

    def _resolve(self, path: pathlib.Path, function: str) -> Callable:
        ns = self._load(path)
        fn = ns.get(function)
        if not callable(fn):
            raise ScriptError(
                f"script {path} does not define callable {function!r} "
                f"(defines: {sorted(k for k, v in ns.items() if callable(v) and not k.startswith('_'))})")
        return fn

    def handle(self, script: str | pathlib.Path,
               function: str) -> ScriptHandle:
        """Resolve (and eagerly validate) a script function."""
        path = self._path_of(script)
        self._resolve(path, function)   # fail fast at config time
        return ScriptHandle(self, path, function)

    def list_scripts(self) -> list[str]:
        """Script files under the template root (script-templates analog)."""
        if self.root is None or not self.root.exists():
            return []
        return sorted(str(p.relative_to(self.root))
                      for p in self.root.rglob("*.py"))


# module-level default manager; config.py binds "scripted" component types
# through it so bare {"script": "...", "function": "..."} specs work.
DEFAULT_MANAGER = ScriptManager()


def script_handle(spec: dict, default_function: str,
                  manager: ScriptManager | None = None) -> ScriptHandle:
    """Build a handle from a ``{script, function?}`` config spec — the
    shared plumbing for every scripted component type in config.py."""
    if "script" not in spec:
        raise ScriptError("scripted component requires a 'script' path")
    mgr = manager or DEFAULT_MANAGER
    return mgr.handle(spec["script"], spec.get("function", default_function))
