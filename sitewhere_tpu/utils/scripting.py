"""Scripting component: user script hooks loaded from files.

The reference customizes pipeline behavior with Groovy scripts managed by
the framework's ScriptingComponent/ScriptingUtils (+ Binding): scripted
event decoders (ScriptedEventDecoder.java:32-63), deduplicators, command
routers/encoders, connector filters, payload/URI builders, and dataset
bootstrap scripts — shipped as templates in
dockerimage/script-templates/*/*.groovy with a documented binding contract.

Here scripts are plain Python files. A script exposes one or more named
functions (the binding contract is the function signature); the manager
compiles the file once, caches by (path, mtime) so edits hot-reload —
the analog of the reference's ZooKeeper-backed script versioning — and
hands `ScriptHandle`s to the scripted components in ingest/decoders.py,
ingest/dedup.py, commands/routing.py, connectors/base.py, and config.py.
"""

from __future__ import annotations

import pathlib
import threading
from typing import Any, Callable


class ScriptError(ValueError):
    pass


class ScriptHandle:
    """One callable resolved from a script file; re-resolves on reload."""

    def __init__(self, manager: "ScriptManager", path: pathlib.Path,
                 function: str):
        self._manager = manager
        self._path = path
        self._function = function

    @property
    def name(self) -> str:
        return f"{self._path.name}:{self._function}"

    def __call__(self, *args, **kwargs):
        fn = self._manager._resolve(self._path, self._function)
        return fn(*args, **kwargs)


class ScriptManager:
    """Loads, caches, and hot-reloads script files (ScriptingComponent +
    ScriptingUtils analog)."""

    def __init__(self, root: str | pathlib.Path | None = None):
        self.root = pathlib.Path(root) if root is not None else None
        self._lock = threading.Lock()
        # path -> (mtime, namespace)
        self._cache: dict[pathlib.Path, tuple[float, dict[str, Any]]] = {}

    def _path_of(self, script: str | pathlib.Path) -> pathlib.Path:
        p = pathlib.Path(script)
        if not p.is_absolute() and self.root is not None:
            p = self.root / p
        return p

    def _load(self, path: pathlib.Path) -> dict[str, Any]:
        try:
            mtime = path.stat().st_mtime
        except OSError as e:
            raise ScriptError(f"script {path} not readable: {e}") from e
        with self._lock:
            cached = self._cache.get(path)
        if cached is not None and cached[0] == mtime:
            return cached[1]
        # compile/exec OUTSIDE the lock: scripts may themselves resolve other
        # scripts through this manager at import time (composite scripts),
        # and a slow load must not stall every other scripted hook. Two
        # racing loads of the same file both succeed; last one wins.
        ns: dict[str, Any] = {"__file__": str(path), "__name__": path.stem}
        code = compile(path.read_text(), str(path), "exec")
        exec(code, ns)
        with self._lock:
            self._cache[path] = (mtime, ns)
        return ns

    def _resolve(self, path: pathlib.Path, function: str) -> Callable:
        ns = self._load(path)
        fn = ns.get(function)
        if not callable(fn):
            raise ScriptError(
                f"script {path} does not define callable {function!r} "
                f"(defines: {sorted(k for k, v in ns.items() if callable(v) and not k.startswith('_'))})")
        return fn

    def handle(self, script: str | pathlib.Path,
               function: str) -> ScriptHandle:
        """Resolve (and eagerly validate) a script function."""
        path = self._path_of(script)
        self._resolve(path, function)   # fail fast at config time
        return ScriptHandle(self, path, function)

    def list_scripts(self) -> list[str]:
        """Script files under the template root (script-templates analog)."""
        if self.root is None or not self.root.exists():
            return []
        return sorted(str(p.relative_to(self.root))
                      for p in self.root.rglob("*.py"))


# module-level default manager; config.py binds "scripted" component types
# through it so bare {"script": "...", "function": "..."} specs work.
DEFAULT_MANAGER = ScriptManager()


def script_handle(spec: dict, default_function: str,
                  manager: ScriptManager | None = None) -> ScriptHandle:
    """Build a handle from a ``{script, function?}`` config spec — the
    shared plumbing for every scripted component type in config.py."""
    if "script" not in spec:
        raise ScriptError("scripted component requires a 'script' path")
    mgr = manager or DEFAULT_MANAGER
    return mgr.handle(spec["script"], spec.get("function", default_function))


# --------------------------------------------------------------------------
# Versioned tenant script management (reference: IScriptManagement consumed
# by Instance.java's /microservices/{id}/tenants/{token}/scripting/* REST
# family — script CRUD, per-version content, clone, activate; versions were
# kept in ZooKeeper, here on disk).
# --------------------------------------------------------------------------

import json as _json
import shutil
import time as _time


class ScriptManagement:
    """Disk-persisted, versioned script store scoped by (functional area,
    tenant) — the identifier/tenantToken pair of the reference's paths.

    Layout::

        root/{identifier}/{tenant}/{script_id}/
            metadata.json   # name/description/category/versions/active
            v{N}.py         # immutable-ish content per version
            active.py       # copy of the activated version

    ``active.py`` is THE path scripted components bind (via ScriptManager,
    which hot-reloads on mtime change), so activating a version takes
    effect on the very next decode/route/filter call — the analog of the
    reference pushing activated content out to listening microservices
    (Instance.java .../versions/{versionId}/activate).
    """

    def __init__(self, root: str | pathlib.Path,
                 manager: ScriptManager | None = None):
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.manager = manager or DEFAULT_MANAGER

    # ------------------------------------------------------------- paths
    def _script_dir(self, identifier: str, tenant: str,
                    script_id: str) -> pathlib.Path:
        for part in (identifier, tenant, script_id):
            if not part or "/" in part or part.startswith("."):
                raise ScriptError(f"invalid path component {part!r}")
        return self.root / identifier / tenant / script_id

    def _meta_path(self, d: pathlib.Path) -> pathlib.Path:
        return d / "metadata.json"

    def _read_meta(self, identifier: str, tenant: str,
                   script_id: str) -> tuple[pathlib.Path, dict]:
        d = self._script_dir(identifier, tenant, script_id)
        mp = self._meta_path(d)
        if not mp.exists():
            raise KeyError(f"script {script_id!r} not found")
        return d, _json.loads(mp.read_text())

    def _write_meta(self, d: pathlib.Path, meta: dict) -> None:
        tmp = self._meta_path(d).with_suffix(".tmp")
        tmp.write_text(_json.dumps(meta, indent=1))
        tmp.replace(self._meta_path(d))

    def active_path(self, identifier: str, tenant: str,
                    script_id: str) -> pathlib.Path:
        """The stable path scripted components reference in config specs."""
        return self._script_dir(identifier, tenant, script_id) / "active.py"

    # ------------------------------------------------------------- reads
    def list_scripts(self, identifier: str, tenant: str) -> list[dict]:
        base = self.root / identifier / tenant
        if not base.exists():
            return []
        out = []
        for d in sorted(base.iterdir()):
            mp = self._meta_path(d)
            if mp.exists():
                out.append(_json.loads(mp.read_text()))
        return out

    def list_by_category(self, identifier: str,
                         tenant: str) -> dict[str, list[dict]]:
        by_cat: dict[str, list[dict]] = {}
        for meta in self.list_scripts(identifier, tenant):
            by_cat.setdefault(meta.get("category") or "uncategorized",
                              []).append(meta)
        return by_cat

    def get_script(self, identifier: str, tenant: str,
                   script_id: str) -> dict:
        return self._read_meta(identifier, tenant, script_id)[1]

    def get_content(self, identifier: str, tenant: str, script_id: str,
                    version_id: str) -> str:
        d, meta = self._read_meta(identifier, tenant, script_id)
        if not any(v["versionId"] == version_id for v in meta["versions"]):
            raise KeyError(f"version {version_id!r} not found")
        return (d / f"{version_id}.py").read_text()

    # ------------------------------------------------------------ writes
    def create_script(self, identifier: str, tenant: str, *, script_id: str,
                      name: str | None = None, description: str = "",
                      category: str = "uncategorized",
                      content: str = "", activate: bool = True) -> dict:
        d = self._script_dir(identifier, tenant, script_id)
        if self._meta_path(d).exists():
            raise ValueError(f"script {script_id!r} already exists")
        d.mkdir(parents=True, exist_ok=True)
        meta = {
            "id": script_id, "name": name or script_id,
            "description": description, "category": category,
            "identifier": identifier, "tenant": tenant,
            "activeVersion": None, "versions": [],
        }
        self._write_meta(d, meta)
        meta = self._add_version(d, meta, content, "initial version")
        if activate:
            meta = self._activate(d, meta, meta["versions"][-1]["versionId"])
        return meta

    def _add_version(self, d: pathlib.Path, meta: dict, content: str,
                     comment: str) -> dict:
        vnum = 1 + max((int(v["versionId"][1:]) for v in meta["versions"]),
                       default=0)
        vid = f"v{vnum}"
        (d / f"{vid}.py").write_text(content)
        meta["versions"].append({
            "versionId": vid, "comment": comment,
            "createdMs": int(_time.time() * 1000),
        })
        self._write_meta(d, meta)
        return meta

    def update_script(self, identifier: str, tenant: str, script_id: str,
                      version_id: str, *, content: str | None = None,
                      name: str | None = None,
                      description: str | None = None,
                      category: str | None = None) -> dict:
        """Update version content and/or script metadata; re-syncs
        ``active.py`` when the updated version is the active one."""
        d, meta = self._read_meta(identifier, tenant, script_id)
        if not any(v["versionId"] == version_id for v in meta["versions"]):
            raise KeyError(f"version {version_id!r} not found")
        if content is not None:
            (d / f"{version_id}.py").write_text(content)
            if meta["activeVersion"] == version_id:
                meta = self._activate(d, meta, version_id)
        if name is not None:
            meta["name"] = name
        if description is not None:
            meta["description"] = description
        if category is not None:
            meta["category"] = category
        self._write_meta(d, meta)
        return meta

    def clone_version(self, identifier: str, tenant: str, script_id: str,
                      version_id: str, comment: str = "") -> dict:
        d, meta = self._read_meta(identifier, tenant, script_id)
        content = self.get_content(identifier, tenant, script_id, version_id)
        return self._add_version(d, meta, content,
                                 comment or f"cloned from {version_id}")

    def _activate(self, d: pathlib.Path, meta: dict,
                  version_id: str) -> dict:
        if not any(v["versionId"] == version_id for v in meta["versions"]):
            raise KeyError(f"version {version_id!r} not found")
        shutil.copyfile(d / f"{version_id}.py", d / "active.py")
        # bump mtime explicitly: copyfile + coarse filesystem timestamps
        # could otherwise leave the ScriptManager's (path, mtime) cache
        # thinking nothing changed
        import os as _os

        _os.utime(d / "active.py")
        meta["activeVersion"] = version_id
        self._write_meta(d, meta)
        return meta

    def activate(self, identifier: str, tenant: str, script_id: str,
                 version_id: str) -> dict:
        d, meta = self._read_meta(identifier, tenant, script_id)
        return self._activate(d, meta, version_id)

    def delete_script(self, identifier: str, tenant: str,
                      script_id: str) -> bool:
        d = self._script_dir(identifier, tenant, script_id)
        if not self._meta_path(d).exists():
            return False
        shutil.rmtree(d)
        return True
