"""Overload discipline: per-tenant admission control + weighted-fair
scheduling (ISSUE 9).

The reference platform isolates tenants structurally — every tenant gets
its own engine and database, so one tenant's flood can only sink its own
pipeline. The TPU-resident engine deliberately shares everything (one
arena pool, one WAL, one device step, one query batcher) for throughput,
which re-creates the classic shared-resource tail problem (Dean &
Barroso, "The Tail at Scale"): nothing stops an abusive tenant from
inflating every other tenant's p99. This module is the enforcement
plane:

  * :class:`TokenBucket` / :class:`AdmissionController` — seeded,
    deterministic per-tenant token-bucket admission, applied at the
    ingest EDGES (REST, RPC, cluster forward handlers, loadgen) and
    NEVER inside the engine's own ingest methods: WAL replay and the
    replication applier must be able to re-apply durable events
    unconditionally, or recovery/standby byte-parity would break.
    Shedding is explicit — HTTP ``429`` + ``Retry-After`` at the REST
    edge, a typed ``RpcError(code=429)`` app-reject at the RPC edge (so
    ``ForwardQueue.retry_once`` classifies it as an application reject
    and never head-of-line-stalls behind it), and a typed
    :class:`ShedError` everywhere in between.
  * :class:`WeightedFairGate` — weighted-fair queuing of the ingest
    critical section (the contended resource behind ``ArenaPool``
    slots): per-tenant virtual-time deficit counters order which
    tenant's batch gets the next turn, so a flood of one tenant's
    batches can no longer starve everyone parked behind it in lock
    order. Uncontended turns are a couple of dict ops.
  * :class:`WFQPicker` — the same virtual-time rule applied to
    ``QueryBatcher`` round membership (today first-come): under read
    contention a tenant's share of fused-program slots follows its
    weight, not its arrival burstiness.

Determinism: every admission decision is a pure function of (config,
clock readings, call sequence). The controller takes an injectable
``clock`` callable; :class:`ManualClock` lets tests and chaos harnesses
replay an admission trace exactly.

All QoS telemetry lives in the Prometheus REGISTRY
(``swtpu_qos_*``, utils/metrics.qos_metrics) and is kept OUT of
``engine.metrics()`` — the full-metrics-dict equality across dispatch
shapes is a tested parity property.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import threading
import time

# one label per controller (same scheme as the autotuner's gauges): the
# metrics REGISTRY is process-global, so without an engine label two
# QoS-enabled engines in one process (in-process cluster ranks, tests)
# would merge counters and last-writer-win each other's gauges
_QOS_IDS = itertools.count()


class ShedError(RuntimeError):
    """A load-shed refusal (typed, carries the retry hint). Raised at
    admission edges and by the arena-stall translation; the REST layer
    maps it to ``429`` + ``Retry-After``, the RPC server to a
    ``code=429`` error frame."""

    def __init__(self, message: str, tenant: str | None = None,
                 retry_after_s: float = 0.05, reason: str = "shed"):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)
        self.reason = reason


class ManualClock:
    """Deterministic clock for admission tests/chaos replay: time moves
    only when the harness says so."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def advance(self, dt: float) -> None:
        self.t += float(dt)

    def __call__(self) -> float:
        return self.t


class TokenBucket:
    """One tenant's admission budget: ``rate`` tokens/s refill up to
    ``capacity``. Pure arithmetic over clock readings — no wall-clock
    reads of its own, so a replayed clock replays the decisions."""

    __slots__ = ("rate", "capacity", "tokens", "t_last")

    def __init__(self, rate_eps: float, burst_s: float, now: float):
        self.rate = float(rate_eps)
        self.capacity = max(1.0, self.rate * float(burst_s))
        self.tokens = self.capacity
        self.t_last = float(now)

    def take(self, n: int, now: float) -> tuple[bool, float]:
        """Try to take ``n`` tokens at clock reading ``now``; returns
        (admitted, seconds_until_enough_tokens). A request larger than
        ``capacity`` can never accumulate ``n`` tokens, so it admits
        against a FULL bucket and drives the balance negative — the debt
        throttles what follows, preserving the long-run rate. Refusing
        it outright would hand the caller a retry hint that waiting can
        never satisfy (a 429 loop at the REST edge, a forward spill that
        redelivers forever)."""
        if now > self.t_last:
            self.tokens = min(self.capacity,
                              self.tokens + (now - self.t_last) * self.rate)
        self.t_last = max(self.t_last, now)
        need = min(float(n), self.capacity)
        if self.tokens >= need:
            self.tokens -= n
            return True, 0.0
        return False, (need - self.tokens) / self.rate


@dataclasses.dataclass
class Admission:
    """One admission decision. ``reason`` on a shed: "rate" (tenant over
    its token bucket) or "saturated" (engine backlog over the shed
    threshold)."""

    admitted: bool
    retry_after_s: float = 0.0
    reason: str | None = None


class AdmissionController:
    """Per-tenant token-bucket admission + engine-saturation shedding.

    ``tenant_rates`` maps tenant -> admitted events/s (a tenant absent
    from the map gets ``default_rate_eps``; 0 = no per-tenant cap).
    ``shed_threshold`` is a staged-row backlog bound: while
    ``backlog_fn()`` is at or above it, EVERY tenant sheds with reason
    "saturated" — the global overload valve the SLO autotuner steers.
    Decisions are counted live into ``swtpu_qos_admitted_total`` /
    ``swtpu_qos_shed_total{reason}`` so shed visibility never depends on
    a scrape ordering."""

    def __init__(self, *, tenant_rates: dict | None = None,
                 default_rate_eps: float = 0.0, burst_s: float = 2.0,
                 shed_threshold: int = 0, backlog_fn=None,
                 clock=time.monotonic, min_retry_after_s: float = 0.05,
                 label: str | None = None):
        from sitewhere_tpu.utils.metrics import qos_metrics

        self.label = label or f"e{next(_QOS_IDS)}"
        self.tenant_rates = dict(tenant_rates or {})
        self.default_rate_eps = float(default_rate_eps)
        self.burst_s = float(burst_s)
        self.shed_threshold = int(shed_threshold)
        self._backlog_fn = backlog_fn
        self._clock = clock
        self.min_retry_after_s = float(min_retry_after_s)
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}
        # conservation accounting (ISSUE 14): ``offered_events`` counts
        # at admit() ENTRY, independently of the verdict, so the edge
        # equation offered == admitted + edge-sheds is falsifiable —
        # never derived from its own right-hand side. ``shed_noted``
        # counts sheds recorded via note_shed (e.g. an arena stall AFTER
        # admission): those events were already offered-and-admitted, so
        # the checker subtracts them from the edge shed total.
        self.offered_events = 0
        self.admitted_events = 0
        self.shed_events = 0
        self.shed_noted = 0
        self.shed_by_tenant: dict[str, int] = {}
        self._metrics = qos_metrics()

    def _rate_for(self, tenant: str) -> float:
        if tenant in self.tenant_rates:
            return float(self.tenant_rates[tenant])
        return self.default_rate_eps

    def _count_shed(self, tenant: str, n: int, reason: str) -> None:
        self.shed_events += n
        self.shed_by_tenant[tenant] = self.shed_by_tenant.get(tenant, 0) + n
        self._metrics["shed"].inc(n, tenant=tenant, reason=reason,
                                  engine=self.label)

    def admit(self, tenant: str, n: int = 1) -> Admission:
        """Decide on ``n`` events for ``tenant``. Saturation is checked
        first (it protects every tenant's tail), then the tenant's own
        bucket; a shed never consumes tokens."""
        tenant = tenant or "default"
        n = max(1, int(n))
        with self._lock:
            self.offered_events += n
            now = self._clock()
            if self.shed_threshold and self._backlog_fn is not None:
                saturated = self._backlog_fn() >= self.shed_threshold
                self._metrics["saturated"].set(1.0 if saturated else 0.0,
                                               engine=self.label)
                if saturated:
                    self._count_shed(tenant, n, "saturated")
                    return Admission(False, self.min_retry_after_s,
                                     "saturated")
            rate = self._rate_for(tenant)
            if rate > 0:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = TokenBucket(
                        rate, self.burst_s, now)
                ok, wait = bucket.take(n, now)
                if not ok:
                    self._count_shed(tenant, n, "rate")
                    return Admission(
                        False, max(self.min_retry_after_s, wait), "rate")
            self.admitted_events += n
            self._metrics["admitted"].inc(n, tenant=tenant,
                                          engine=self.label)
            return Admission(True)

    def note_shed(self, tenant: str, n: int, reason: str) -> None:
        """Count a shed decided elsewhere (e.g. an arena stall translated
        by the engine) so the ``swtpu_qos_shed_total`` ledger stays the
        one place sheds are visible."""
        with self._lock:
            self.shed_noted += max(1, int(n))
            self._count_shed(tenant or "default", max(1, int(n)), reason)

    def bucket_fill(self) -> dict[str, float]:
        """Current token balance per tenant (refreshed to the current
        clock reading) — the scrape-time gauge source."""
        with self._lock:
            now = self._clock()
            out = {}
            for tenant, b in self._buckets.items():
                if now > b.t_last:
                    b.tokens = min(b.capacity,
                                   b.tokens + (now - b.t_last) * b.rate)
                    b.t_last = now
                out[tenant] = b.tokens
            return out


def admit_or_raise(engine, tenant: str, n: int = 1) -> None:
    """Edge helper: consult ``engine.qos`` (None = QoS off) and raise a
    typed :class:`ShedError` on refusal. The REST/RPC layers translate
    the error to their wire form (429 + Retry-After)."""
    qos = getattr(engine, "qos", None)
    if qos is None:
        return
    d = qos.admit(tenant or "default", n)
    if not d.admitted:
        raise ShedError(
            f"tenant {tenant!r} shed ({d.reason}): retry after "
            f"{d.retry_after_s:.3f}s", tenant=tenant,
            retry_after_s=d.retry_after_s, reason=d.reason or "shed")


class WeightedFairGate:
    """Weighted-fair turn-taking over one exclusive resource (the
    engine's ingest critical section — the path that acquires
    ``ArenaPool`` slots and staging-buffer room).

    Virtual-time rule: each granted turn charges its tenant
    ``cost / weight`` virtual seconds; a waiter proceeds only when no
    OTHER tenant is waiting with a smaller virtual time. A tenant
    arriving after idling is clamped to the gate's current virtual
    clock, so silence never banks priority. Under saturation (every
    tenant always has a waiter) grant throughput converges to the
    weight ratio — 2:1 weights serve ~2:1 events — while an uncontended
    turn is granted immediately."""

    def __init__(self, weights: dict | None = None,
                 default_weight: float = 1.0):
        self.weights = dict(weights or {})
        self.default_weight = float(default_weight)
        self._cv = threading.Condition()
        self._vtime: dict[str, float] = {}
        self._vnow = 0.0
        self._waiting: dict[str, int] = {}
        self._busy = False
        self.grants: dict[str, int] = {}   # tenant -> granted cost units

    def weight(self, tenant: str) -> float:
        return max(1e-9, float(self.weights.get(tenant,
                                                self.default_weight)))

    def _prior_waiter(self, tenant: str) -> bool:
        mine = self._vtime[tenant]
        for t, n in self._waiting.items():
            if t != tenant and n > 0 and self._vtime[t] < mine:
                return True
        return False

    @contextlib.contextmanager
    def turn(self, tenant: str, cost: float = 1.0):
        tenant = tenant or "default"
        cost = max(1.0, float(cost))
        with self._cv:
            # late arrival after idling starts at the current virtual
            # clock — it may not cash in its silence as priority
            self._vtime[tenant] = max(self._vtime.get(tenant, 0.0),
                                      self._vnow)
            self._waiting[tenant] = self._waiting.get(tenant, 0) + 1
            while self._busy or self._prior_waiter(tenant):
                self._cv.wait()
            self._waiting[tenant] -= 1
            if not self._waiting[tenant]:
                del self._waiting[tenant]
            self._busy = True
            self._vnow = self._vtime[tenant]
            self._vtime[tenant] += cost / self.weight(tenant)
            self.grants[tenant] = self.grants.get(tenant, 0) + int(cost)
        try:
            yield
        finally:
            with self._cv:
                self._busy = False
                self._cv.notify_all()

    def vtimes(self) -> dict[str, float]:
        with self._cv:
            return dict(self._vtime)


class WFQPicker:
    """Weighted-fair round membership for the query batcher: given the
    queued entries (each a dict carrying ``"tenant"``), select up to
    ``k`` in virtual-time order, FIFO within a tenant. Single-threaded
    (the batcher calls it under its own mutex); virtual time persists
    across rounds so a backlogged tenant's share follows its weight over
    time, not per round."""

    def __init__(self, weights: dict | None = None,
                 default_weight: float = 1.0):
        self.weights = dict(weights or {})
        self.default_weight = float(default_weight)
        self._vtime: dict[str, float] = {}
        self._vnow = 0.0

    def weight(self, tenant: str) -> float:
        return max(1e-9, float(self.weights.get(tenant,
                                                self.default_weight)))

    def pick(self, entries: list, k: int) -> tuple[list, list]:
        """(selected, rest) — ``rest`` keeps arrival order."""
        queues: dict[str, list] = {}
        for e in entries:
            queues.setdefault(e.get("tenant") or "default", []).append(e)
        for t in queues:
            self._vtime[t] = max(self._vtime.get(t, 0.0), self._vnow)
        selected: list = []
        chosen: set[int] = set()
        while len(selected) < k and queues:
            t = min(queues, key=lambda q: (self._vtime[q], q))
            e = queues[t].pop(0)
            selected.append(e)
            chosen.add(id(e))
            self._vnow = self._vtime[t]
            self._vtime[t] += 1.0 / self.weight(t)
            if not queues[t]:
                del queues[t]
        rest = [e for e in entries if id(e) not in chosen]
        return selected, rest

    def vtimes(self) -> dict[str, float]:
        return dict(self._vtime)
