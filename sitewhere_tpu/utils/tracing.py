"""Tracing / profiling hooks.

The reference defers tracing to the Istio mesh and measures stages with
Prometheus histograms (SURVEY.md §5.1). Here: lightweight host-side stage
spans feeding the metrics histograms, a wrapper around the JAX profiler
for device traces (viewable in TensorBoard/Perfetto), and the
``traceparent`` context that the flight recorder (utils/flight.py) and
the cluster RPC use to follow one batch across ranks (the Dapper-style
trace-context propagation the reference gets from Istio headers).

Trace ids are W3C-traceparent shaped (``00-<32 hex>-<16 hex>-01``) so a
future OTLP exporter can forward them unchanged. The CURRENT traceparent
lives in a :mod:`contextvars` variable — per-thread AND per-asyncio-task,
so the RPC server can bind it around a handler without cross-talk between
multiplexed calls.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time

from sitewhere_tpu.utils.metrics import REGISTRY

_STAGE_HIST = REGISTRY.histogram(
    "swtpu_stage_seconds", "host pipeline stage latency"
)

_local = threading.local()

# ------------------------------------------------------------ traceparent
_TRACEPARENT: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "swtpu_traceparent", default=None)
_SPAN_SEQ = itertools.count(1)


def new_trace_id(rank: int = 0) -> str:
    """A 32-hex trace id: rank + wall-clock ns + in-process sequence —
    unique across ranks and restarts without coordination (the forward-id
    recipe of parallel/cluster._next_fid, in W3C shape)."""
    return (f"{rank & 0xFFFF:04x}"
            f"{time.time_ns() & 0xFFFFFFFFFFFFFFFF:016x}"
            f"{next(_SPAN_SEQ) & 0xFFFFFFFFFFFF:012x}")


def new_traceparent(rank: int = 0, trace_id: str | None = None) -> str:
    """A W3C-style traceparent header value for a (possibly new) trace."""
    tid = trace_id or new_trace_id(rank)
    span = f"{(next(_SPAN_SEQ) ^ (rank << 48)) & 0xFFFFFFFFFFFFFFFF:016x}"
    return f"00-{tid}-{span}-01"


def trace_id_of(traceparent: str | None) -> str | None:
    """The 32-hex trace id inside a traceparent; None on malformed input
    (a peer shipping garbage must not poison the recorder index)."""
    if not traceparent:
        return None
    parts = traceparent.split("-")
    if len(parts) >= 2 and len(parts[1]) == 32:
        return parts[1]
    return None


def current_traceparent() -> str | None:
    """The traceparent bound to this thread/task, or None."""
    return _TRACEPARENT.get()


@contextlib.contextmanager
def bind_traceparent(traceparent: str | None):
    """Bind ``traceparent`` for the enclosed block (no-op on None, so an
    unpropagated call keeps whatever context it inherited)."""
    if traceparent is None:
        yield
        return
    token = _TRACEPARENT.set(traceparent)
    try:
        yield
    finally:
        _TRACEPARENT.reset(token)


@contextlib.contextmanager
def stage(name: str, **labels):
    """Span for one pipeline stage; nests (child spans record their own
    stage label), observations land in the shared histogram."""
    t0 = time.perf_counter()
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()
        _STAGE_HIST.observe(time.perf_counter() - t0, stage=name, **labels)


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture a JAX device profile (xplane) for the enclosed block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Decorator: trace a function as a stage span + XLA annotation."""
    import functools

    import jax

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with stage(name), jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)

        return inner

    return wrap
