"""Tracing / profiling hooks.

The reference defers tracing to the Istio mesh and measures stages with
Prometheus histograms (SURVEY.md §5.1). Here: lightweight host-side stage
spans feeding the metrics histograms, plus a wrapper around the JAX
profiler for device traces (viewable in TensorBoard/Perfetto).
"""

from __future__ import annotations

import contextlib
import threading
import time

from sitewhere_tpu.utils.metrics import REGISTRY

_STAGE_HIST = REGISTRY.histogram(
    "swtpu_stage_seconds", "host pipeline stage latency"
)

_local = threading.local()


@contextlib.contextmanager
def stage(name: str, **labels):
    """Span for one pipeline stage; nests (child spans record their own
    stage label), observations land in the shared histogram."""
    t0 = time.perf_counter()
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()
        _STAGE_HIST.observe(time.perf_counter() - t0, stage=name, **labels)


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture a JAX device profile (xplane) for the enclosed block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Decorator: trace a function as a stage span + XLA annotation."""
    import functools

    import jax

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with stage(name), jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)

        return inner

    return wrap
